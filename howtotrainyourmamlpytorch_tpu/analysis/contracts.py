"""Program-contract vocabulary + HLO census helpers + the pinned baseline.

This module is deliberately stdlib-only (``re``/``json``/``dataclasses``):
``bench.py`` imports the census helpers for its ``hlo_cost`` / ``donation``
fields, and the contracts/baseline plumbing must stay importable before any
backend is settled. Everything that needs jax (tracing, compiling, walking
jaxprs) lives in :mod:`analysis.auditor`.

The contracts the auditor enforces (one name each, used in violations,
reports and tests):

* ``donation``     — the executable aliases at least the donated state's
  bytes in place (``memory_analysis``), and jax emitted no "donated
  buffers were not usable" diagnostic: params + LSLR + BN + Adam moments
  stay single-buffered in HBM across dispatches (PR 4's ``TRAIN_DONATE``);
* ``no_transfer``  — no host<->device traffic inside the step: no
  ``device_put`` / host-callback primitives in the jaxpr, no
  infeed/outfeed/send/recv in the optimized HLO (the index-only <1KB/step
  H2D contract of PR 2 — all transfers happen at the dispatch boundary,
  never mid-program);
* ``dtype_policy`` — no f64 anywhere (x64 creep), and under
  ``compute_dtype='bfloat16'`` no matmul-class op (dot/conv) runs with
  f32 operands beyond scalar-loss size — an accidental upcast would
  silently halve MXU throughput;
* ``op_census``    — the optimized-HLO opcode census must not regress
  against the pinned ``CONTRACTS.json`` baseline, and a config that
  resolves to the GEMM conv path must compile with zero grouped
  (``feature_group_count>1``) convolutions (the exact lowering regression
  PR 4's throughput depends on).
"""

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: the contract names, in reporting order
CONTRACT_NAMES = ("donation", "no_transfer", "dtype_policy", "op_census")

#: op classes that distinguish a healthy lowering from a regressed one —
#: the census the baseline pins and the regression check compares (the full
#: census would drown the signal in elementwise noise). Shared with
#: bench.py's ``hlo_cost`` field.
INTERESTING_OPS = (
    "dot", "convolution", "fusion", "custom-call", "all-reduce",
    "all-gather", "reduce-scatter", "copy", "transpose", "pad",
    "gather", "scatter", "while",
)

#: scalar cost_analysis keys surfaced whole by ``hlo_cost_breakdown``
HLO_SCALAR_KEYS = ("flops", "transcendentals", "bytes accessed",
                   "optimal_seconds")

#: HLO opcodes that ARE host<->device traffic (send/recv also cover the
#: host-transfer forms; within-device collectives are not in this list)
HOST_TRANSFER_HLO_OPS = ("infeed", "outfeed", "send", "recv",
                         "send-done", "recv-done")


@dataclass(frozen=True)
class ContractViolation:
    """One broken contract on one program."""

    contract: str  # one of CONTRACT_NAMES
    program: str   # e.g. "train_step[so=1]"
    detail: str

    def __str__(self) -> str:
        return f"[{self.contract}] {self.program}: {self.detail}"


class AuditError(RuntimeError):
    """Raised under ``analysis_level='strict'`` when contracts are broken."""

    def __init__(self, violations: List[ContractViolation]):
        self.violations = list(violations)
        lines = "\n  ".join(str(v) for v in self.violations)
        super().__init__(
            f"{len(self.violations)} program-contract violation(s):\n  {lines}"
        )


@dataclass
class AuditReport:
    """What one program's audit found (violations may be empty)."""

    program: str
    backend: str
    contracts_checked: Tuple[str, ...]
    violations: List[ContractViolation] = field(default_factory=list)
    census: Dict[str, int] = field(default_factory=dict)
    donation: Optional[Dict[str, int]] = None

    @property
    def ok(self) -> bool:
        return not self.violations


# -- optimized-HLO text analysis ---------------------------------------------


def hlo_op_census(hlo_text: str) -> Dict[str, int]:
    """Instruction counts per opcode over an optimized-HLO dump.

    Counts every ``= <shape> <opcode>(`` instruction; callers usually
    filter to ``INTERESTING_OPS``. This is the census bench.py's
    ``hlo_cost`` field records and the ``op_census`` contract pins.
    """
    ops: Dict[str, int] = {}
    for m in re.finditer(r"=\s+\S+\s+([a-z][a-z0-9-]*)\(", hlo_text):
        ops[m.group(1)] = ops.get(m.group(1), 0) + 1
    return ops


def interesting_census(hlo_text: str) -> Dict[str, int]:
    ops = hlo_op_census(hlo_text)
    return {k: ops[k] for k in INTERESTING_OPS if k in ops}


def grouped_conv_count(hlo_text: str) -> int:
    """Number of ``convolution`` instructions with ``feature_group_count>1``
    — the grouped-conv lowering the GEMM path exists to eliminate."""
    return sum(
        1
        for m in re.finditer(r"feature_group_count=(\d+)", hlo_text)
        if int(m.group(1)) > 1
    )


def host_transfer_ops(hlo_text: str) -> Dict[str, int]:
    """Census of host<->device transfer opcodes in an optimized-HLO dump."""
    ops = hlo_op_census(hlo_text)
    return {k: ops[k] for k in HOST_TRANSFER_HLO_OPS if k in ops}


def f64_shape_count(hlo_text: str) -> int:
    """Occurrences of an ``f64[...]`` shape anywhere in the HLO text."""
    return len(re.findall(r"\bf64\[", hlo_text))


# -- compiled-executable helpers (shared with bench.py) ----------------------


def cost_analysis_dict(compiled) -> dict:
    """``compiled.cost_analysis()`` normalized to one dict (older jax
    returns ``[dict]``, newer a plain dict) — the single normalization
    point for bench.py and the auditor."""
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    return ca


def hlo_cost_breakdown(compiled, ca: dict) -> Optional[dict]:
    """Per-category HLO cost summary of a compiled executable.

    Combines XLA's cost analysis ``ca`` (total flops / bytes accessed, plus
    any per-category entries the backend exposes) with the opcode census of
    the optimized HLO, so a lowering regression (e.g. the task-batched GEMM
    conv silently falling back to grouped convolutions) is visible in the
    BENCH_* trajectory without a profiler. Best-effort: returns None when
    the backend exposes neither surface.
    """
    import sys

    out: dict = {}
    try:
        for key in HLO_SCALAR_KEYS:
            if key in ca:
                out[key.replace(" ", "_")] = float(ca[key])
        breakdown = {
            k: float(v)
            for k, v in ca.items()
            if k not in HLO_SCALAR_KEYS
            and not re.fullmatch(r"(bytes accessed|utilization)\w*\{\}", k)
        }
        if breakdown:
            out["cost_breakdown"] = breakdown
    except Exception as e:  # noqa: BLE001 - cost analysis is best-effort
        print(f"analysis: cost_analysis breakdown unavailable ({e!r})",
              file=sys.stderr)
    try:
        census = interesting_census(compiled.as_text())
        if census:
            out["hlo_op_counts"] = census
    except Exception as e:  # noqa: BLE001
        print(f"analysis: HLO op census unavailable ({e!r})", file=sys.stderr)
    return out or None


def donation_stats(compiled, donate_argnums) -> Optional[dict]:
    """Aliasing/donation figures of a compiled step: a donation regression
    (state no longer aliased in place -> double-buffered params+Adam in HBM)
    shows up as alias_size_bytes collapsing toward zero."""
    import sys

    try:
        ma = compiled.memory_analysis()
        return {
            "donate_argnums": list(donate_argnums),
            "alias_size_bytes": int(ma.alias_size_in_bytes),
            "argument_size_bytes": int(ma.argument_size_in_bytes),
            "output_size_bytes": int(ma.output_size_in_bytes),
            "temp_size_bytes": int(ma.temp_size_in_bytes),
        }
    except Exception as e:  # noqa: BLE001 - memory analysis is best-effort
        print(f"analysis: memory_analysis unavailable ({e!r})",
              file=sys.stderr)
        return {"donate_argnums": list(donate_argnums)}


# -- the pinned baseline (CONTRACTS.json) ------------------------------------

BASELINE_VERSION = 1
BASELINE_FILENAME = "CONTRACTS.json"


def default_baseline_path() -> str:
    """``CONTRACTS.json`` at the repository root (two levels above this
    package). May not exist — e.g. for an installed wheel — in which case
    the census-regression check is simply skipped."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        BASELINE_FILENAME,
    )


def census_key(program: str, backend: str) -> str:
    return f"{program}@{backend}"


def load_baseline(path: Optional[str] = None) -> Optional[dict]:
    """Parse a pinned baseline, or None when absent/unreadable (the
    regression check degrades to the invariant constraints only)."""
    path = path or default_baseline_path()
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or "programs" not in data:
        return None
    return data


def save_baseline(path: str, *, jax_version: str, backend: str,
                  config_fingerprint: str,
                  reports: List[AuditReport]) -> dict:
    """Re-pin the baseline from a set of audit reports (``cli audit
    --pin``). The jax version and config fingerprint are recorded so a
    later compare against a different toolchain or audit config skips
    with a note instead of producing phantom regressions."""
    data = {
        "version": BASELINE_VERSION,
        "jax": jax_version,
        "backend": backend,
        "config_fingerprint": config_fingerprint,
        "programs": {
            census_key(r.program, r.backend): {
                "census": dict(r.census),
                "alias_size_bytes": (
                    (r.donation or {}).get("alias_size_bytes")
                ),
            }
            for r in reports
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


def baseline_comparable(baseline: Optional[dict], *, jax_version: str,
                        config_fingerprint: str) -> bool:
    """A baseline only yields regression signals when it was pinned with
    the same jax (XLA rewrites change op counts release to release) and
    the same audit config (shapes change the census legitimately)."""
    return bool(
        baseline
        and baseline.get("jax") == jax_version
        and baseline.get("config_fingerprint") == config_fingerprint
    )


def compare_census(current: Dict[str, int], pinned: Dict[str, int],
                   ) -> List[str]:
    """Regressions of ``current`` vs the pinned census: any interesting op
    class that grew, or appeared where the baseline had none. Shrinkage is
    an improvement, reported by ``cli audit`` as a re-pin suggestion, never
    a violation."""
    regressions = []
    for op in INTERESTING_OPS:
        now = int(current.get(op, 0))
        then = int(pinned.get(op, 0))
        if now > then:
            regressions.append(f"{op}: {then} -> {now}")
    return regressions


def config_fingerprint(cfg_dict: dict) -> str:
    """Stable fingerprint of the audit config (shape-relevant keys only
    would invite drift bugs; hash the whole dict, sorted)."""
    import hashlib

    blob = json.dumps(cfg_dict, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]
