"""Program-contract vocabulary + HLO census helpers + the pinned baseline.

This module is deliberately stdlib-only (``re``/``json``/``dataclasses``):
``bench.py`` imports the census helpers for its ``hlo_cost`` / ``donation``
fields, and the contracts/baseline plumbing must stay importable before any
backend is settled. Everything that needs jax (tracing, compiling, walking
jaxprs) lives in :mod:`analysis.auditor`.

The contracts the auditor enforces (one name each, used in violations,
reports and tests):

* ``donation``     — the executable aliases at least the donated state's
  bytes in place (``memory_analysis``), and jax emitted no "donated
  buffers were not usable" diagnostic: params + LSLR + BN + Adam moments
  stay single-buffered in HBM across dispatches (PR 4's ``TRAIN_DONATE``);
* ``no_transfer``  — no host<->device traffic inside the step: no
  ``device_put`` / host-callback primitives in the jaxpr, no
  infeed/outfeed/send/recv in the optimized HLO (the index-only <1KB/step
  H2D contract of PR 2 — all transfers happen at the dispatch boundary,
  never mid-program);
* ``dtype_policy`` — no f64 anywhere (x64 creep), and under
  ``compute_dtype='bfloat16'`` no matmul-class op (dot/conv) runs with
  f32 operands beyond scalar-loss size — an accidental upcast would
  silently halve MXU throughput;
* ``op_census``    — the optimized-HLO opcode census must not regress
  against the pinned ``CONTRACTS.json`` baseline, and a config that
  resolves to the GEMM conv path must compile with zero grouped
  (``feature_group_count>1``) convolutions (the exact lowering regression
  PR 4's throughput depends on).
"""

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: the contract names, in reporting order
CONTRACT_NAMES = ("donation", "no_transfer", "dtype_policy", "op_census")

#: the SPMD performance contracts (analysis/spmd.py), in reporting order:
#: ``sharding`` — batch args sharded over (data, task), state/stores
#: replicated in AND out; ``collective_census`` — the per-axis collective
#: op/byte census must not regress vs the mesh-keyed baseline, and no
#: collective may carry uint8 (pixel-store) data or store-sized volumes;
#: ``hbm_budget`` — the static per-device memory_analysis peak plus the
#: resident-store expectation must fit ``hbm_budget_gb``; ``roofline`` —
#: the static roofline/MFU model's device-peak entry and flops cross-check
#: (analysis/roofline.py) must hold.
SPMD_CONTRACT_NAMES = ("sharding", "collective_census", "hbm_budget",
                       "roofline")

#: op classes that distinguish a healthy lowering from a regressed one —
#: the census the baseline pins and the regression check compares (the full
#: census would drown the signal in elementwise noise). Shared with
#: bench.py's ``hlo_cost`` field. ``reduce``/``reduce-window`` joined in
#: PR 16: the inner-loop compute diet (fused BN statistics, reshape pool,
#: invariant im2col hoisting) exists to SHRINK them, so the baseline pins
#: the reduction and a lever regression (an extra statistics pass per BN,
#: the pool falling back to select-and-scatter) shows up as census growth.
INTERESTING_OPS = (
    "dot", "convolution", "fusion", "custom-call", "all-reduce",
    "all-gather", "reduce-scatter", "copy", "transpose", "pad",
    "gather", "scatter", "while", "reduce", "reduce-window",
)

#: scalar cost_analysis keys surfaced whole by ``hlo_cost_breakdown``
HLO_SCALAR_KEYS = ("flops", "transcendentals", "bytes accessed",
                   "optimal_seconds")

#: HLO opcodes that ARE host<->device traffic (send/recv also cover the
#: host-transfer forms; within-device collectives are not in this list)
HOST_TRANSFER_HLO_OPS = ("infeed", "outfeed", "send", "recv",
                         "send-done", "recv-done")

#: HLO opcodes that are cross-device collectives — the ops the SPMD
#: collective census counts and the mesh-keyed baseline pins (the ``-start``
#: async forms are folded into their base opcode by the census)
HLO_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                      "collective-permute", "all-to-all")

#: HLO element-type prefix -> bytes per element (the types this codebase
#: can emit; unknown prefixes are counted as 4 bytes with no error — the
#: census must never crash on exotic HLO)
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


@dataclass(frozen=True)
class ContractViolation:
    """One broken contract on one program."""

    contract: str  # one of CONTRACT_NAMES
    program: str   # e.g. "train_step[so=1]"
    detail: str

    def __str__(self) -> str:
        return f"[{self.contract}] {self.program}: {self.detail}"


class AuditError(RuntimeError):
    """Raised under ``analysis_level='strict'`` when contracts are broken."""

    def __init__(self, violations: List[ContractViolation]):
        self.violations = list(violations)
        lines = "\n  ".join(str(v) for v in self.violations)
        super().__init__(
            f"{len(self.violations)} program-contract violation(s):\n  {lines}"
        )


@dataclass
class AuditReport:
    """What one program's audit found (violations may be empty)."""

    program: str
    backend: str
    contracts_checked: Tuple[str, ...]
    violations: List[ContractViolation] = field(default_factory=list)
    census: Dict[str, int] = field(default_factory=dict)
    donation: Optional[Dict[str, int]] = None

    @property
    def ok(self) -> bool:
        return not self.violations


@dataclass
class SpmdAuditReport(AuditReport):
    """An SPMD audit's findings: the base report plus the per-axis
    collective census, the static per-device HBM figures and the roofline
    model (analysis/roofline.py) of the compiled sharded program."""

    mesh_spec: str = ""
    collectives: Dict[str, Dict[str, Dict[str, int]]] = field(
        default_factory=dict
    )
    hbm: Optional[Dict[str, float]] = None
    roofline: Optional[dict] = None


# -- optimized-HLO text analysis ---------------------------------------------


def hlo_op_census(hlo_text: str) -> Dict[str, int]:
    """Instruction counts per opcode over an optimized-HLO dump.

    Counts every ``= <shape> <opcode>(`` instruction; callers usually
    filter to ``INTERESTING_OPS``. This is the census bench.py's
    ``hlo_cost`` field records and the ``op_census`` contract pins.
    """
    ops: Dict[str, int] = {}
    for m in re.finditer(r"=\s+\S+\s+([a-z][a-z0-9-]*)\(", hlo_text):
        ops[m.group(1)] = ops.get(m.group(1), 0) + 1
    return ops


def interesting_census(hlo_text: str) -> Dict[str, int]:
    ops = hlo_op_census(hlo_text)
    return {k: ops[k] for k in INTERESTING_OPS if k in ops}


def grouped_conv_count(hlo_text: str) -> int:
    """Number of ``convolution`` instructions with ``feature_group_count>1``
    — the grouped-conv lowering the GEMM path exists to eliminate."""
    return sum(
        1
        for m in re.finditer(r"feature_group_count=(\d+)", hlo_text)
        if int(m.group(1)) > 1
    )


def host_transfer_ops(hlo_text: str) -> Dict[str, int]:
    """Census of host<->device transfer opcodes in an optimized-HLO dump."""
    ops = hlo_op_census(hlo_text)
    return {k: ops[k] for k in HOST_TRANSFER_HLO_OPS if k in ops}


def f64_shape_count(hlo_text: str) -> int:
    """Occurrences of an ``f64[...]`` shape anywhere in the HLO text."""
    return len(re.findall(r"\bf64\[", hlo_text))


# -- SPMD collective census (analysis/spmd.py drives this) --------------------

_SHAPE_RE = re.compile(r"(pred|[a-z]+\d+)\[([0-9,]*)\]")

#: one HLO instruction: `%name = <shape-or-tuple> <opcode>(...)`
_COLLECTIVE_INSN_RE = re.compile(
    r"=\s+(\([^)]*\)|\S+)\s+("
    + "|".join(HLO_COLLECTIVE_OPS)
    + r")(-start)?\(([^)]*)\)([^\n]*)"
)

#: iota replica groups: `[2,4]<=[8]` or `[4,2]<=[2,4]T(1,0)`
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)
#: explicit replica groups: `replica_groups={{0,1},{2,3}}`
_EXPLICIT_GROUPS_RE = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})\}")


def hlo_shape_bytes(shape_str: str) -> int:
    """Bytes of an HLO shape string — `f32[8,4]`, a tuple `(f32[2], u8[4])`,
    or anything containing such shapes (layout suffixes ignored). Scalar
    shapes (`f32[]`) count one element."""
    total = 0
    for m in _SHAPE_RE.finditer(shape_str):
        dtype, dims = m.group(1), m.group(2)
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        total += n * _HLO_DTYPE_BYTES.get(dtype, 4)
    return total


def _parse_iota_groups(group_dims, iota_dims, perm) -> List[List[int]]:
    """Expand the iota replica-group form into explicit id lists: ids
    0..prod(iota_dims)-1 reshaped to ``iota_dims``, transposed by ``perm``,
    flattened, then split into ``group_dims[0]`` groups of
    prod(group_dims[1:]) members (V2 iota tile assignment semantics)."""
    n = 1
    for d in iota_dims:
        n *= d
    ids = list(range(n))
    if perm and perm != list(range(len(iota_dims))):
        # transpose: position in the permuted array -> original id
        strides = [0] * len(iota_dims)
        acc = 1
        for i in range(len(iota_dims) - 1, -1, -1):
            strides[i] = acc
            acc *= iota_dims[i]
        new_dims = [iota_dims[p] for p in perm]
        new_strides = [strides[p] for p in perm]
        out = []
        idx = [0] * len(new_dims)
        for _ in range(n):
            out.append(sum(i * s for i, s in zip(idx, new_strides)))
            for axis in range(len(new_dims) - 1, -1, -1):
                idx[axis] += 1
                if idx[axis] < new_dims[axis]:
                    break
                idx[axis] = 0
        ids = out
    group_size = 1
    for d in group_dims[1:]:
        group_size *= d
    group_size = max(1, group_size)
    return [ids[i:i + group_size] for i in range(0, len(ids), group_size)]


def parse_replica_groups(insn_tail: str) -> Optional[List[List[int]]]:
    """Replica groups of one collective instruction's trailing attributes,
    as explicit device-id lists; None when absent/unparseable (the census
    then classifies the collective as 'unknown' instead of guessing)."""
    m = _IOTA_GROUPS_RE.search(insn_tail)
    if m:
        group_dims = [int(d) for d in m.group(1).split(",")]
        iota_dims = [int(d) for d in m.group(2).split(",")]
        perm = [int(d) for d in m.group(3).split(",")] if m.group(3) else None
        return _parse_iota_groups(group_dims, iota_dims, perm)
    m = _EXPLICIT_GROUPS_RE.search(insn_tail)
    if m:
        groups = []
        for grp in re.findall(r"\{([0-9, ]*)\}", m.group(1)):
            ids = [int(x) for x in grp.replace(" ", "").split(",") if x]
            if ids:
                groups.append(ids)
        return groups or None
    return None


def classify_replica_groups(
    groups: Optional[List[List[int]]], rows: int, cols: int
) -> str:
    """Which mesh axis a collective's replica groups span, for a (rows,
    cols) = (data/DCN, task/ICI) mesh whose devices are laid out row-major
    (device d sits at (d // cols, d % cols) — how ``hybrid_task_mesh``
    builds its grid and how the partitioner numbers them):

    * ``'ici'``  — every group stays within one mesh row (task axis);
    * ``'dcn'``  — every group stays within one mesh column (data axis);
    * ``'both'`` — some group spans rows AND columns (a global reduce);
    * ``'unknown'`` — groups missing/unparseable.

    Degenerate single-row meshes (1xN) classify as 'ici', single-column
    (Nx1) as 'dcn'.
    """
    if not groups:
        return "unknown"
    span_rows = False
    span_cols = False
    for g in groups:
        if len(g) < 2:
            continue
        if len({d // cols for d in g}) > 1:
            span_rows = True
        if len({d % cols for d in g}) > 1:
            span_cols = True
    if span_rows and span_cols:
        return "both"
    if span_rows:
        return "dcn"
    if span_cols:
        return "ici"
    return "ici" if rows == 1 else ("dcn" if cols == 1 else "unknown")


def collective_instructions(hlo_text: str) -> List[dict]:
    """Every collective instruction in an optimized-HLO dump:
    ``{"op", "bytes", "shape", "groups"}`` — bytes is the instruction's
    output volume (what actually crosses the interconnect, up to the
    reduction factor), groups the parsed replica groups (or None).

    Async ``-start`` forms (TPU optimized HLO emits start/done pairs) are
    folded into their base opcode, and their tuple shape — which aliases
    the operand(s) alongside the result(s) — is charged only its result
    half, not double. The ``-done`` op consumes the start's tuple and is
    not matched at all.
    """
    out = []
    for m in _COLLECTIVE_INSN_RE.finditer(hlo_text):
        shape, op, is_start, _operands, tail = m.groups()
        if is_start and shape.startswith("("):
            # (operands..., results...): the second half is what lands
            parts = _SHAPE_RE.findall(shape)
            results = parts[len(parts) // 2:]
            nbytes = sum(
                hlo_shape_bytes(f"{dtype}[{dims}]")
                for dtype, dims in results
            )
        else:
            nbytes = hlo_shape_bytes(shape)
        out.append({
            "op": op,
            "bytes": nbytes,
            "shape": shape if "(" not in shape else shape[:120],
            "groups": parse_replica_groups(tail),
        })
    return out


def collective_census(
    hlo_text: str, rows: int, cols: int
) -> Dict[str, Dict[str, Dict[str, int]]]:
    """The SPMD collective census: per collective opcode, per mesh-axis
    class (``ici`` / ``dcn`` / ``both`` / ``unknown``), instruction count
    and total output bytes — the figure the mesh-keyed baseline pins and
    ``compare_collective_census`` guards."""
    census: Dict[str, Dict[str, Dict[str, int]]] = {}
    for insn in collective_instructions(hlo_text):
        axis = classify_replica_groups(insn["groups"], rows, cols)
        slot = census.setdefault(insn["op"], {}).setdefault(
            axis, {"count": 0, "bytes": 0}
        )
        slot["count"] += 1
        slot["bytes"] += insn["bytes"]
    return census


def compare_collective_census(
    current: Dict[str, Dict[str, Dict[str, int]]],
    pinned: Dict[str, Dict[str, Dict[str, int]]],
) -> List[str]:
    """Regressions of the current collective census vs the pinned one: any
    (op, axis) whose count or byte volume GREW, or that appeared where the
    baseline had none. Shrinkage is an improvement — reported by ``cli
    audit`` as a re-pin suggestion, never a violation (same semantics as
    ``compare_census``)."""
    regressions = []
    for op in sorted(current):
        for axis in sorted(current[op]):
            now = current[op][axis]
            then = (pinned.get(op) or {}).get(axis) or {"count": 0, "bytes": 0}
            for key in ("count", "bytes"):
                if int(now.get(key, 0)) > int(then.get(key, 0)):
                    regressions.append(
                        f"{op}@{axis} {key}: {int(then.get(key, 0))} -> "
                        f"{int(now.get(key, 0))}"
                    )
    return regressions


# -- compiled-executable helpers (shared with bench.py) ----------------------


def cost_analysis_dict(compiled) -> dict:
    """``compiled.cost_analysis()`` normalized to one dict (older jax
    returns ``[dict]``, newer a plain dict) — the single normalization
    point for bench.py and the auditor."""
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    return ca


def hlo_cost_breakdown(compiled, ca: dict) -> Optional[dict]:
    """Per-category HLO cost summary of a compiled executable.

    Combines XLA's cost analysis ``ca`` (total flops / bytes accessed, plus
    any per-category entries the backend exposes) with the opcode census of
    the optimized HLO, so a lowering regression (e.g. the task-batched GEMM
    conv silently falling back to grouped convolutions) is visible in the
    BENCH_* trajectory without a profiler. Best-effort: returns None when
    the backend exposes neither surface.
    """
    import sys

    out: dict = {}
    try:
        for key in HLO_SCALAR_KEYS:
            if key in ca:
                out[key.replace(" ", "_")] = float(ca[key])
        breakdown = {
            k: float(v)
            for k, v in ca.items()
            if k not in HLO_SCALAR_KEYS
            and not re.fullmatch(r"(bytes accessed|utilization)\w*\{\}", k)
        }
        if breakdown:
            out["cost_breakdown"] = breakdown
    except Exception as e:  # noqa: BLE001 - cost analysis is best-effort
        print(f"analysis: cost_analysis breakdown unavailable ({e!r})",
              file=sys.stderr)
    try:
        census = interesting_census(compiled.as_text())
        if census:
            out["hlo_op_counts"] = census
    except Exception as e:  # noqa: BLE001
        print(f"analysis: HLO op census unavailable ({e!r})", file=sys.stderr)
    return out or None


def donation_stats(compiled, donate_argnums) -> Optional[dict]:
    """Aliasing/donation figures of a compiled step: a donation regression
    (state no longer aliased in place -> double-buffered params+Adam in HBM)
    shows up as alias_size_bytes collapsing toward zero."""
    import sys

    try:
        ma = compiled.memory_analysis()
        return {
            "donate_argnums": list(donate_argnums),
            "alias_size_bytes": int(ma.alias_size_in_bytes),
            "argument_size_bytes": int(ma.argument_size_in_bytes),
            "output_size_bytes": int(ma.output_size_in_bytes),
            "temp_size_bytes": int(ma.temp_size_in_bytes),
        }
    except Exception as e:  # noqa: BLE001 - memory analysis is best-effort
        print(f"analysis: memory_analysis unavailable ({e!r})",
              file=sys.stderr)
        return {"donate_argnums": list(donate_argnums)}


# -- the pinned baseline (CONTRACTS.json) ------------------------------------

BASELINE_VERSION = 1
BASELINE_FILENAME = "CONTRACTS.json"


def default_baseline_path() -> str:
    """``CONTRACTS.json`` at the repository root (two levels above this
    package). May not exist — e.g. for an installed wheel — in which case
    the census-regression check is simply skipped."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        BASELINE_FILENAME,
    )


def census_key(program: str, backend: str) -> str:
    return f"{program}@{backend}"


def spmd_census_key(program: str, backend: str, mesh_spec: str) -> str:
    """Mesh-keyed baseline key (``train_step[so=1]@cpu@1x8``): the same
    program compiles to different collectives per mesh shape, so SPMD
    entries pin per ``program@backend@mesh``."""
    return f"{program}@{backend}@{mesh_spec}"


def load_baseline(path: Optional[str] = None) -> Optional[dict]:
    """Parse a pinned baseline, or None when absent/unreadable (the
    regression check degrades to the invariant constraints only)."""
    path = path or default_baseline_path()
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or "programs" not in data:
        return None
    return data


def save_baseline(path: str, *, jax_version: str, backend: str,
                  config_fingerprint: str,
                  reports: List[AuditReport],
                  mesh_spec: Optional[str] = None) -> dict:
    """Re-pin the baseline from a set of audit reports (``cli audit
    --pin``). The jax version and config fingerprint are recorded so a
    later compare against a different toolchain or audit config skips
    with a note instead of producing phantom regressions.

    ``mesh_spec`` keys the entries per mesh (``program@backend@RxC``) and
    records any per-report collective census. When the on-disk baseline
    was pinned under the SAME jax/backend/fingerprint, entries for OTHER
    keys are preserved — so ``cli audit --pin`` and ``cli audit --mesh 1x8
    --pin`` compose instead of clobbering each other's programs; a
    foreign baseline is replaced outright."""
    prior = load_baseline(path)
    programs: Dict[str, dict] = {}
    if prior is not None and baseline_comparable(
        prior, jax_version=jax_version, config_fingerprint=config_fingerprint
    ) and prior.get("backend") == backend:
        programs.update(prior.get("programs", {}))
    for r in reports:
        key = (
            spmd_census_key(r.program, r.backend, mesh_spec)
            if mesh_spec
            else census_key(r.program, r.backend)
        )
        entry: Dict[str, object] = {
            "census": dict(r.census),
            "alias_size_bytes": ((r.donation or {}).get("alias_size_bytes")),
        }
        collectives = getattr(r, "collectives", None)
        if collectives is not None:
            entry["collectives"] = collectives
        programs[key] = entry
    data = {
        "version": BASELINE_VERSION,
        "jax": jax_version,
        "backend": backend,
        "config_fingerprint": config_fingerprint,
        "programs": programs,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


def baseline_comparable(baseline: Optional[dict], *, jax_version: str,
                        config_fingerprint: str) -> bool:
    """A baseline only yields regression signals when it was pinned with
    the same jax (XLA rewrites change op counts release to release) and
    the same audit config (shapes change the census legitimately)."""
    return bool(
        baseline
        and baseline.get("jax") == jax_version
        and baseline.get("config_fingerprint") == config_fingerprint
    )


def compare_census(current: Dict[str, int], pinned: Dict[str, int],
                   ) -> List[str]:
    """Regressions of ``current`` vs the pinned census: any interesting op
    class that grew, or appeared where the baseline had none. Shrinkage is
    an improvement, reported by ``cli audit`` as a re-pin suggestion, never
    a violation."""
    regressions = []
    for op in INTERESTING_OPS:
        now = int(current.get(op, 0))
        then = int(pinned.get(op, 0))
        if now > then:
            regressions.append(f"{op}: {then} -> {now}")
    return regressions


def config_fingerprint(cfg_dict: dict) -> str:
    """Stable fingerprint of the audit config (shape-relevant keys only
    would invite drift bugs; hash the whole dict, sorted)."""
    import hashlib

    blob = json.dumps(cfg_dict, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]
