"""Static analysis: program-contract audits + a repo-specific lint pass.

The layers, by import weight:

* :mod:`analysis.contracts` (stdlib-only) — the contract vocabulary
  (``ContractViolation`` / ``AuditError``), the optimized-HLO op-census
  AND collective-census helpers shared with ``bench.py``, and the pinned
  ``CONTRACTS.json`` baseline format (single-device ``program@backend``
  keys plus mesh-keyed ``program@backend@RxC`` SPMD entries);
* :mod:`analysis.roofline` (stdlib-only) — the static roofline/MFU model:
  the device-peak table (also bench.py's MFU denominator), the roofline
  prediction per compiled program, and the ranked decomposition of
  predicted time into HLO opcode contributors;
* :mod:`analysis.auditor` (imports jax) — ``ProgramAuditor`` verifies the
  single-device contracts against the jaxpr and compiled HLO of every
  jitted program the system builds, and ``RetraceDetector`` watches
  abstract dispatch signatures at runtime for mid-run retraces;
* :mod:`analysis.spmd` (imports jax) — ``SpmdAuditor`` compiles the same
  program family under a real ``(data, task)`` mesh and verifies the SPMD
  performance contracts: sharding, per-axis collective census, static
  per-device HBM budget, roofline;
* :mod:`analysis.lint` (stdlib-only, AST-based) — repo-specific
  traced-code pitfall checkers, runnable on a machine without jax;
* :mod:`analysis.autotune` (stdlib-only; the sweep shells out to
  bench.py) — the roofline-driven step autotuner behind ``cli tune``:
  sweeps the lowering knob grid, ranks by measured step time
  cross-checked against the roofline predictions, and writes the
  device-kind-keyed ``TUNING.json`` that ``config``'s ``'auto'``
  resolution consults.

``cfg.analysis_level`` gates everything: ``'off'`` (default) installs
nothing and the jitted programs are bit-identical to a pre-analysis build
(tested); ``'warn'`` audits at program-build time (adding the SPMD audit
on multi-device single-host builds) and reports retraces to telemetry;
``'strict'`` fails the run on any violation or retrace.
"""

from .contracts import AuditError, ContractViolation  # noqa: F401
