"""Static analysis: program-contract audits + a repo-specific lint pass.

Two layers with different import weights:

* :mod:`analysis.contracts` (stdlib-only) — the contract vocabulary
  (``ContractViolation`` / ``AuditError``), the optimized-HLO op-census
  helpers shared with ``bench.py``, and the pinned ``CONTRACTS.json``
  baseline format;
* :mod:`analysis.auditor` (imports jax) — ``ProgramAuditor`` verifies the
  contracts against the jaxpr and compiled HLO of every jitted program the
  system builds, and ``RetraceDetector`` watches abstract dispatch
  signatures at runtime for mid-run retraces;
* :mod:`analysis.lint` (stdlib-only, AST-based) — repo-specific
  traced-code pitfall checkers, runnable on a machine without jax.

``cfg.analysis_level`` gates everything: ``'off'`` (default) installs
nothing and the jitted programs are bit-identical to a pre-analysis build
(tested); ``'warn'`` audits at program-build time and reports retraces to
telemetry; ``'strict'`` fails the run on any violation or retrace.
"""

from .contracts import AuditError, ContractViolation  # noqa: F401
