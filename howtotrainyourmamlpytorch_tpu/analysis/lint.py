"""Repo-specific JAX-pitfall lint pass (AST-based, jax-free).

Generic Python hygiene is ruff's job (config in ``pyproject.toml``); this
linter carries only the rules that need repo knowledge — which seams are
traced, which must donate, which I/O must retry:

* **MP001** — host operations inside traced code: within ``core/`` and
  ``ops/``, a function scope that does jax math (uses ``jnp.`` / ``lax.``
  / ``jax.lax`` / ``jax.vmap`` / ...) must not call ``np.*``, ``.item()``,
  ``float()`` / ``int()``, ``print()`` or ``open()`` — each is a silent
  device->host sync, a trace-time constant bake, or a side effect that
  breaks under jit;
* **MP002** — a ``jax.jit`` of a ``make_train*`` factory without
  ``donate_argnums``: every train-step executable must donate the state
  (``maml.TRAIN_DONATE``) or params+Adam double-buffer in HBM;
* **MP003** — a telemetry record built outside ``schema``'s blessed
  constructor: any dict literal with a ``"schema"`` key outside
  ``telemetry/sinks.py`` (``make_record`` is the single construction
  point — hand-rolled records skip the non-finite masking and version
  stamping);
* **MP004** — checkpoint/statistics I/O in ``experiment/builder.py`` not
  routed through ``resilience.retry`` (the ``retry.call(lambda: ...)`` /
  ``_write_stats(lambda: ...)`` seams): a bare call turns a transient
  filesystem fault into a dead run;
* **MP005** — a suppression comment without a reason (suppressions are
  ``# lint-ok: MPnnn <reason>`` on the offending line; the reason is
  mandatory and the rule id must exist);
* **MP006** — a non-owning numpy view over foreign-owned memory:
  ``np.frombuffer(...)`` anywhere (always ``owndata=False`` over a buffer
  something else may free), and ``np.asarray(...)`` / ``np.asanyarray``
  inside ``experiment/checkpoint.py`` (the restore seam — PR 6's
  owndata=False corruption class: numpy views over tensorstore-owned
  capsules that die with the restore context). The owning spelling is
  ``np.array(...)`` (or ``.copy()``); a justified view carries a reasoned
  ``# lint-ok: MP006`` suppression;
* **MP007** — ``time.time()`` anywhere: the wall clock steps under NTP
  slew/DST and must never measure a DURATION — durations are
  ``time.perf_counter()`` (what every span, latency decomposition and
  step timer uses; a clock mix also breaks cross-record correlation in
  the trace timeline). The handful of genuine wall-clock TIMESTAMPS
  (record ``ts`` envelopes, mtime comparisons) carry a reasoned
  ``# lint-ok: MP007`` suppression.

Run via ``python -m howtotrainyourmamlpytorch_tpu.cli lint [paths...]``
(defaults to the package + ``bench.py``); exits nonzero on violations.
Pure stdlib — works on a machine with neither jax nor numpy.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

RULES: Dict[str, str] = {
    "MP001": "host operation inside traced code (core/ and ops/)",
    "MP002": "jax.jit of a make_train* factory without donate_argnums",
    "MP003": "telemetry record constructed outside schema's make_record",
    "MP004": "checkpoint/stats I/O not routed through resilience.retry",
    "MP005": "lint suppression without a reason",
    "MP006": "non-owning numpy view over restored/foreign memory "
             "(np.frombuffer, or np.asarray in the checkpoint restore "
             "seam) — use an owning np.array copy",
    "MP007": "time.time() used where a duration may be measured — use "
             "time.perf_counter(); genuine wall-clock timestamps carry "
             "a reasoned suppression",
}

#: builtins whose call inside a traced scope forces a host sync or bakes a
#: trace-time constant
_HOST_BUILTINS = ("float", "int", "print", "open")

#: I/O seams MP004 requires behind a retry lambda in the builder
_RETRY_FUNCS = {"save_statistics", "save_to_json"}
_RETRY_METHODS = {"save_model", "load_model", "save_checkpoint",
                  "save_checkpoint_async", "load_checkpoint"}

_SUPPRESS_RE = re.compile(r"#\s*lint-ok:\s*(MP\d{3})\b[ \t]*(.*\S)?")


@dataclass(frozen=True)
class Violation:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute chain ('jax.lax.scan'), '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _numpy_aliases(tree: ast.Module) -> Set[str]:
    """Module-level aliases bound to numpy ('np' usually)."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy" or a.name.startswith("numpy."):
                    aliases.add(a.asname or a.name.split(".")[0])
    return aliases or {"np", "numpy"}


def _jax_math_aliases(tree: ast.Module) -> Set[str]:
    """Aliases whose use marks a scope as traced jax math: jax.numpy,
    jax.lax (however imported)."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("jax.numpy", "jax.lax"):
                    aliases.add(a.asname or a.name.split(".")[-1])
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name in ("numpy", "lax"):
                        aliases.add(a.asname or a.name)
            elif node.module in ("jax.numpy", "jax.lax"):
                for a in node.names:
                    aliases.add(a.asname or a.name)
    return aliases or {"jnp", "lax"}


#: jax.* attribute roots that also mark a scope as traced math
_JAX_TRACED_ATTRS = ("jax.lax.", "jax.nn.", "jax.vmap", "jax.grad",
                     "jax.value_and_grad", "jax.checkpoint")


class _ScopeInfo:
    def __init__(self, node: ast.AST):
        self.node = node
        self.uses_jax_math = False
        self.hits: List[Violation] = []


def _check_traced_host_ops(path: str, tree: ast.Module) -> List[Violation]:
    """MP001 — per function scope: jax math + host ops don't mix."""
    np_aliases = _numpy_aliases(tree)
    jm_aliases = _jax_math_aliases(tree)
    out: List[Violation] = []

    def scan_scope(fn_node) -> None:
        """One function scope: its own statements, not nested defs."""
        uses_math = False
        hits: List[tuple] = []

        def visit(node, top: bool):
            nonlocal uses_math
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    scan_scope(child)
                    continue
                if isinstance(child, ast.Lambda):
                    # lambdas share the enclosing scope's traced-ness
                    pass
                chain = ""
                if isinstance(child, ast.Attribute):
                    chain = _attr_chain(child)
                elif isinstance(child, ast.Name):
                    chain = child.id
                if chain:
                    root = chain.split(".")[0]
                    if root in jm_aliases or any(
                        chain.startswith(p) for p in _JAX_TRACED_ATTRS
                    ):
                        uses_math = True
                if isinstance(child, ast.Call):
                    func = child.func
                    fchain = _attr_chain(func) if isinstance(
                        func, (ast.Attribute, ast.Name)
                    ) else ""
                    if fchain.split(".")[0] in np_aliases and "." in fchain:
                        hits.append((child.lineno,
                                     f"call to {fchain}() in a traced scope"))
                    elif isinstance(func, ast.Attribute) and \
                            func.attr == "item":
                        hits.append((child.lineno,
                                     "'.item()' forces a device->host sync "
                                     "in a traced scope"))
                    elif isinstance(func, ast.Name) and \
                            func.id in _HOST_BUILTINS:
                        hits.append((child.lineno,
                                     f"call to {func.id}() in a traced "
                                     "scope"))
                visit(child, False)

        visit(fn_node, True)
        if uses_math:
            out.extend(
                Violation(path, line, "MP001", msg) for line, msg in hits
            )

    for node in ast.iter_child_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(node)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan_scope(item)
    return out


def _check_jit_donation(path: str, tree: ast.Module) -> List[Violation]:
    """MP002 — jax.jit(...make_train*...) must pass donate_argnums."""
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func) if isinstance(
            node.func, (ast.Attribute, ast.Name)
        ) else ""
        if not (chain == "jit" or chain.endswith(".jit")):
            continue
        mentions_train_factory = any(
            "make_train" in (_attr_chain(sub) or "")
            for arg in node.args
            for sub in ast.walk(arg)
            if isinstance(sub, (ast.Attribute, ast.Name))
        )
        if not mentions_train_factory:
            continue
        if not any(kw.arg == "donate_argnums" for kw in node.keywords):
            out.append(Violation(
                path, node.lineno, "MP002",
                "jax.jit of a make_train* factory without donate_argnums "
                "(state double-buffers in HBM; use maml.TRAIN_DONATE)",
            ))
    return out


def _check_schema_bypass(path: str, tree: ast.Module) -> List[Violation]:
    """MP003 — dict literals with a "schema" key outside make_record."""
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for key in node.keys:
            if isinstance(key, ast.Constant) and key.value == "schema":
                out.append(Violation(
                    path, node.lineno, "MP003",
                    "telemetry record built by hand (dict with a 'schema' "
                    "key); route it through telemetry.sinks.make_record",
                ))
    return out


def _check_unrouted_io(path: str, tree: ast.Module) -> List[Violation]:
    """MP004 — builder I/O seams must sit behind a retry lambda."""
    out: List[Violation] = []

    def visit(node, in_lambda: bool):
        for child in ast.iter_child_nodes(node):
            child_in_lambda = in_lambda or isinstance(child, ast.Lambda)
            if isinstance(child, ast.Call) and not child_in_lambda:
                func = child.func
                name = ""
                if isinstance(func, ast.Name):
                    name = func.id
                elif isinstance(func, ast.Attribute):
                    name = func.attr
                if name in _RETRY_FUNCS or name in _RETRY_METHODS:
                    out.append(Violation(
                        path, child.lineno, "MP004",
                        f"direct call to {name}() — route it through "
                        "resilience.retry (retry.call(lambda: ...) or "
                        "_write_stats(lambda: ...)) so transient I/O "
                        "faults are retried",
                    ))
            visit(child, child_in_lambda)

    visit(tree, False)
    return out


def _check_view_over_foreign_memory(
    path: str, tree: ast.Module, restore_seam: bool
) -> List[Violation]:
    """MP006 — numpy views that do not own their memory.

    ``np.frombuffer`` is flagged everywhere: its result is always a view
    (``owndata=False``) over a buffer whose lifetime something else
    controls — the exact class of the PR 6 checkpoint-corruption bugs.
    In the checkpoint restore seam (``restore_seam=True``), ``np.asarray``
    / ``np.asanyarray`` are flagged too: over a freshly-restored
    tensorstore/orbax leaf they alias memory that dies with the restore
    context; the owning spelling there is ``np.array``. A call whose
    result is immediately copied (``np.frombuffer(...).copy()`` or
    wrapped in ``np.array(...)``) is an explicit owning copy and passes.
    """
    np_aliases = _numpy_aliases(tree)
    out: List[Violation] = []

    def flagged_call(node: ast.Call) -> Optional[str]:
        func = node.func
        chain = _attr_chain(func) if isinstance(
            func, (ast.Attribute, ast.Name)
        ) else ""
        if chain.split(".")[0] not in np_aliases or "." not in chain:
            return None
        attr = chain.split(".")[-1]
        if attr == "frombuffer":
            return chain
        if restore_seam and attr in ("asarray", "asanyarray"):
            return chain
        return None

    def owned(parent: ast.AST, node: ast.Call) -> bool:
        # np.array(np.frombuffer(...)) or np.frombuffer(...).copy(): the
        # view never escapes un-owned
        if isinstance(parent, ast.Attribute) and parent.attr == "copy":
            return True
        if isinstance(parent, ast.Call):
            chain = _attr_chain(parent.func) if isinstance(
                parent.func, (ast.Attribute, ast.Name)
            ) else ""
            if chain.split(".")[-1] == "array" and (
                chain.split(".")[0] in np_aliases
            ):
                return True
        return False

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call):
                chain = flagged_call(child)
                if chain is not None and not owned(node, child):
                    out.append(Violation(
                        path, child.lineno, "MP006",
                        f"{chain}() returns a non-owning view over memory "
                        "something else may free (the PR 6 owndata=False "
                        "checkpoint-corruption class); copy it with "
                        "np.array(...) or .copy() while the source is "
                        "alive",
                    ))
            visit(child)

    visit(tree)
    return out


def _time_aliases(tree: ast.Module) -> Dict[str, str]:
    """Names that resolve to ``time.time`` in this module: the ``time``
    module's aliases -> 'module', plus direct ``from time import time``
    bindings -> 'func'."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    out[a.asname or "time"] = "module"
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    out[a.asname or "time"] = "func"
    return out


def _check_wall_clock(path: str, tree: ast.Module) -> List[Violation]:
    """MP007 — every ``time.time()`` call (however ``time`` is bound).

    A duration-vs-timestamp dataflow analysis would miss aliased reads,
    so the rule is total: perf_counter is ALWAYS correct for durations,
    and the few legitimate wall-clock timestamps (record ``ts`` fields,
    mtime comparisons) each carry a reasoned suppression — which also
    documents, in place, why the wall clock is the right clock there.
    """
    aliases = _time_aliases(tree)
    if not aliases:
        return []
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        hit = False
        if isinstance(func, ast.Attribute):
            chain = _attr_chain(func)
            root = chain.split(".")[0]
            if (
                chain.endswith(".time")
                and chain.count(".") == 1
                and aliases.get(root) == "module"
            ):
                hit = True
        elif isinstance(func, ast.Name):
            if aliases.get(func.id) == "func":
                hit = True
        if hit:
            out.append(Violation(
                path, node.lineno, "MP007",
                "time.time() steps with the wall clock; measure "
                "durations with time.perf_counter() (a genuine "
                "timestamp needs `# lint-ok: MP007 <why wall clock>`)",
            ))
    return out


def _apply_suppressions(
    violations: List[Violation], path: str, source_lines: List[str]
) -> List[Violation]:
    """Drop violations whose line carries a matching reasoned suppression;
    flag malformed suppressions (MP005)."""
    suppressions: Dict[int, tuple] = {}
    out: List[Violation] = []
    for lineno, line in enumerate(source_lines, 1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rule, reason = m.group(1), (m.group(2) or "").strip()
        if rule not in RULES:
            out.append(Violation(
                path, lineno, "MP005",
                f"suppression names unknown rule {rule!r}",
            ))
        elif not reason:
            out.append(Violation(
                path, lineno, "MP005",
                f"suppression of {rule} without a reason — justify it "
                "(# lint-ok: MPnnn <why this is safe>)",
            ))
        else:
            suppressions[lineno] = (rule, reason)
    for v in violations:
        sup = suppressions.get(v.line)
        if sup is not None and sup[0] == v.rule:
            continue
        out.append(v)
    return out


def _package_relpath(path: str) -> Optional[str]:
    """Path relative to the package root, or None when outside it."""
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if "howtotrainyourmamlpytorch_tpu" in parts:
        i = parts.index("howtotrainyourmamlpytorch_tpu")
        return "/".join(parts[i + 1:])
    return None


def lint_file(path: str) -> List[Violation]:
    """Lint one Python file with the rules that apply to its location."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Violation(path, e.lineno or 0, "MP001",
                          f"file does not parse: {e.msg}")]
    rel = _package_relpath(path)
    violations: List[Violation] = []
    if rel is not None and rel.split("/")[0] in ("core", "ops"):
        violations += _check_traced_host_ops(path, tree)
    violations += _check_jit_donation(path, tree)
    if rel not in ("telemetry/sinks.py", "telemetry/schema.py"):
        violations += _check_schema_bypass(path, tree)
    if rel == "experiment/builder.py":
        violations += _check_unrouted_io(path, tree)
    violations += _check_view_over_foreign_memory(
        path, tree, restore_seam=(rel == "experiment/checkpoint.py")
    )
    violations += _check_wall_clock(path, tree)
    return _apply_suppressions(violations, path, source.splitlines())


def iter_python_files(paths: Sequence[str]):
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for fn in sorted(files):
                if fn.endswith(".py"):
                    yield os.path.join(root, fn)


def lint_paths(paths: Sequence[str]) -> List[Violation]:
    violations: List[Violation] = []
    for path in iter_python_files(paths):
        violations += lint_file(path)
    return violations


def default_paths() -> List[str]:
    """The package itself plus bench.py at the repo root (when present)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [pkg]
    bench = os.path.join(os.path.dirname(pkg), "bench.py")
    if os.path.isfile(bench):
        paths.append(bench)
    return paths


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="lint",
        description="JAX-pitfall lint pass (repo-specific rules; generic "
                    "Python hygiene is ruff's job)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the package + bench.py)",
    )
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON output")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)
    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0
    paths = list(args.paths) or default_paths()
    violations = lint_paths(paths)
    if args.json:
        print(json.dumps(
            [v.__dict__ for v in violations], indent=2, sort_keys=True
        ))
    else:
        for v in violations:
            print(v)
        n_files = sum(1 for _ in iter_python_files(paths))
        print(
            f"lint: {len(violations)} violation(s) in {n_files} file(s)",
            file=sys.stderr,
        )
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
