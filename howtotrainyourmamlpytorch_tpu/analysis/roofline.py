"""Static roofline/MFU model of a compiled program.

BENCH_BASELINE.json pins the flagship second-order step at 2.5% MFU on
TPU v5 lite and ROADMAP item 2 says "close the gap" — but the bench line
only states the number; nothing explains *where the other 97.5% goes*.
This module turns the already-available static surfaces (XLA's
``cost_analysis`` flops + bytes accessed, the optimized-HLO op census,
and a small device-peak table) into a roofline model per program:

* which side of the roofline the program sits on (compute- vs
  memory-bound: arithmetic intensity ``flops / bytes`` against the
  device's critical intensity ``peak_flops / hbm_bw``);
* the predicted step time, HFU and — when the analytic model-flop count
  is supplied — MFU implied by the static counts alone;
* a ranked decomposition of that predicted time into the top-k HLO
  opcode contributors (dot/conv flops are recovered per instruction from
  the HLO text; everything else is charged its memory traffic), so "the
  MFU is low" becomes "fusions move 4x the bytes the dots do" — a work
  list, not a mystery.

The model is *static*: no execution, no profiler — it runs at audit time
(``cli audit --mesh``), at build time (``analysis_level != 'off'``) and
inside ``bench.py`` (the ``roofline`` field), and its flops/task is
cross-checked against the ``xla_flops_per_task`` the bench records (both
derive from the same ``cost_analysis`` surface, so a disagreement means
the model is reading a different executable than the bench timed).

Deliberately stdlib-only, like :mod:`analysis.contracts`: ``bench.py``
imports the device-peak table from here (ONE peak table — the MFU the
bench quotes and the MFU the roofline predicts can never disagree about
what "peak" means), and jax-free tooling can rank an HLO dump scp'd off
a pod.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from .contracts import ContractViolation, cost_analysis_dict, hlo_shape_bytes

#: Peak dense-matmul FLOPs/chip and HBM bandwidth by device kind. bf16
#: rates are the published MXU peaks; fp32 runs at roughly a third of
#: bf16 on these parts (emulated via multiple bf16 passes). ``nominal``
#: entries (the CPU fallback) let the roofline model run anywhere —
#: bench.py's quoted MFU ignores them (a made-up CPU "peak" would turn
#: the longitudinal MFU series into noise).
DEVICE_PEAKS: List[dict] = [
    {"kind": "v5 lite", "flops": {"bfloat16": 197e12, "float32": 66e12},
     "hbm_bytes_per_s": 819e9, "nominal": False},
    {"kind": "v5e", "flops": {"bfloat16": 197e12, "float32": 66e12},
     "hbm_bytes_per_s": 819e9, "nominal": False},
    {"kind": "v5p", "flops": {"bfloat16": 459e12, "float32": 153e12},
     "hbm_bytes_per_s": 2765e9, "nominal": False},
    {"kind": "v4", "flops": {"bfloat16": 275e12, "float32": 92e12},
     "hbm_bytes_per_s": 1228e9, "nominal": False},
    {"kind": "v6", "flops": {"bfloat16": 918e12, "float32": 306e12},
     "hbm_bytes_per_s": 1638e9, "nominal": False},
    # CPU hosts: a nominal single-core figure so the model (and its CI
    # tests) produce a full report on the 8-virtual-device test backend
    {"kind": "cpu", "flops": {"bfloat16": 1e11, "float32": 1e11},
     "hbm_bytes_per_s": 5e10, "nominal": True},
]

#: contributors reported by the decomposition
TOP_K_CONTRIBUTORS = 5


def find_peak_entry(
    device_kind: str, peaks: Optional[List[dict]] = None
) -> Optional[dict]:
    """The peak-table entry whose ``kind`` substring matches
    ``device_kind`` (case-insensitive), or None."""
    kind = (device_kind or "").lower()
    for entry in peaks if peaks is not None else DEVICE_PEAKS:
        if entry.get("kind", "") in kind:
            return entry
    return None


def peak_flops(
    device_kind: str, dtype: str, peaks: Optional[List[dict]] = None
) -> Optional[float]:
    """Published peak FLOPs/s for (device kind, compute dtype) — None for
    unknown hardware AND for nominal (CPU-fallback) entries: this is the
    denominator of the MFU the bench *quotes*, which must never be a
    made-up number."""
    entry = find_peak_entry(device_kind, peaks)
    if entry is None or entry.get("nominal"):
        return None
    table = entry.get("flops") or {}
    value = table.get(dtype, table.get("float32"))
    return float(value) if value else None


# -- per-instruction flop/byte recovery from the optimized HLO ----------------

#: `%name = <shape> <opcode>(<operands>)<attributes>`
_INSN_RE = re.compile(
    r"=\s+(\([^)]*\)|\S+)\s+([a-z][a-z0-9-]*)\(([^\n]*)"
)
_DIMS_RE = re.compile(r"\{([0-9,]*)\}")
_OPERAND_SHAPE_RE = re.compile(r"(?:pred|[a-z]+\d+)\[[0-9,]*\]")
_WINDOW_SIZE_RE = re.compile(r"window=\{[^}]*size=([0-9x]+)")
_DIM_LABELS_RE = re.compile(r"dim_labels=(\S+?)_(\S+?)->")


def _shape_elems(shape_str: str) -> int:
    m = re.search(r"\[([0-9,]*)\]", shape_str)
    if not m:
        return 1
    n = 1
    for d in m.group(1).split(","):
        if d.strip():
            n *= int(d)
    return n


def _shape_dims(shape_str: str) -> List[int]:
    m = re.search(r"\[([0-9,]*)\]", shape_str)
    if not m:
        return []
    return [int(d) for d in m.group(1).split(",") if d.strip()]


def _dot_flops(out_shape: str, operands: str, tail: str) -> float:
    """2 * out_elems * K for one HLO ``dot``: K from the lhs operand's
    contracting dims (printed inline in the instruction)."""
    shapes = _OPERAND_SHAPE_RE.findall(operands)
    m = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", tail)
    if not shapes or not m:
        return 0.0
    lhs_dims = _shape_dims(shapes[0])
    k = 1
    for idx in (int(d) for d in m.group(1).split(",") if d.strip()):
        if idx < len(lhs_dims):
            k *= lhs_dims[idx]
    return 2.0 * _shape_elems(out_shape) * k


def _conv_flops(out_shape: str, operands: str, tail: str) -> float:
    """2 * out_elems * kernel_spatial * cin_per_group for one HLO
    ``convolution`` (dim_labels names the rhs input-feature dim)."""
    shapes = _OPERAND_SHAPE_RE.findall(operands)
    win = _WINDOW_SIZE_RE.search(tail)
    labels = _DIM_LABELS_RE.search(tail)
    if len(shapes) < 2 or win is None:
        return 0.0
    spatial = 1
    for d in win.group(1).split("x"):
        spatial *= int(d)
    rhs_dims = _shape_dims(shapes[1])
    cin = 1
    if labels is not None and "i" in labels.group(2):
        i_pos = labels.group(2).index("i")
        if i_pos < len(rhs_dims):
            cin = rhs_dims[i_pos]
    elif rhs_dims:
        cin = rhs_dims[-2] if len(rhs_dims) >= 2 else rhs_dims[0]
    return 2.0 * _shape_elems(out_shape) * spatial * cin


#: opcodes that move no bytes and do no math — pure aliasing/bookkeeping,
#: excluded from the decomposition so the ranking names real work
_FREE_OPS = frozenset({"bitcast", "tuple", "get-tuple-element",
                       "after-all", "partition-id", "replica-id"})

#: elementwise arithmetic opcodes charged ~1 flop per output element (the
#: XLA cost analysis counts these too — without them the decomposition's
#: flop coverage collapses on elementwise-heavy programs)
_ELEMENTWISE_OPS = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "power",
    "exponential", "log", "rsqrt", "sqrt", "tanh", "negate", "compare",
    "select", "and", "or", "xor", "fusion",
})


def _strip_fused_computation_bodies(hlo_text: str) -> str:
    """Drop the instruction lines inside ``%fused_computation`` blocks.

    A fusion's *internals* live in registers — charging each internal
    add/multiply its full output bytes would count as HBM traffic exactly
    the bytes fusion exists to keep out of HBM, and double-count the work
    the enclosing ``fusion`` instruction is already charged for.
    Computation headers sit at column 0 in the HLO dump; everything until
    the closing ``}`` of a fused computation is skipped. Other non-entry
    computations (while bodies, reduction regions) are kept: their ops
    run for real."""
    out = []
    in_fused = False
    for line in hlo_text.splitlines():
        if line and not line[0].isspace() and line.rstrip().endswith("{"):
            in_fused = line.lstrip().startswith("%fused_computation")
            continue
        if line.strip() == "}":
            in_fused = False
            continue
        if not in_fused:
            out.append(line)
    return "\n".join(out)


def op_cost_census(hlo_text: str) -> Dict[str, Dict[str, float]]:
    """Per-opcode static costs over an optimized-HLO dump:
    ``{op: {count, flops, bytes}}``. Flops are recovered per instruction
    for dot/convolution (the ops that carry the model's real compute) and
    estimated at one per output element for elementwise arithmetic;
    every opcode is charged its output bytes as memory traffic (fusion
    bodies excluded — see ``_strip_fused_computation_bodies``). The dot
    of this census with the device-peak table is the decomposition
    ``roofline_report`` ranks."""
    census: Dict[str, Dict[str, float]] = {}
    for m in _INSN_RE.finditer(_strip_fused_computation_bodies(hlo_text)):
        shape, op, rest = m.groups()
        if op in _FREE_OPS:
            continue
        # split the operand list from the trailing attributes at the
        # closing paren of the call (best-effort: attributes follow ')')
        depth, split = 1, len(rest)
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    split = i
                    break
        operands, tail = rest[:split], rest[split:]
        slot = census.setdefault(
            op, {"count": 0.0, "flops": 0.0, "bytes": 0.0}
        )
        slot["count"] += 1
        slot["bytes"] += hlo_shape_bytes(shape)
        if op == "dot":
            slot["flops"] += _dot_flops(shape, operands, tail)
        elif op == "convolution":
            slot["flops"] += _conv_flops(shape, operands, tail)
        elif op in _ELEMENTWISE_OPS:
            slot["flops"] += _shape_elems(shape)
    return census


# -- the model ----------------------------------------------------------------


def roofline_report(
    compiled,
    *,
    device_kind: str,
    dtype: str,
    tasks: int = 1,
    model_flops: Optional[float] = None,
    peaks: Optional[List[dict]] = None,
    top_k: int = TOP_K_CONTRIBUTORS,
) -> dict:
    """The static roofline report of one compiled executable.

    ``tasks`` is the task count the executable processes per dispatch (per
    device for a sharded module — ``cost_analysis`` counts the partitioned
    program), so ``flops_per_task`` is directly comparable to the
    ``xla_flops_per_task`` the bench records. ``model_flops`` is the
    *algorithmic* flop count (no remat recompute) when the caller has one
    — it turns the predicted HFU into a predicted MFU. ``peaks`` overrides
    the device table (tests perturb it; ``verify_roofline`` then fails the
    cross-check).
    """
    ca = cost_analysis_dict(compiled)
    flops = float(ca.get("flops") or 0.0)
    bytes_accessed = float(ca.get("bytes accessed") or 0.0)
    entry = find_peak_entry(device_kind, peaks)
    report: dict = {
        "device_kind": device_kind,
        "dtype": dtype,
        "tasks": int(tasks),
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "flops_per_task": flops / tasks if tasks else None,
        "model_flops": model_flops,
        "peak_flops": None,
        "hbm_bytes_per_s": None,
        "nominal_peaks": None,
        "arithmetic_intensity": (
            flops / bytes_accessed if bytes_accessed > 0 else None
        ),
        "critical_intensity": None,
        "bound": None,
        "predicted_step_seconds": None,
        "predicted_hfu": None,
        "predicted_mfu": None,
        "flops_coverage": None,
        "top_contributors": [],
    }
    if entry is not None:
        table = entry.get("flops") or {}
        peak = table.get(dtype, table.get("float32"))
        bw = entry.get("hbm_bytes_per_s")
        report["peak_flops"] = float(peak) if peak else None
        report["hbm_bytes_per_s"] = float(bw) if bw else None
        report["nominal_peaks"] = bool(entry.get("nominal"))
    peak = report["peak_flops"]
    bw = report["hbm_bytes_per_s"]
    if peak and peak > 0 and bw and bw > 0 and flops > 0:
        t_compute = flops / peak
        t_memory = bytes_accessed / bw
        t = max(t_compute, t_memory)
        report["critical_intensity"] = peak / bw
        report["bound"] = "compute" if t_compute >= t_memory else "memory"
        report["predicted_step_seconds"] = t
        report["predicted_hfu"] = round(t_compute / t, 4) if t > 0 else None
        if model_flops and t > 0:
            report["predicted_mfu"] = round(model_flops / peak / t, 4)
        # decomposition: charge each opcode class its own roofline time
        try:
            census = op_cost_census(compiled.as_text())
        except Exception:  # noqa: BLE001 - decomposition is best-effort
            census = {}
        est_flops = sum(c["flops"] for c in census.values())
        report["flops_coverage"] = (
            round(est_flops / flops, 4) if flops > 0 else None
        )
        contributors = []
        for op, c in census.items():
            t_op = max(c["flops"] / peak, c["bytes"] / bw)
            contributors.append({
                "op": op,
                "count": int(c["count"]),
                "flops": c["flops"],
                "bytes": c["bytes"],
                "seconds": t_op,
                "bound": (
                    "compute" if c["flops"] / peak >= c["bytes"] / bw
                    else "memory"
                ),
            })
        contributors.sort(key=lambda c: c["seconds"], reverse=True)
        total_t = sum(c["seconds"] for c in contributors) or 1.0
        for c in contributors:
            c["time_share"] = round(c["seconds"] / total_t, 4)
        report["top_contributors"] = contributors[:top_k]
    return report


def verify_roofline(
    report: dict,
    program: str,
    reference_flops_per_task: Optional[float] = None,
    rel_tol: float = 0.05,
) -> List[ContractViolation]:
    """The ``roofline`` contract: the model must have produced a usable
    prediction (a device-peak entry exists and is positive, the cost
    analysis yielded flops), and — when a reference is supplied (the
    ``xla_flops_per_task`` a bench line recorded for the same workload) —
    the model's flops/task must agree within ``rel_tol``. A perturbed or
    missing peak-table entry fails here, nowhere else."""
    violations: List[ContractViolation] = []

    def flag(detail: str) -> None:
        violations.append(ContractViolation("roofline", program, detail))

    peak = report.get("peak_flops")
    bw = report.get("hbm_bytes_per_s")
    if not peak or peak <= 0 or not bw or bw <= 0:
        flag(
            f"device-peak table has no usable entry for "
            f"kind={report.get('device_kind')!r} dtype="
            f"{report.get('dtype')!r} (peak_flops={peak!r}, "
            f"hbm_bytes_per_s={bw!r}) — the MFU model cannot run"
        )
    if not report.get("flops"):
        flag("cost_analysis reported no flops; the roofline model has no "
             "numerator")
    current = report.get("flops_per_task")
    if (
        reference_flops_per_task
        and current
        and abs(current - reference_flops_per_task)
        > rel_tol * reference_flops_per_task
    ):
        flag(
            f"model flops/task {current:.3e} disagrees with the recorded "
            f"xla_flops_per_task {reference_flops_per_task:.3e} by more "
            f"than {rel_tol:.0%} — the model is reading a different "
            "program than the bench measured"
        )
    return violations
