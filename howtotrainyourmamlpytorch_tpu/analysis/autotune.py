"""Roofline-driven step autotuner: measure, rank, pin the fast lowering.

ROADMAP item 2's gap had a mechanical cause: the lowering knobs that
decide whether the train step saturates the MXU (``conv_impl``,
``pad_channels``, ``remat_policy``, ``meta_accum_steps``, and — since
PR 16's inner-loop compute diet — ``bn_stats_impl`` and ``pool_impl``)
were resolved by *heuristics*, and the heuristics lost quietly
(BENCH_BASELINE.json records ``conv_impl='lax'`` at 2.5% MFU on a
machine where the gemm path existed). This module replaces the guess
with a measurement:

* ``cli tune`` sweeps the knob grid with ``bench.py``'s harness (one
  subprocess per point — the same timed-step protocol, donation and
  tunnel-proof sync as the longitudinal bench line), ranks the points by
  measured ``meta_tasks_per_sec_per_chip``, cross-checks the ranking
  against the static roofline predictions each bench line carries
  (``analysis/roofline.py`` — a point whose measurement and prediction
  disagree about the winner is flagged, not silently trusted), and
  writes a **device-kind-keyed tuning table** (``TUNING.json``);
* ``config.resolved_conv_impl`` / ``resolved_pad_channels`` consult the
  table under ``'auto'``: the measured winner for this device kind +
  compute dtype becomes the default, with the PR-4 heuristic as the
  fallback when no table (or no entry) exists.

The table half is deliberately stdlib-only (config imports it on every
``'auto'`` resolution; the sweep half shells out to ``bench.py`` so jax
state never leaks between points — every point compiles in a pristine
process).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Any, Dict, List, Optional, Tuple

TUNING_VERSION = 1

#: env var overriding the table location (tests point it at tmp files;
#: operators can ship a pod-wide table without touching the checkout)
TUNING_TABLE_ENV = "MAML_TUNING_TABLE"

#: the swept knobs, in the order they appear in point labels.
#: ``bn_stats_impl`` / ``pool_impl`` joined in PR 16 (the inner-loop
#: compute diet): both change the scan body's reduction structure, so the
#: table — not the heuristic — decides per device kind whether the fused
#: BN statistics pass and the reshape pool win.
SWEEP_KNOBS: Tuple[str, ...] = (
    "conv_impl", "pad_channels", "remat_policy", "meta_accum_steps",
    "bn_stats_impl", "pool_impl",
)

_VALID_CONV_IMPL = ("lax", "im2col", "gemm")
_VALID_PAD = ("off", "tile")
_VALID_REMAT = ("full", "save_conv")
_VALID_BN_STATS = ("twopass", "fused")
_VALID_POOL = ("reshape", "reduce_window")


def default_table_path() -> str:
    """``$MAML_TUNING_TABLE`` when set, else ``TUNING.json`` at the repo
    root (next to CONTRACTS.json / BENCH_BASELINE.json)."""
    env = os.environ.get(TUNING_TABLE_ENV)
    if env:
        return env
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "TUNING.json",
    )


def table_key(device_kind: str, dtype: str) -> str:
    """Entries are keyed ``<device_kind>@<compute_dtype>`` — the same pair
    that keys the roofline peak table, so one host never reads another
    accelerator generation's tuning."""
    return f"{device_kind}@{dtype}"


def validate_tuning_table(data: Any) -> None:
    """Raise ``ValueError`` unless ``data`` is a structurally valid tuning
    table (what the CI ``cli tune --fast`` gate asserts)."""
    if not isinstance(data, dict):
        raise ValueError("tuning table must be a JSON object")
    if data.get("version") != TUNING_VERSION:
        raise ValueError(
            f"tuning table version {data.get('version')!r} != "
            f"{TUNING_VERSION}"
        )
    entries = data.get("entries")
    if not isinstance(entries, dict) or not entries:
        raise ValueError("tuning table has no 'entries' mapping")
    for key, entry in entries.items():
        if "@" not in key:
            raise ValueError(
                f"entry key {key!r} is not '<device_kind>@<dtype>'"
            )
        if not isinstance(entry, dict):
            raise ValueError(f"entry {key!r} is not an object")
        if entry.get("conv_impl") not in _VALID_CONV_IMPL:
            raise ValueError(
                f"entry {key!r}: conv_impl {entry.get('conv_impl')!r} "
                f"not in {_VALID_CONV_IMPL}"
            )
        pad = entry.get("pad_channels")
        if not (
            pad in _VALID_PAD
            or (isinstance(pad, int) and not isinstance(pad, bool) and pad > 0)
        ):
            raise ValueError(
                f"entry {key!r}: pad_channels {pad!r} must be 'off', "
                "'tile' or a positive int"
            )
        if entry.get("remat_policy") not in _VALID_REMAT:
            raise ValueError(
                f"entry {key!r}: remat_policy "
                f"{entry.get('remat_policy')!r} not in {_VALID_REMAT}"
            )
        accum = entry.get("meta_accum_steps")
        if not (
            isinstance(accum, int) and not isinstance(accum, bool)
            and accum >= 1
        ):
            raise ValueError(
                f"entry {key!r}: meta_accum_steps {accum!r} must be an "
                "int >= 1"
            )
        # the PR-16 axes are validated when present but not REQUIRED: a
        # table measured before the sweep grew them still pins its
        # conv/pad/remat/accum winners (the resolvers fall back to the
        # heuristic for the missing knobs); every table this version
        # writes carries both, and the CI gate asserts that on the
        # freshly-swept table
        bn_stats = entry.get("bn_stats_impl")
        if bn_stats is not None and bn_stats not in _VALID_BN_STATS:
            raise ValueError(
                f"entry {key!r}: bn_stats_impl {bn_stats!r} not in "
                f"{_VALID_BN_STATS}"
            )
        pool = entry.get("pool_impl")
        if pool is not None and pool not in _VALID_POOL:
            raise ValueError(
                f"entry {key!r}: pool_impl {pool!r} not in {_VALID_POOL}"
            )
        tps = entry.get("tasks_per_sec_per_chip")
        if not isinstance(tps, (int, float)) or isinstance(tps, bool) or (
            tps <= 0
        ):
            raise ValueError(
                f"entry {key!r}: tasks_per_sec_per_chip {tps!r} must be a "
                "positive number"
            )


# the table is consulted inside config property resolution, which runs
# during program tracing — memoize by (path, mtime) so a trace pays one
# stat, not one parse, per consult
_TABLE_CACHE: Dict[str, Tuple[float, Optional[dict]]] = {}


def load_tuning_table(path: Optional[str] = None) -> Optional[dict]:
    """The parsed tuning table, or None when absent/unreadable/invalid.
    Never raises: a corrupt table degrades to the heuristics with a
    one-line stderr note, it must not take training down."""
    path = path or default_table_path()
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    cached = _TABLE_CACHE.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    data: Optional[dict] = None
    try:
        with open(path) as f:
            loaded = json.load(f)
        validate_tuning_table(loaded)
        data = loaded
    except (OSError, ValueError) as e:
        print(
            f"[autotune] ignoring invalid tuning table {path}: {e}",
            file=sys.stderr,
        )
    _TABLE_CACHE[path] = (mtime, data)
    return data


def tuned_entry(
    device_kind: str, dtype: str, table: Optional[dict] = None,
    path: Optional[str] = None,
) -> Optional[dict]:
    """The tuning entry for (device kind, compute dtype), or None. Exact
    key match first, then a case-insensitive substring match on the device
    kind (the same relaxed matching the roofline peak table uses — a table
    pinned on 'TPU v5 lite' serves a host reporting 'TPU v5 litepod')."""
    if table is None:
        table = load_tuning_table(path)
    if table is None:
        return None
    entries = table.get("entries", {})
    exact = entries.get(table_key(device_kind, dtype))
    if exact is not None:
        return exact
    kind = (device_kind or "").lower()
    for key, entry in entries.items():
        entry_kind, _, entry_dtype = key.rpartition("@")
        if entry_dtype == dtype and entry_kind.lower() in kind and entry_kind:
            return entry
    return None


def clear_cache() -> None:
    """Drop the memoized tables (tests rewrite table files in place)."""
    _TABLE_CACHE.clear()


# -- the sweep ---------------------------------------------------------------


def sweep_points(fast: bool = False) -> List[Dict[str, Any]]:
    """The knob grid ``cli tune`` measures.

    ``fast`` (the CI smoke): 2 points that still cross every axis once —
    enough to prove the harness end to end without a grid of bench runs.
    Full: conv_impl x pad_channels x remat_policy x meta_accum_steps x
    bn_stats_impl x pool_impl — the ROADMAP-item-2 lowering grid crossed
    with the PR-16 compute-diet axes (144 points; each is one reduced
    bench run, so the full sweep is an hours-scale hardware session,
    which is the point: measured once per device generation, consulted
    forever).
    """
    if fast:
        return [
            {"conv_impl": "gemm", "pad_channels": "tile",
             "remat_policy": "save_conv", "meta_accum_steps": 1,
             "bn_stats_impl": "fused", "pool_impl": "reshape"},
            {"conv_impl": "im2col", "pad_channels": "off",
             "remat_policy": "full", "meta_accum_steps": 2,
             "bn_stats_impl": "twopass", "pool_impl": "reduce_window"},
        ]
    points = []
    conv_impls = ["lax", "gemm", "im2col"]
    for conv_impl in conv_impls:
        for pad in ("off", "tile"):
            for remat in ("full", "save_conv"):
                for accum in (1, 2, 4):
                    for bn_stats in ("twopass", "fused"):
                        for pool in ("reshape", "reduce_window"):
                            points.append({
                                "conv_impl": conv_impl,
                                "pad_channels": pad,
                                "remat_policy": remat,
                                "meta_accum_steps": accum,
                                "bn_stats_impl": bn_stats,
                                "pool_impl": pool,
                            })
    return points


def point_label(point: Dict[str, Any]) -> str:
    # tolerate pre-PR-16 points (no bn_stats_impl/pool_impl axes)
    return ",".join(
        f"{k}={point[k]}" for k in SWEEP_KNOBS if k in point
    )


#: sub-measurements every sweep point skips — points rank train-step
#: throughput only, exactly like bench_sweep
_SWEEP_ENV = {
    "BENCH_NO_BASELINE_WRITE": "1",
    "BENCH_SKIP_EPOCH_BOUNDARY": "1",
    "BENCH_SKIP_INPUT_PIPELINE": "1",
    "BENCH_SKIP_TELEMETRY_OVERHEAD": "1",
    "BENCH_SKIP_HEALTH_OVERHEAD": "1",
}

#: tiny-workload knobs for --fast (CI runs this on a CPU runner; the
#: point is a valid table, not a meaningful number)
_FAST_ENV = {
    "BENCH_WARMUP_STEPS": "1",
    "BENCH_TIMED_STEPS": "2",
    "BENCH_BATCH_SIZE": "2",
    "BENCH_CNN_NUM_FILTERS": "8",
    "BENCH_IMAGE_HEIGHT": "16",
    "BENCH_IMAGE_WIDTH": "16",
    "BENCH_NUMBER_OF_TRAINING_STEPS_PER_ITER": "2",
    "BENCH_NUMBER_OF_EVALUATION_STEPS_PER_ITER": "2",
}


def bench_script_path() -> str:
    """``bench.py`` at the repo root (next to this package)."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "bench.py",
    )


def run_bench_point(
    point: Dict[str, Any],
    fast: bool = False,
    timeout_s: float = 1800.0,
    extra_env: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """One sweep point = one ``bench.py`` subprocess with the point's
    knobs as BENCH_* env vars. Returns the parsed bench line (raises
    ``RuntimeError`` naming the point on a non-zero exit / unparsable
    output)."""
    env = dict(os.environ)
    env.update(_SWEEP_ENV)
    if fast:
        env.update(_FAST_ENV)
    env["BENCH_CONV_IMPL"] = str(point["conv_impl"])
    env["BENCH_PAD_CHANNELS"] = str(point["pad_channels"])
    env["BENCH_REMAT_POLICY"] = str(point["remat_policy"])
    env["BENCH_USE_REMAT"] = "true"
    env["BENCH_META_ACCUM_STEPS"] = str(point["meta_accum_steps"])
    env["BENCH_BN_STATS_IMPL"] = str(point["bn_stats_impl"])
    env["BENCH_POOL_IMPL"] = str(point["pool_impl"])
    if extra_env:
        env.update(extra_env)
    script = bench_script_path()
    r = subprocess.run(
        [sys.executable, script],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout_s,
    )
    label = point_label(point)
    if r.returncode != 0:
        raise RuntimeError(
            f"bench point [{label}] exited {r.returncode}: "
            f"{r.stderr.strip().splitlines()[-1] if r.stderr.strip() else ''}"
        )
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.strip()]
    if not lines:
        raise RuntimeError(f"bench point [{label}] produced no output")
    try:
        rec = json.loads(lines[-1])
    except json.JSONDecodeError as e:
        raise RuntimeError(
            f"bench point [{label}] emitted an unparsable line: {e}"
        ) from e
    rec["point"] = dict(point)
    return rec


def measured_step_seconds(rec: Dict[str, Any]) -> Optional[float]:
    """Wall seconds per dispatch implied by a bench line: batch tasks over
    global tasks/s (value is per *working* chip)."""
    value = rec.get("value")
    batch = rec.get("batch_size")
    chips = rec.get("n_chips") or 1
    if not value or not batch:
        return None
    return float(batch) / (float(value) * float(chips))


def cross_check_roofline(results: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Hold the measured ranking to the static roofline predictions the
    bench lines carry: per point, measured vs predicted step seconds (and
    their ratio); plus whether the measured winner is also the predicted
    winner. Informational — a disagreement means the static model misses
    something the hardware sees (or vice versa), which is exactly the
    point worth a human look before the table is trusted on a pod."""
    per_point = []
    for rec in results:
        roofline = rec.get("roofline") or {}
        predicted = roofline.get("predicted_step_seconds")
        measured = measured_step_seconds(rec)
        per_point.append({
            "label": point_label(rec["point"]),
            "measured_step_s": measured,
            "predicted_step_s": predicted,
            "measured_over_predicted": (
                round(measured / predicted, 3)
                if measured and predicted else None
            ),
        })
    by_measured = sorted(
        (r for r in results if r.get("value")),
        key=lambda r: -float(r["value"]),
    )
    with_pred = [
        r for r in results
        if (r.get("roofline") or {}).get("predicted_step_seconds")
    ]
    by_predicted = sorted(
        with_pred,
        key=lambda r: float(r["roofline"]["predicted_step_seconds"]),
    )
    agrees = None
    if by_measured and by_predicted:
        agrees = (
            point_label(by_measured[0]["point"])
            == point_label(by_predicted[0]["point"])
        )
    return {
        "points": per_point,
        "winner_agrees_with_roofline": agrees,
        "predicted_winner": (
            point_label(by_predicted[0]["point"]) if by_predicted else None
        ),
    }


def build_table(
    results: List[Dict[str, Any]],
    existing: Optional[dict] = None,
) -> dict:
    """Fold sweep results into a tuning table: per (device_kind, dtype)
    key, the measured-fastest point wins. MERGES with ``existing`` (same
    discipline as CONTRACTS.json pinning: a CPU smoke sweep must never
    clobber the TPU entry) — and a REDUCED sweep (the tiny-workload
    ``--fast`` smoke) never replaces a full-workload entry for the same
    key: the smoke proves the harness, the full measurement stays the
    tuning."""
    table: dict = {
        "version": TUNING_VERSION,
        "entries": dict((existing or {}).get("entries", {})),
    }
    best: Dict[str, Dict[str, Any]] = {}
    for rec in results:
        if not rec.get("value"):
            continue
        key = table_key(
            str(rec.get("device_kind", "")), str(rec.get("dtype", ""))
        )
        if key not in best or float(rec["value"]) > float(
            best[key]["value"]
        ):
            best[key] = rec
    for key, rec in best.items():
        prior = table["entries"].get(key)
        if (
            prior is not None
            and rec.get("reduced")
            and not prior.get("reduced")
        ):
            print(
                f"[autotune] keeping the existing full-workload entry for "
                f"{key}: this sweep ran the reduced workload",
                file=sys.stderr,
            )
            continue
        point = rec["point"]
        table["entries"][key] = {
            "conv_impl": point["conv_impl"],
            "pad_channels": point["pad_channels"],
            "remat_policy": point["remat_policy"],
            # the accum bench.py ACTUALLY measured: it clamps a sweep
            # point's accum to the largest batch divisor and reports the
            # clamped value in the emitted line
            "meta_accum_steps": int(
                rec.get("meta_accum_steps", point["meta_accum_steps"])
            ),
            "tasks_per_sec_per_chip": float(rec["value"]),
            "mfu": rec.get("mfu"),
            "backend": rec.get("backend"),
            "batch_size": rec.get("batch_size"),
            "reduced": rec.get("reduced"),
        }
        # the PR-16 diet axes, recorded when the point swept them (bench
        # echoes the RESOLVED value; pre-PR-16 result records have
        # neither and their entries stay knob-free, which validate
        # accepts)
        for knob in ("bn_stats_impl", "pool_impl"):
            val = rec.get(knob, point.get(knob))
            if val is not None:
                table["entries"][key][knob] = str(val)
    return table


def main(argv: Optional[List[str]] = None) -> int:
    """``cli tune`` — sweep, rank, cross-check, write the table."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="tune",
        description="Sweep (conv_impl x pad_channels x remat_policy x "
                    "meta_accum_steps x bn_stats_impl x pool_impl) with "
                    "bench.py, rank by measured step time cross-checked "
                    "against the static roofline, and write the "
                    "device-kind-keyed tuning table that config 'auto' "
                    "resolution consults",
    )
    parser.add_argument("--fast", action="store_true",
                        help="2-point smoke sweep on a tiny workload (the "
                             "CI gate; proves the harness, not the number)")
    parser.add_argument("--out", default=None,
                        help="tuning table path (default: TUNING.json at "
                             "the repo root, or $MAML_TUNING_TABLE)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON output")
    parser.add_argument("--timeout-s", type=float, default=1800.0,
                        help="per-point bench subprocess timeout")
    args = parser.parse_args(argv)

    out_path = args.out or default_table_path()
    points = sweep_points(fast=args.fast)
    results: List[Dict[str, Any]] = []
    failures: List[str] = []
    for i, point in enumerate(points):
        label = point_label(point)
        print(
            f"tune: [{i + 1}/{len(points)}] {label} ...",
            file=sys.stderr, flush=True,
        )
        try:
            rec = run_bench_point(
                point, fast=args.fast, timeout_s=args.timeout_s
            )
        except (RuntimeError, subprocess.TimeoutExpired) as e:
            # an OOM/unsupported point is a sweep RESULT (that config
            # doesn't fit this device), not a harness failure
            print(f"tune: point failed: {e}", file=sys.stderr, flush=True)
            failures.append(label)
            continue
        print(
            f"tune:   -> {rec.get('value')} tasks/s/chip "
            f"(mfu={rec.get('mfu')})",
            file=sys.stderr, flush=True,
        )
        results.append(rec)
    if not results:
        print("tune: every sweep point failed; no table written",
              file=sys.stderr)
        return 1
    check = cross_check_roofline(results)
    existing = load_tuning_table(out_path)
    table = build_table(results, existing=existing)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(table, f, indent=1, sort_keys=True)
    os.replace(tmp, out_path)
    clear_cache()
    ranked = sorted(results, key=lambda r: -float(r.get("value") or 0.0))
    if args.json:
        print(json.dumps({
            "table_path": out_path,
            "entries": table["entries"],
            "ranking": [
                {"label": point_label(r["point"]),
                 "tasks_per_sec_per_chip": r.get("value"),
                 "mfu": r.get("mfu")}
                for r in ranked
            ],
            "roofline_cross_check": check,
            "failed_points": failures,
        }, indent=2, sort_keys=True))
    else:
        print(f"tune: ranking ({len(results)} point(s)"
              + (f", {len(failures)} failed" if failures else "") + "):")
        for r in ranked:
            measured = measured_step_seconds(r)
            step = f"step={measured * 1e3:.1f}ms  " if measured else ""
            print(
                f"  {r.get('value'):>10} tasks/s/chip  {step}"
                f"[{point_label(r['point'])}]"
            )
        if check["winner_agrees_with_roofline"] is False:
            print(
                "tune: NOTE measured winner disagrees with the roofline-"
                f"predicted winner ({check['predicted_winner']}) — trust "
                "the measurement, but the static model missed something",
            )
        print(f"tune: wrote {out_path} "
              f"({len(table['entries'])} device entr"
              f"{'y' if len(table['entries']) == 1 else 'ies'})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
