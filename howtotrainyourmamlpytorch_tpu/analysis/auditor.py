"""ProgramAuditor: statically verify contracts on every jitted program.

The repo's performance story rests on invariants asserted nowhere at
runtime: whole-state donation, index-only H2D with zero mid-step
transfers, GEMM lowering with no grouped convs on the hot path, bf16/f32
dtype discipline, and zero mid-run retraces. The auditor turns them into
machine-checked contracts: given any jitted callable the system builds, it
traces (``jitted.trace``) and compiles (AOT — ``ShapeDtypeStruct`` args,
so auditing allocates nothing) and verifies each contract against the
jaxpr and the optimized HLO. See :mod:`analysis.contracts` for the
contract list and the pinned ``CONTRACTS.json`` baseline format.

Two entry points:

* ``audit_system_programs(cfg)`` — the canonical program family: the four
  train-step jits (plain / multi / indexed / multi-indexed, the same
  factories ``experiment/system.py`` jits with ``maml.TRAIN_DONATE``),
  the fused eval multi-step, the device-pipeline index expander, and the
  serving family (jitted with ``maml.SERVE_DONATE`` /
  ``maml.PREDICT_DONATE`` exactly like ``serving/engine.py`` — the
  donation contract is the state passthrough alias): the f32 and uint8
  multi-tenant serve steps plus the cache-hit predict-only step, whose
  pinned census is the machine-checked proof it carries NO inner-loop
  gradient ops. Driven by ``cli audit``, the builder's build-time audit
  (``analysis_level != 'off'``) and the contract tests.
* ``RetraceDetector`` — the runtime half: hashes the abstract signature
  (treedef + leaf shapes/dtypes) of every dispatch at its site; a second
  distinct signature at one site is a mid-run retrace (a new 20-40s TPU
  compile nothing should be paying) — reported via ``on_retrace`` (the
  builder emits a telemetry ``retrace`` record, schema v4) and fatal
  under ``analysis_level='strict'``.
"""

from __future__ import annotations

import hashlib
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MAMLConfig
from ..core import maml
from ..ops import device_pipeline
from . import contracts as C

#: jaxpr primitives that move data across the host<->device boundary (or
#: call back into the host) — none may appear inside a step program
TRANSFER_PRIMITIVES = frozenset({
    "device_put", "infeed", "outfeed", "pure_callback", "io_callback",
    "debug_callback", "callback", "host_callback_call", "copy_to_host",
})

#: f32-operand dot/conv ops with outputs at or below this element count are
#: tolerated under the bf16 policy: scalar-loss reductions (the MSL
#: weighting dot, cross-entropy means) legitimately run in f32 for
#: stability; anything bigger is real matmul compute leaking off the
#: bf16 MXU path (calibrated: the clean bf16 train step's largest f32 dot
#: output is 8 elements, the smallest genuine-compute dot is >200)
F32_MATMUL_OUTPUT_LIMIT = 64

_MATMUL_PRIMITIVES = ("dot_general", "conv_general_dilated")


def _iter_subjaxprs(params: Dict[str, Any]):
    """Jaxprs nested in an eqn's params (pjit/scan/cond/remat/custom_*)."""
    for value in params.values():
        items = value if isinstance(value, (tuple, list)) else (value,)
        for item in items:
            if hasattr(item, "jaxpr") and hasattr(item, "consts"):
                yield item.jaxpr  # ClosedJaxpr
            elif hasattr(item, "eqns"):
                yield item  # raw Jaxpr

def walk_jaxpr(jaxpr, visit: Callable[[Any], None]) -> None:
    """Depth-first visit of every eqn in ``jaxpr`` and its sub-jaxprs."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        for sub in _iter_subjaxprs(eqn.params):
            walk_jaxpr(sub, visit)


def _eqn_avals(eqn):
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "dtype"):
            yield aval


def tree_byte_size(tree) -> int:
    """Total bytes of a pytree of arrays / ShapeDtypeStructs."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            total += int(np.prod(leaf.shape, dtype=np.int64)) * int(
                np.dtype(leaf.dtype).itemsize
            )
    return total


class ProgramAuditor:
    """Verify the program contracts on jitted callables.

    ``baseline`` is a parsed ``CONTRACTS.json`` (or None: the op-census
    regression check degrades to the invariant constraints only);
    ``config_fingerprint`` must match the baseline's for the census
    compare to arm (see ``contracts.baseline_comparable``).
    """

    def __init__(
        self,
        cfg: MAMLConfig,
        baseline: Optional[dict] = None,
        config_fingerprint: str = "",
    ):
        self.cfg = cfg
        self.baseline = baseline
        self._census_armed = C.baseline_comparable(
            baseline,
            jax_version=jax.__version__,
            config_fingerprint=config_fingerprint,
        )

    # -- the audit ---------------------------------------------------------

    def audit(
        self,
        program: str,
        jitted,
        args: Sequence[Any],
        donate: Tuple[int, ...] = (),
        expect_no_grouped_conv: Optional[bool] = None,
    ) -> C.AuditReport:
        """Trace + compile ``jitted(*args)`` and check every contract.

        ``args`` may be ``ShapeDtypeStruct`` trees — the audit is fully
        abstract and allocates nothing. ``donate`` declares which argnums
        the *system* donates (the jit must have been built with matching
        ``donate_argnums``; the donation contract checks the executable
        actually honors it). ``expect_no_grouped_conv`` overrides the
        config-derived arming of the grouped-conv census constraint
        (tests use it to point the contract at a deliberately grouped
        lowering).
        """
        violations: List[C.ContractViolation] = []

        def flag(contract: str, detail: str) -> None:
            violations.append(C.ContractViolation(contract, program, detail))

        # any "donated buffers were not usable" diagnostic jax emits while
        # tracing/compiling is a donation-contract failure in its own right
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            traced = jitted.trace(*args)
            self._check_jaxpr(program, traced.jaxpr.jaxpr, flag)
            compiled = traced.lower().compile()
        for w in caught:
            msg = str(w.message)
            if "donated" in msg.lower():
                flag("donation", f"compiler diagnostic: {msg}")

        hlo_text = compiled.as_text()
        census = C.interesting_census(hlo_text)
        donation = None
        if donate:
            donation = C.donation_stats(compiled, donate)
            state_bytes = sum(tree_byte_size(args[i]) for i in donate)
            alias = donation.get("alias_size_bytes")
            if alias is None:
                flag("donation", "memory_analysis unavailable on this "
                                 "backend; donation unverifiable")
            elif alias < state_bytes:
                flag(
                    "donation",
                    f"executable aliases {alias} bytes but the donated "
                    f"argument(s) hold {state_bytes} bytes — the state is "
                    "double-buffered (donate_argnums missing or unusable)",
                )
        self._check_hlo(program, hlo_text, census, flag,
                        expect_no_grouped_conv)
        return C.AuditReport(
            program=program,
            backend=jax.default_backend(),
            contracts_checked=C.CONTRACT_NAMES,
            violations=violations,
            census=census,
            donation=donation,
        )

    def _check_jaxpr(self, program: str, jaxpr, flag) -> None:
        bf16 = self.cfg.compute_dtype == "bfloat16"
        transfer_hits: Dict[str, int] = {}
        f64_prims: Dict[str, int] = {}
        f32_matmuls: List[str] = []

        def visit(eqn):
            name = eqn.primitive.name
            if name in TRANSFER_PRIMITIVES:
                transfer_hits[name] = transfer_hits.get(name, 0) + 1
            for aval in _eqn_avals(eqn):
                if str(aval.dtype) == "float64":
                    f64_prims[name] = f64_prims.get(name, 0) + 1
                    break
            if bf16 and name in _MATMUL_PRIMITIVES:
                in_dtypes = [
                    str(v.aval.dtype)
                    for v in eqn.invars
                    if hasattr(getattr(v, "aval", None), "dtype")
                ]
                out = eqn.outvars[0].aval
                out_size = int(np.prod(out.shape, dtype=np.int64)) if (
                    out.shape
                ) else 1
                if "float32" in in_dtypes and (
                    out_size > F32_MATMUL_OUTPUT_LIMIT
                ):
                    f32_matmuls.append(
                        f"{name} with f32 operands -> {out.shape}"
                    )

        walk_jaxpr(jaxpr, visit)
        if transfer_hits:
            flag(
                "no_transfer",
                "host<->device primitives inside the program: "
                + ", ".join(f"{k} x{v}" for k, v in sorted(
                    transfer_hits.items())),
            )
        if f64_prims:
            flag(
                "dtype_policy",
                "float64 values in the program (x64 creep): "
                + ", ".join(f"{k} x{v}" for k, v in sorted(f64_prims.items())),
            )
        if f32_matmuls:
            flag(
                "dtype_policy",
                f"f32 matmul compute under compute_dtype='bfloat16' "
                f"(unintended upcast): {'; '.join(f32_matmuls[:4])}"
                + (f" (+{len(f32_matmuls) - 4} more)"
                   if len(f32_matmuls) > 4 else ""),
            )

    def _check_hlo(self, program: str, hlo_text: str,
                   census: Dict[str, int], flag,
                   expect_no_grouped_conv: Optional[bool]) -> None:
        transfers = C.host_transfer_ops(hlo_text)
        if transfers:
            flag(
                "no_transfer",
                "host-transfer opcodes in the optimized HLO: "
                + ", ".join(f"{k} x{v}" for k, v in sorted(transfers.items())),
            )
        n_f64 = C.f64_shape_count(hlo_text)
        if n_f64:
            flag("dtype_policy",
                 f"f64 shapes in the optimized HLO ({n_f64} occurrences)")
        if expect_no_grouped_conv is None:
            expect_no_grouped_conv = (
                self.cfg.resolved_conv_impl == "gemm"
                and self.cfg.task_axis_mode == "vmap"
            )
        if expect_no_grouped_conv:
            grouped = C.grouped_conv_count(hlo_text)
            if grouped:
                flag(
                    "op_census",
                    f"{grouped} grouped convolution(s) "
                    "(feature_group_count>1) in a GEMM-lowered program — "
                    "the conv path fell off the batched-GEMM lowering",
                )
        if self._census_armed:
            key = C.census_key(program, jax.default_backend())
            pinned = (self.baseline or {}).get("programs", {}).get(key)
            if pinned is not None:
                regressions = C.compare_census(census, pinned.get("census", {}))
                if regressions:
                    flag(
                        "op_census",
                        "census regression vs pinned baseline: "
                        + ", ".join(regressions),
                    )


# -- the canonical program family --------------------------------------------


def _sds(shape, dtype) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def _batch_avals(cfg: MAMLConfig, k: int = 0):
    """ShapeDtypeStructs of one (or k stacked) pixel task batch(es)."""
    b, n = cfg.batch_size, cfg.num_classes_per_set
    s, t = cfg.num_samples_per_class, cfg.num_target_samples
    h, w, c = cfg.im_shape
    lead = (k,) if k else ()
    return (
        _sds(lead + (b, n, s, h, w, c), jnp.float32),
        _sds(lead + (b, n, s), jnp.int32),
        _sds(lead + (b, n, t, h, w, c), jnp.float32),
        _sds(lead + (b, n, t), jnp.int32),
    )


def _index_avals(cfg: MAMLConfig, k: int = 0, store_images: int = 64):
    """ShapeDtypeStructs of the resident store + one (or k) index batches."""
    b, n = cfg.batch_size, cfg.num_classes_per_set
    per = cfg.num_samples_per_class + cfg.num_target_samples
    h, w, c = cfg.im_shape
    lead = (k,) if k else ()
    store = _sds((store_images, h, w, c), jnp.uint8)
    gather = _sds(lead + (b, n, per), jnp.int32)
    rot_k = _sds(lead + (b, n), jnp.int32)
    return store, gather, rot_k


def _state_avals(cfg: MAMLConfig):
    """The MetaState as ShapeDtypeStructs — ``eval_shape`` over init, so
    the audit never allocates a real state."""
    return jax.eval_shape(lambda: maml.init_state(cfg))


def _batch_avals_uint8(cfg: MAMLConfig):
    """The uint8-ingest serve batch: raw pixel dtype, same geometry."""
    x_s, y_s, x_t, y_t = _batch_avals(cfg)
    return (
        _sds(x_s.shape, jnp.uint8), y_s, _sds(x_t.shape, jnp.uint8), y_t
    )


def _fast_avals(cfg: MAMLConfig, bucket: int):
    """Per-tenant adapted fast weights as (bucket, ...) ShapeDtypeStructs
    (the predict-only program's cached-params argument)."""
    from ..core import partition

    state = _state_avals(cfg)
    adapted, _ = partition.split_inner(cfg, state.net)
    return {
        k: _sds((bucket,) + tuple(v.shape), v.dtype)
        for k, v in adapted.items()
    }


def audit_system_programs(
    cfg: MAMLConfig,
    auditor: Optional[ProgramAuditor] = None,
    second_order: Optional[bool] = None,
    k: int = 2,
    programs: Optional[Sequence[str]] = None,
) -> List[C.AuditReport]:
    """Audit the canonical program family the system builds.

    Returns one ``AuditReport`` per program: the four train-step jits
    (each built with ``maml.TRAIN_DONATE`` exactly like
    ``experiment/system.py``), the fused eval multi-step, the
    device-pipeline index expander, and the serving family — the f32 and
    uint8 multi-tenant serve steps plus the cache-hit predict-only step
    (built with ``maml.SERVE_DONATE`` / ``maml.PREDICT_DONATE`` exactly
    like ``serving/engine.py``; audited at the config's batch_size as
    their tenant bucket). ``k`` is the fused-dispatch chunk used for the
    multi variants; ``programs`` filters by name.
    """
    auditor = auditor or ProgramAuditor(cfg)
    so = cfg.second_order if second_order is None else bool(second_order)
    state = _state_avals(cfg)
    weights = _sds((cfg.number_of_training_steps_per_iter,), jnp.float32)
    lr = _sds((), jnp.float32)
    batch = _batch_avals(cfg)
    batch_k = _batch_avals(cfg, k)
    store, gather, rot_k = _index_avals(cfg)
    _, gather_k, rot_k_k = _index_avals(cfg, k)
    so_tag = int(so)

    specs: List[Tuple[str, Any, tuple, tuple]] = [
        (
            f"train_step[so={so_tag}]",
            jax.jit(maml.make_train_step(cfg, so),
                    donate_argnums=maml.TRAIN_DONATE),
            (state, *batch, weights, lr),
            maml.TRAIN_DONATE,
        ),
        (
            f"train_multi_step[so={so_tag},k={k}]",
            jax.jit(maml.make_train_multi_step(cfg, so),
                    donate_argnums=maml.TRAIN_DONATE),
            (state, *batch_k, weights, lr),
            maml.TRAIN_DONATE,
        ),
        (
            f"train_step_indexed[so={so_tag}]",
            jax.jit(maml.make_train_step_indexed(cfg, so, augment=False),
                    donate_argnums=maml.TRAIN_DONATE),
            (state, store, gather, rot_k, weights, lr),
            maml.TRAIN_DONATE,
        ),
        (
            f"train_multi_step_indexed[so={so_tag},k={k}]",
            jax.jit(maml.make_train_multi_step_indexed(cfg, so,
                                                       augment=False),
                    donate_argnums=maml.TRAIN_DONATE),
            (state, store, gather_k, rot_k_k, weights, lr),
            maml.TRAIN_DONATE,
        ),
        (
            f"eval_multi_step[k={k}]",
            jax.jit(maml.make_eval_multi_step(cfg, with_preds=False)),
            (state, *batch_k),
            (),
        ),
        (
            "index_expander",
            jax.jit(device_pipeline.make_index_expander(cfg, augment=False)),
            (store, gather, rot_k),
            (),
        ),
        (
            f"serve_step[b={cfg.batch_size}]",
            jax.jit(maml.make_serve_step(cfg),
                    donate_argnums=maml.SERVE_DONATE),
            (state, *batch, _sds((cfg.batch_size,), jnp.float32)),
            maml.SERVE_DONATE,
        ),
        (
            f"serve_step_uint8[b={cfg.batch_size}]",
            jax.jit(maml.make_serve_step(cfg, ingest="uint8"),
                    donate_argnums=maml.SERVE_DONATE),
            (state, *_batch_avals_uint8(cfg),
             _sds((cfg.batch_size,), jnp.float32)),
            maml.SERVE_DONATE,
        ),
        (
            f"predict_step[b={cfg.batch_size}]",
            jax.jit(maml.make_predict_step(cfg),
                    donate_argnums=maml.PREDICT_DONATE),
            (state, _fast_avals(cfg, cfg.batch_size),
             _sds((cfg.batch_size, cfg.num_classes_per_set,
                   cfg.num_target_samples, *cfg.im_shape), jnp.float32),
             _sds((cfg.batch_size, cfg.num_classes_per_set,
                   cfg.num_target_samples), jnp.int32),
             _sds((cfg.batch_size,), jnp.float32)),
            maml.PREDICT_DONATE,
        ),
    ]
    reports = []
    for name, jitted, args, donate in specs:
        if programs is not None and name not in programs:
            continue
        reports.append(auditor.audit(name, jitted, args, donate=donate))
    return reports


#: the four donating train-step program-name prefixes (tests key off these)
TRAIN_STEP_PROGRAMS = (
    "train_step[", "train_multi_step[", "train_step_indexed[",
    "train_multi_step_indexed[",
)


# -- runtime retrace detection -----------------------------------------------


class RetraceError(RuntimeError):
    """A dispatch site changed its abstract signature mid-run
    (``analysis_level='strict'``)."""


class RetraceDetector:
    """Watch abstract dispatch signatures; flag mid-run retraces.

    A *site* is one logical jitted program including its static variant
    keys (e.g. ``train_multi_step[so=1,k=4]``); within a site, every
    distinct abstract signature (pytree structure + leaf shapes/dtypes)
    is a separate XLA compile. The first signature per site is the
    expected compile; any later NEW signature is a retrace — 20-40s of
    TPU compile mid-run that the shape discipline should have prevented.

    ``observe`` costs one ``tree_flatten`` plus a tuple hash per dispatch
    when installed; ``analysis_level='off'`` installs nothing and the
    dispatch path pays a single attribute check (same discipline as
    ``resilience.faults``).
    """

    def __init__(
        self,
        on_retrace: Optional[Callable[..., None]] = None,
        strict: bool = False,
    ):
        self.on_retrace = on_retrace
        self.strict = strict
        self._sigs: Dict[str, set] = {}
        self.events: List[Dict[str, Any]] = []

    @staticmethod
    def _abstract_key(tree) -> Tuple:
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        descr = tuple(
            (tuple(leaf.shape), str(leaf.dtype))
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
            else ("py", type(leaf).__name__)
            for leaf in leaves
        )
        return (treedef, descr)

    @staticmethod
    def signature_digest(key: Tuple) -> str:
        blob = "|".join(str(part) for part in key[1]) + str(key[0])
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    @property
    def retrace_count(self) -> int:
        return len(self.events)

    def observe(self, site: str, tree) -> bool:
        """Record one dispatch; returns True (and reports) on a retrace."""
        key = self._abstract_key(tree)
        seen = self._sigs.setdefault(site, set())
        if key in seen:
            return False
        first = not seen
        seen.add(key)
        if first:
            return False
        event = {
            "site": site,
            "signature": self.signature_digest(key),
            "n_signatures": len(seen),
        }
        self.events.append(event)
        if self.on_retrace is not None:
            self.on_retrace(**event)
        if self.strict:
            raise RetraceError(
                f"dispatch site {site!r} retraced mid-run: signature "
                f"{event['signature']} is its {len(seen)}th distinct "
                "abstract signature (analysis_level='strict')"
            )
        return True
