"""Parameter partitioning: inner-loop-adapted vs frozen, trainable vs not.

Replaces the reference's name-string filtering
(``get_inner_loop_parameter_dict`` few_shot_learning_system.py:105-120: all
``requires_grad`` params except those whose name contains ``norm_layer``) and
its ``requires_grad`` bookkeeping scattered across module definitions
(meta_neural_network_architectures.py:177-198,279) with two pure predicates
over flat parameter names.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp

from ..config import MAMLConfig

Params = Dict[str, jnp.ndarray]


def is_norm_param(name: str) -> bool:
    return ".norm." in name


def is_inner_adapted(cfg: MAMLConfig, name: str) -> bool:
    """Whether a parameter is updated by the inner loop.

    Reference: norm params are excluded unless
    ``enable_inner_loop_optimizable_bn_params``
    (few_shot_learning_system.py:115-119). Layer-norm gamma is frozen
    (requires_grad=False, meta_...py:279) so it is never adapted even with the
    enable flag — the reference's inner dict filters on requires_grad.
    """
    if not is_norm_param(name):
        return True
    if not cfg.enable_inner_loop_optimizable_bn_params:
        return False
    if cfg.norm_layer == "layer_norm" and name.endswith(".gamma"):
        return False
    return True


def is_trainable(cfg: MAMLConfig, name: str) -> bool:
    """Whether the outer (Adam) optimizer updates a parameter.

    Mirrors the reference's requires_grad flags: BN gamma/beta trainability
    from ``learnable_bn_gamma``/``learnable_bn_beta`` (meta_...py:182-192);
    layer-norm gamma frozen (:279); conv/linear always trainable.
    """
    if not is_norm_param(name):
        return True
    if name.endswith(".gamma"):
        if cfg.norm_layer == "layer_norm":
            return False
        return cfg.learnable_bn_gamma
    if name.endswith(".beta"):
        if cfg.norm_layer == "layer_norm":
            return True
        return cfg.learnable_bn_beta
    return True


def split_inner(cfg: MAMLConfig, params: Params) -> Tuple[Params, Params]:
    """Partition net params into (adapted, frozen) flat dicts."""
    adapted = {k: v for k, v in params.items() if is_inner_adapted(cfg, k)}
    frozen = {k: v for k, v in params.items() if not is_inner_adapted(cfg, k)}
    return adapted, frozen


def trainable_labels(cfg: MAMLConfig, params: Params) -> Dict[str, str]:
    """'train'/'freeze' labels for optax.multi_transform over net params."""
    return {
        k: ("train" if is_trainable(cfg, k) else "freeze") for k in params
    }
