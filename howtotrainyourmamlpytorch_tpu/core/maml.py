"""The bi-level (MAML / MAML++) optimization core, TPU-native.

Re-architecture of the reference's ``MAMLFewShotClassifier``
(few_shot_learning_system.py:26-424). The reference runs a Python loop over
tasks, each with a Python loop over inner steps calling
``torch.autograd.grad(create_graph=...)`` (few_shot_learning_system.py:
193-244,138-139). Here the whole outer step is ONE jit-compiled pure
function:

* inner loop   -> ``lax.scan`` over steps with ``jax.grad`` inside; second
  order falls out of differentiating through the scan, first order is a
  ``stop_gradient`` on the inner grads (ref's ``create_graph`` switch);
* task loop    -> ``jax.vmap`` over the meta-batch (tasks are independent);
* devices      -> the task axis is sharded over a ``jax.sharding.Mesh``; XLA
  inserts the gradient ``psum`` over ICI (replaces ``nn.DataParallel``'s
  scatter/gather and the reference's device-dim repeat/squeeze hack,
  few_shot_learning_system.py:142-158,201-206);
* MSL          -> the per-step target losses are weighted by a host-computed
  vector (one-hot on the last step when MSL is inactive), making the MSL and
  plain branches (few_shot_learning_system.py:232-244) one code path;
* memory       -> ``jax.checkpoint`` on the inner step bounds the memory of
  differentiating through the unrolled inner loop (the reference instead pays
  for the full retained autograd graph).

Outer optimizer: Adam + cosine annealing, matching ``optim.Adam`` +
``CosineAnnealingLR`` (few_shot_learning_system.py:69-71); the elementwise
±10 gradient clamp for imagenet datasets (:332-335) is applied to the network
gradients only (LSLR LRs are NOT clamped — the reference iterates
``self.classifier.named_parameters()``).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import MAMLConfig
from ..models import vgg
from ..ops import device_pipeline
from ..ops import functional as F
from . import lslr as lslr_lib
from . import msl as msl_lib
from . import partition


class MetaState(NamedTuple):
    """The full, checkpointable training state — an ordinary pytree.

    The reference's equivalent is the module state_dict + Adam state
    (few_shot_learning_system.py:399-408).
    """

    net: Dict[str, jnp.ndarray]
    lslr: Dict[str, jnp.ndarray]
    bn: Dict[str, jnp.ndarray]
    opt: Any


# -- buffer-donation contract ------------------------------------------------
#
# Every train step consumes a MetaState and returns the next one; without
# donation XLA must double-buffer params + LSLR + BN + Adam moments in HBM on
# every dispatch. ``TRAIN_DONATE`` is the single source of truth for the
# donated argnums of every ``make_train_step*`` variant (plain, multi,
# indexed, multi-indexed) — used by experiment/system.py and bench.py:
# the state (argnum 0) aliases in place onto the returned state (identical
# pytree of shapes), halving the steady-state HBM footprint of
# params+LSLR+BN+Adam. The caller must re-bind its reference to the returned
# state (the system facade does) and never touch the donated one again;
# checkpointing stays safe because ``save_checkpoint_async`` finishes the
# device->host copy before returning (experiment/checkpoint.py), and the
# indexed variants never donate argnum 1 — the resident uint8 store is a
# registry-owned invariant reused by every subsequent dispatch.
#
# Eval deliberately donates NOTHING: the state is not legal to donate (eval
# returns no replacement and the caller keeps dispatching the same state),
# and donating the placed pixel/index batches is not usable — no output
# shares their shape, so XLA cannot alias them, jax warns, and the buffers
# are not even freed early (measured on the CPU backend; tested in
# tests/test_donation.py).
TRAIN_DONATE = (0,)


def cosine_lr(cfg: MAMLConfig, epoch: int) -> float:
    """CosineAnnealingLR closed form, stepped per-iteration with the integer
    epoch index exactly like the reference (few_shot_learning_system.py:70-71,
    345-346): eta_min + (lr0 - eta_min) * (1 + cos(pi * epoch / T_max)) / 2.
    """
    return cfg.min_learning_rate + 0.5 * (
        cfg.meta_learning_rate - cfg.min_learning_rate
    ) * (1.0 + math.cos(math.pi * epoch / cfg.total_epochs))


def init_state(cfg: MAMLConfig, seed: Optional[int] = None) -> MetaState:
    """Build params, LSLR, BN state, and Adam state.

    Seed discipline mirrors ``set_torch_seed`` (few_shot_learning_system.py:
    13-23): the model seed is drawn from RandomState(cfg.seed).
    """
    rng = np.random.RandomState(cfg.seed if seed is None else seed)
    jax_seed = int(rng.randint(0, 999999))
    params, bn_state = vgg.init(cfg, jax.random.PRNGKey(jax_seed))
    adapted, _ = partition.split_inner(cfg, params)
    lslr_params = lslr_lib.init(
        sorted(adapted.keys()),
        cfg.number_of_training_steps_per_iter,
        cfg.inner_lr_init,
    )
    opt = make_optimizer(cfg, params)
    opt_state = opt.init({"net": params, "lslr": lslr_params})
    return MetaState(net=params, lslr=lslr_params, bn=bn_state, opt=opt_state)


def make_optimizer(cfg: MAMLConfig, params: Dict[str, jnp.ndarray]):
    """Adam over {net, lslr} with frozen leaves zeroed.

    torch defaults: betas (0.9, 0.999), eps 1e-8, amsgrad False
    (few_shot_learning_system.py:69). The LR is applied separately each step
    (cosine schedule of the epoch index), so the transform here produces the
    raw Adam direction.
    """
    labels = {
        "net": partition.trainable_labels(cfg, params),
        "lslr": {
            k: (
                "train"
                if cfg.learnable_per_layer_per_step_inner_loop_learning_rate
                and cfg.inner_loop_optimizer != "sgd"
                else "freeze"
            )
            for k in sorted(partition.split_inner(cfg, params)[0].keys())
        },
    }
    return optax.multi_transform(
        {
            "train": optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8),
            "freeze": optax.set_to_zero(),
        },
        labels,
    )


def _task_learner(
    cfg: MAMLConfig, num_steps: int, second_order: bool, collect: bool = False,
    return_adapted: bool = False,
):
    """Per-task bi-level loss: the reference's per-task body
    (few_shot_learning_system.py:197-252) as a pure function.

    Returns (task_loss, (per_sample_correct, new_bn_state, final_softmax,
    dynamics)). ``collect`` (``telemetry_level='dynamics'``) additionally
    stacks per-inner-step support/target losses and per-layer inner-grad
    L2 norms into ``dynamics`` — computed inside the existing scan from
    values the step already has (the support gradient is reused, the loss
    value rides along via value_and_grad), all under ``stop_gradient`` so
    the meta-gradient graph is untouched; ``collect=False`` traces the
    exact pre-telemetry program (``dynamics`` is then an empty pytree).

    ``return_adapted`` (the serving adapted-params cache) appends the
    post-adaptation fast weights — the scan's final ``theta`` carry, the
    exact dict the last target forward consumed — as a fifth aux element.
    The forward math is untouched: ``theta_f`` is already computed as the
    scan carry; returning it only keeps it from being DCE'd.
    """

    def inner_step(
        frozen, lslr_params, x_s, y_s, x_t, y_t, p_s, p_t, carry, step
    ):
        theta, bn_st = carry

        def support_loss_fn(th):
            logits, new_bn = vgg.apply(
                cfg, {**frozen, **th}, bn_st, x_s, step, training=True,
                x_patches=p_s,
            )
            return F.cross_entropy(logits, y_s), new_bn

        if collect:
            (s_loss, new_bn), grads = jax.value_and_grad(
                support_loss_fn, has_aux=True
            )(theta)
        else:
            grads, new_bn = jax.grad(support_loss_fn, has_aux=True)(theta)
        extras = {}
        if collect:
            extras = {
                "support_losses": jax.lax.stop_gradient(s_loss),
                "grad_norms": {
                    k: jax.lax.stop_gradient(
                        jnp.sqrt(jnp.sum(jnp.square(g))).astype(jnp.float32)
                    )
                    for k, g in grads.items()
                },
            }
        if not second_order:
            # first-order MAML: cut the graph through the inner gradient
            # (ref: create_graph=False, few_shot_learning_system.py:138)
            grads = jax.tree_util.tree_map(jax.lax.stop_gradient, grads)
        if cfg.inner_loop_optimizer == "sgd":
            # plain fixed-LR rule (inner_loop_optimizers.py:39-52)
            theta = lslr_lib.sgd_update_params(theta, grads, cfg.inner_lr_init)
        else:
            theta = lslr_lib.update_params(theta, grads, lslr_params, step)
        # target loss with the *updated* weights at BN index `step`
        # (few_shot_learning_system.py:233-244)
        t_logits, new_bn = vgg.apply(
            cfg, {**frozen, **theta}, new_bn, x_t, step, training=True,
            x_patches=p_t,
        )
        t_loss = F.cross_entropy(t_logits, y_t)
        return (theta, new_bn), (t_loss, t_logits, extras)

    def task_loss(net, lslr_params, bn_state, x_s, y_s, x_t, y_t, loss_weights):
        # flatten (n, s, h, w, c) sets to (n*s, h, w, c)
        # (few_shot_learning_system.py:208-213)
        x_s = x_s.reshape((-1,) + x_s.shape[-3:])
        x_t = x_t.reshape((-1,) + x_t.shape[-3:])
        y_s = y_s.reshape(-1)
        y_t = y_t.reshape(-1)
        adapted, frozen = partition.split_inner(cfg, net)
        # invariant im2col hoisting (cfg.im2col_hoist): the support/target
        # images are loop constants, so layer 1's patch extraction — the
        # im2col over the largest spatial tensor — is computed ONCE here,
        # outside the checkpointed scan body, and threaded in as a scan
        # invariant (the same discipline as the resident FlatStore).  The
        # hoisted tensors are bitwise the values the inline extraction
        # would produce (pure data movement — models.vgg.layer1_patches),
        # and as step_fn inputs they are saved residuals: the remat
        # backward re-extracts nothing either.  None (hoist off or
        # inapplicable) keeps the self-contained per-step program.
        p_s = vgg.layer1_patches(cfg, x_s)
        p_t = vgg.layer1_patches(cfg, x_t)
        step_fn = partial(
            inner_step, frozen, lslr_params, x_s, y_s, x_t, y_t, p_s, p_t
        )
        if cfg.use_remat:
            if cfg.remat_policy == "save_conv":
                # keep the conv outputs (named in ops.functional.conv2d),
                # recompute only the cheap elementwise tail — less MXU
                # recompute at some memory cost
                step_fn = jax.checkpoint(
                    step_fn,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        "conv_out"
                    ),
                )
            else:
                step_fn = jax.checkpoint(step_fn)
        # fully unroll the (short: 3-5) inner loop: the step indices become
        # literals, so per-step BN gathers/updates lower to static slices
        # XLA can fuse instead of dynamic-update-slice machinery — a large
        # constant-factor win on CPU, neutral-to-positive on TPU (compile
        # time stays bounded because num_steps is small)
        (theta_f, bn_f), (t_losses, t_logits, extras) = jax.lax.scan(
            step_fn,
            (adapted, bn_state),
            jnp.arange(num_steps),
            unroll=True if num_steps <= 8 else 1,
        )
        loss = jnp.dot(loss_weights.astype(t_losses.dtype), t_losses)
        final_logits = t_logits[-1]
        correct = F.accuracy(final_logits, y_t)
        dynamics = {}
        if collect:
            # (num_steps,) stacks per key; target losses are the MSL inputs
            dynamics = {
                **extras,
                "target_losses": jax.lax.stop_gradient(t_losses),
            }
        aux = (
            correct, bn_f, jax.nn.softmax(final_logits, axis=-1), dynamics
        )
        if return_adapted:
            aux = aux + (theta_f,)
        return loss, aux

    return task_loss


def _merge_bn(bn_batched: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Merge per-task BN running stats into one state.

    The reference mutates shared stats sequentially across tasks (last task
    wins, meta_...py:246-247 under the task loop); under vmap tasks are
    independent, so we take the mean over the task axis — deterministic and
    order-independent (documented deviation; running stats never normalize
    anything, see ops.functional.batch_norm).
    """
    return jax.tree_util.tree_map(lambda v: jnp.mean(v, axis=0), bn_batched)


def _map_tasks(learner_call, mode, x_s, y_s, x_t, y_t):
    """Run the per-task learner over the task axis.

    'vmap' (default): one batched program. After inner step 1 every task
    carries its own adapted weights, so each conv is a batched-*weights*
    conv — under ``conv_impl='lax'`` that lowers to a
    ``feature_group_count=tasks`` grouped conv NO backend runs near peak
    (XLA:CPU an order of magnitude below; the TPU grouped-conv path far off
    the MXU's large-GEMM rate), which is why ``resolved_conv_impl`` picks
    the 'gemm' lowering on accelerators: the batching rule then folds every
    layer into ONE (task, N*Ho*Wo, K) x (task, K, cout) batched GEMM at
    every derivative order (ops.functional.conv2d). 'map' (lax.map = scan):
    sequential per-task execution with ordinary shared-weight convs — the
    right choice on single-core CPU hosts (measured 5-10x faster at 64
    filters), numerically equivalent.
    """
    if mode == "map":
        return jax.lax.map(lambda a: learner_call(*a), (x_s, y_s, x_t, y_t))
    return jax.vmap(learner_call)(x_s, y_s, x_t, y_t)


def _split_microbatches(accum: int, *batches):
    """Reshape each batch's leading task axis b -> (accum, b // accum)."""
    out = []
    for a in batches:
        b = a.shape[0]
        if b % accum != 0:
            raise ValueError(
                f"meta_accum_steps={accum} must divide the task batch "
                f"({b} tasks)"
            )
        out.append(a.reshape((accum, b // accum) + a.shape[1:]))
    return tuple(out)


def _meta_loss_and_grads(
    learner, state, x_s, y_s, x_t, y_t, loss_weights, task_mode="vmap",
    accum=1,
):
    """Outer loss + meta-gradients over the task batch.

    The meta-gradient is computed PER TASK (``value_and_grad`` of the
    per-task loss, mapped over the task axis) and reduced once with an
    explicit ``mean`` over the full task axis — mathematically identical
    to differentiating the task-mean loss (the backward seeds distribute
    over the mean), and the form that makes ``meta_accum_steps`` exact:

    ``accum > 1`` scans the task axis in ``accum`` microbatches of
    ``b / accum`` tasks inside the same program, stacking each
    microbatch's per-task grads/losses/aux, then applies THE SAME final
    reductions over the re-flattened (b, ...) stacks. Per-task values are
    independent of the vmap width (each task's math is its own
    GEMM/elementwise chain), so at matched total batch the accumulated
    step is bit-exact (f32) with the monolithic one — while the
    activation peak of differentiating through the inner loop shrinks
    ~accum-fold (per-task grads are params-sized and cheap to stack; the
    unrolled-inner-loop activations are what dominate HBM). Accumulation
    stays in f32: per-task meta-grads are f32 (grads of the f32 master
    params) on both the f32 and bf16 compute paths.

    Three mechanisms make the exactness hold in practice (each measured
    to drift by ~1 grad ulp without it):

    * the ``optimization_barrier`` before the final reductions — without
      it XLA fuses the cross-task mean into the monolithic backward,
      reassociating the sum the scanned program materializes;
    * the microbatch loop is FULLY UNROLLED (``lax.scan(..., unroll=
      True)``) — a rolled loop body is compiled as its own computation
      whose fusion choices differ from straight-line code, perturbing
      per-task values themselves; unrolled, every microbatch lowers
      exactly like the monolithic program (compile time grows ~linearly
      with ``accum``, same discipline as the unrolled inner loop);
    * each microbatch's inputs are gated on the previous microbatch's
      losses through an ``optimization_barrier`` token — WITH the loop
      unrolled the microbatches would otherwise be dataflow-independent
      and XLA could schedule them concurrently, silently restoring the
      monolithic activation peak; the token serializes them in dataflow
      terms (statically visible: ``memory_analysis`` temp bytes drop
      ~accum-fold, tested).

    Cost of the barrier: one b x params-sized HBM round-trip per step —
    noise next to the inner-loop activations. The caveats the tests pin:
    the structural mechanisms above remove every GRAPH-level divergence,
    but XLA's per-task codegen itself can still reassociate *within-task*
    reductions when the vmap width crosses a hardware vectorization
    boundary (measured on XLA:CPU/AVX-512: widths 2..12 agree bit-for-bit
    at the test geometries, width 16 and width 1 drift by ~1 ulp) — keep
    microbatch widths moderate (``2 <= b/accum``, and on CPU below the
    16-lane boundary; the flagship's batch-12/accum-{2,4} sits squarely
    in the verified envelope). bf16 compute remains ULP-bounded, not
    bit-exact (the bf16 MXU passes reassociate internally).
    """
    trainable = {"net": state.net, "lslr": state.lslr}

    def per_task(xs, ys, xt, yt):
        def task_loss(tr):
            return learner(
                tr["net"], tr["lslr"], state.bn, xs, ys, xt, yt,
                loss_weights,
            )

        (loss, aux), task_grads = jax.value_and_grad(
            task_loss, has_aux=True
        )(trainable)
        return loss, aux, task_grads

    if accum > 1:
        micro = _split_microbatches(accum, x_s, y_s, x_t, y_t)

        def body(token, mb):
            *mb_gated, token = jax.lax.optimization_barrier((*mb, token))
            out = _map_tasks(per_task, task_mode, *mb_gated)
            return out[0], out  # next token: this microbatch's losses

        token0 = jnp.zeros((x_s.shape[0] // accum,), jnp.float32)
        _, stacked = jax.lax.scan(body, token0, micro, unroll=True)
        # flatten (accum, b/accum, ...) -> (b, ...): same per-task value
        # stream as the monolithic program, reduced identically below
        losses, (correct, bns, preds, dyn), grads = jax.tree_util.tree_map(
            lambda v: v.reshape((-1,) + v.shape[2:]), stacked
        )
    else:
        losses, (correct, bns, preds, dyn), grads = _map_tasks(
            per_task, task_mode, x_s, y_s, x_t, y_t
        )
    del preds  # train never consumes the softmax stacks: stay DCE-able
    # (deliberately OUTSIDE the barrier — a barrier would force XLA to
    # compute them every step)
    losses, correct, bns, dyn, grads = jax.lax.optimization_barrier(
        (losses, correct, bns, dyn, grads)
    )
    # mean over tasks (few_shot_learning_system.py:164) — loss and grads
    # reduce over the same full task axis in both branches
    loss = jnp.mean(losses)
    grads = jax.tree_util.tree_map(lambda g: jnp.mean(g, axis=0), grads)
    return trainable, loss, correct, bns, grads, dyn


def make_grads_fn(cfg: MAMLConfig, second_order: bool):
    """The meta-gradient computation alone (no optimizer update).

    Used by equivalence tests (remat vs no-remat, sharded vs single-device):
    post-Adam weights are the wrong comparison surface because Adam's
    sign-normalization amplifies float-reordering noise on parameters whose
    true gradient is ~0 (e.g. a conv bias followed by batch-norm) into
    O(lr) weight differences.
    """
    learner = _task_learner(
        cfg, cfg.number_of_training_steps_per_iter, second_order
    )

    def grads_fn(state: MetaState, x_s, y_s, x_t, y_t, loss_weights):
        _, loss, _, _, grads, _ = _meta_loss_and_grads(
            learner, state, x_s, y_s, x_t, y_t, loss_weights,
            cfg.task_axis_mode, accum=cfg.meta_accum_steps,
        )
        return loss, grads

    return grads_fn


def _tree_sq_sum(tree) -> jnp.ndarray:
    """Sum of squares over every leaf, accumulated in f32 (bf16 configs
    would overflow/underflow a same-dtype reduction)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return sum(
        jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves
    )


def _tree_nonfinite_count(tree) -> jnp.ndarray:
    """Number of non-finite elements across every leaf (int32)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return sum(
        jnp.sum(~jnp.isfinite(leaf)).astype(jnp.int32) for leaf in leaves
    )


def _health_probes(loss, raw_grads, updates, new_trainable):
    """The on-device anomaly probes (``health_level != 'off'``).

    A handful of scalar reductions over values the step already holds —
    the PRE-clip meta-gradients (an explosion must be visible before the
    ±10 clamp hides it), the post-LR updates and the post-update
    parameters — returned under ``metrics['health']`` so they ride back
    with the metrics like the dynamics stacks: zero extra device syncs,
    and the training math is untouched (probes are pure reads of step
    outputs, never inputs to the loss/update graph). The host-side
    ``telemetry.health.AnomalyDetector`` consumes these one dispatch
    behind the device.
    """
    return {
        "loss": loss.astype(jnp.float32),
        "grad_norm": jnp.sqrt(_tree_sq_sum(raw_grads)),
        "nonfinite_grads": _tree_nonfinite_count(raw_grads),
        "update_norm": jnp.sqrt(_tree_sq_sum(updates)),
        "param_norm": jnp.sqrt(_tree_sq_sum(new_trainable)),
    }


def _decode_prelude(cfg: MAMLConfig, decode_uint8: Optional[bool]):
    """The in-jit uint8 decode for ``data_placement='uint8_stream'`` batches
    (None => follow the config), or None when batches arrive as float32."""
    if decode_uint8 is None:
        decode_uint8 = cfg.data_placement == "uint8_stream"
    return device_pipeline.make_decoder(cfg) if decode_uint8 else None


def make_train_step(
    cfg: MAMLConfig, second_order: bool, decode_uint8: Optional[bool] = None
):
    """Build the jitted outer step: vmap over tasks, grad, Adam.

    Signature: (state, x_s, y_s, x_t, y_t, loss_weights, lr) -> (state, metrics)

    Under ``data_placement='uint8_stream'`` the x batches arrive as raw
    uint8 (host gathered + rotated, decode deferred) and the step decodes
    them on device as a prelude; ``decode_uint8`` overrides the gate (the
    indexed path decodes inside its own expander).

    ``cfg.meta_accum_steps > 1`` scans the meta-batch in that many
    task microbatches INSIDE this one compiled step, accumulating the
    per-task meta-grads in f32 and reducing them once — bit-exact (f32)
    with the single-pass program at equal total batch while the
    activation peak shrinks ~accum-fold (see ``_meta_loss_and_grads``).
    All four train-step factories inherit it (the multi/indexed variants
    wrap this step).

    ``telemetry_level='dynamics'`` adds a ``metrics['dynamics']`` dict to
    the output — per-inner-step support/target losses and per-layer
    inner-grad norms (task-mean, stacked ``(num_steps,)`` inside the
    existing scan), the post-update LSLR vectors, and the MSL weight
    vector. It rides back with the metrics, so collection adds zero extra
    device syncs; with telemetry off the traced program is unchanged.

    ``health_level != 'off'`` adds a ``metrics['health']`` dict under the
    same zero-extra-syncs contract: the scalar anomaly probes of
    ``_health_probes`` (pre-clip meta-gradient norm, non-finite grad
    count, update/param norms), consumed one dispatch behind the device by
    the host-side anomaly detector (telemetry/health.py).
    """
    num_steps = cfg.number_of_training_steps_per_iter
    collect = cfg.telemetry_level == "dynamics"
    probe = cfg.health_level != "off"
    learner = _task_learner(cfg, num_steps, second_order, collect)
    decode = _decode_prelude(cfg, decode_uint8)

    def train_step(state: MetaState, x_s, y_s, x_t, y_t, loss_weights, lr):
        # precision is scoped to this step's trace (not process-global jax
        # config): fp32 configs need true fp32 MXU multiplies — TPU 'default'
        # single-bf16-pass multiplies starve the second-order meta-gradient
        # (measured: 20-way val 14% vs 65% at 100 iters) — and two coexisting
        # systems with different compute_dtype must not leak settings into
        # each other's lazily-traced steps
        if decode is not None:
            x_s, x_t = decode(x_s), decode(x_t)
        with jax.default_matmul_precision(cfg.resolved_matmul_precision):
            return _train_step_body(state, x_s, y_s, x_t, y_t, loss_weights, lr)

    def _train_step_body(state: MetaState, x_s, y_s, x_t, y_t, loss_weights, lr):
        # labels depend only on (static) key names, so building the transform
        # inside the traced function is free
        opt = make_optimizer(cfg, state.net)
        trainable, loss, correct, bns, grads, dyn = _meta_loss_and_grads(
            learner, state, x_s, y_s, x_t, y_t, loss_weights,
            cfg.task_axis_mode, accum=cfg.meta_accum_steps,
        )
        raw_grads = grads  # pre-clip view for the health probes
        if cfg.clip_grads:
            # elementwise clamp to ±10, net params only
            # (few_shot_learning_system.py:332-335)
            grads = {
                "net": jax.tree_util.tree_map(
                    lambda g: jnp.clip(g, -10.0, 10.0), grads["net"]
                ),
                "lslr": grads["lslr"],
            }
        updates, new_opt = opt.update(grads, state.opt, trainable)
        updates = jax.tree_util.tree_map(lambda u: -lr * u, updates)
        new_trainable = optax.apply_updates(trainable, updates)
        new_state = MetaState(
            net=new_trainable["net"],
            lslr=new_trainable["lslr"],
            bn=_merge_bn(bns) if state.bn else state.bn,
            opt=new_opt,
        )
        metrics = {"loss": loss, "accuracy": jnp.mean(correct)}
        if probe:
            metrics["health"] = _health_probes(
                loss, raw_grads, updates, new_trainable
            )
        if collect:
            # mean over the (leading) task axis keeps the payload tiny:
            # a handful of (num_steps,) vectors per dispatch
            dynamics = jax.tree_util.tree_map(
                lambda v: jnp.mean(v, axis=0), dyn
            )
            dynamics["lslr"] = new_trainable["lslr"]  # the learned LSLR
            dynamics["msl_weights"] = jnp.asarray(loss_weights)
            metrics["dynamics"] = dynamics
        return new_state, metrics

    return train_step


def make_train_multi_step(cfg: MAMLConfig, second_order: bool):
    """K outer updates in ONE compiled program: ``lax.scan`` over a leading
    batch-of-batches axis (config ``steps_per_dispatch``).

    Signature: (state, x_s, y_s, x_t, y_t, loss_weights, lr) ->
    (state, metrics) where every batch argument carries a leading k axis and
    the metrics come back stacked (k,).

    Why: each dispatch over a networked device transport (the remote-TPU
    tunnel) costs a host round-trip that can dwarf device compute — measured
    ~0.5 s/dispatch against ~30 ms of compute for the paper-width Omniglot
    step, capping training at ~1.8 iters/s with the chip 95% idle. One
    upload + one dispatch per K steps amortizes that. LR, MSL weights and
    the order flag are epoch-functions and therefore constant within a
    chunk; the experiment builder flushes chunks at epoch boundaries.
    """
    step = make_train_step(cfg, second_order)

    def multi_step(state, x_s, y_s, x_t, y_t, loss_weights, lr):
        def body(st, batch):
            xs, ys, xt, yt = batch
            st, metrics = step(st, xs, ys, xt, yt, loss_weights, lr)
            return st, metrics

        # unroll small chunks (same policy + bound as the inner-loop
        # scan): a rolled scan body is compiled as its own computation
        # whose fusion choices differ from straight-line code, which
        # would break the meta_accum_steps bit-exactness contract for
        # the multi factories (see _meta_loss_and_grads) — and k fused
        # updates are short (2-8) by construction
        return jax.lax.scan(
            body, state, (x_s, y_s, x_t, y_t),
            unroll=True if x_s.shape[0] <= 8 else 1,
        )

    return multi_step


def make_eval_multi_step(cfg: MAMLConfig, with_preds: bool = False):
    """K evaluation passes in ONE compiled program: ``lax.scan`` over a
    leading batch-of-batches axis (config ``eval_batches_per_dispatch``) —
    the eval twin of ``make_train_multi_step``.

    Signature: (state, x_s, y_s, x_t, y_t) -> (metrics, preds) where every
    batch argument carries a leading k axis, metrics come back stacked (k,),
    and preds — only materialised when ``with_preds`` (the test ensemble
    needs them, plain validation must not pay the stacked-softmax output) —
    come back (k, tasks, targets, classes).

    Why: MAML++ validates over num_evaluation_tasks fixed tasks every epoch
    and the top-N ensemble re-runs the test stream per checkpoint; with
    per-batch dispatch the epoch boundary pays one host round-trip per batch
    (~0.5 s over the networked device transport vs ~30 ms compute), which the
    fused train path (steps_per_dispatch) left as the dominant serial tail.
    Eval never updates state, so the scan carry is just the (replicated)
    state passed through untouched.
    """
    step = make_eval_step(cfg)

    def multi_eval(state: MetaState, x_s, y_s, x_t, y_t):
        def body(st, batch):
            metrics, preds = step(st, *batch)
            return st, (metrics, preds if with_preds else None)

        _, (metrics, preds) = jax.lax.scan(
            body, state, (x_s, y_s, x_t, y_t)
        )
        return metrics, preds

    return multi_eval


def make_eval_step(cfg: MAMLConfig, decode_uint8: Optional[bool] = None):
    """Build the jitted evaluation step.

    Reference semantics (few_shot_learning_system.py:311-323,371-397): always
    first order, ``number_of_evaluation_steps_per_iter`` inner steps, only the
    final step's target loss (MSL gate off because training_phase=False,
    :232), BN running-stat updates discarded afterwards — which here is simply
    "don't return new BN state" (no backup/restore mutation needed).

    Returns (metrics, per_task_softmax_preds) — the preds feed the top-5
    checkpoint ensemble (experiment_builder.py:247-300).

    ``decode_uint8``: same uint8_stream prelude gate as ``make_train_step``.
    """
    num_steps = cfg.number_of_evaluation_steps_per_iter
    learner = _task_learner(cfg, num_steps, second_order=False)
    loss_weights = jnp.asarray(msl_lib.final_step_only(num_steps))
    decode = _decode_prelude(cfg, decode_uint8)

    def eval_step(state: MetaState, x_s, y_s, x_t, y_t):
        # same per-step precision scoping as train_step (see there)
        if decode is not None:
            x_s, x_t = decode(x_s), decode(x_t)
        with jax.default_matmul_precision(cfg.resolved_matmul_precision):
            losses, (correct, _, preds, _) = _map_tasks(
                lambda xs, ys, xt, yt: learner(
                    state.net, state.lslr, state.bn, xs, ys, xt, yt,
                    loss_weights
                ),
                cfg.task_axis_mode, x_s, y_s, x_t, y_t,
            )
            metrics = {"loss": jnp.mean(losses), "accuracy": jnp.mean(correct)}
            return metrics, preds

    return eval_step


# -- serving (adapt-on-request meta-inference) -------------------------------
#
# The serving hot path is the SAME fused adapt-then-predict program eval
# runs, with the meta-batch axis repurposed as a concurrent-TENANT axis:
# many users' support sets ride one dispatch, each adapting its own weight
# clone under vmap. Unlike eval, the serve step (a) passes the state
# THROUGH as an output so it can be donated — the executable aliases the
# state buffers input->output (verified by the donation contract:
# alias_size_bytes == state bytes), the engine re-binds its reference per
# dispatch, and params + LSLR + BN stay single-buffered in HBM exactly
# like the train family — and (b) takes a per-tenant ``valid`` mask so
# PAD tenants (the batcher rounds partial dispatches up to a static
# bucket) cannot perturb the aggregate metrics; per-tenant outputs are
# untouched by padding by construction (vmap tasks are independent
# chains), which the serving bit-exactness tests pin.
SERVE_DONATE = (0,)


def _serve_outputs(losses, correct, preds, valid, adapted=None):
    """The shared serving epilogue: barrier-materialize the per-tenant
    stacks, then the masked tenant-mean metrics.

    The ``optimization_barrier`` materializes the per-tenant stacks
    before the masked reductions, so the extra consumers the mask (and,
    when the adapted-params cache is on, the fast-weights output)
    introduces can never perturb the per-task codegen the bit-exactness
    contracts rest on (same discipline as the indexed train factories).
    """
    stacks = (losses, correct, preds)
    if adapted is not None:
        stacks = stacks + (adapted,)
    stacks = jax.lax.optimization_barrier(stacks)
    losses, correct, preds = stacks[:3]
    mask = valid.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    per_tenant_acc = jnp.mean(correct, axis=-1)
    out = {
        "preds": preds,
        "loss": losses,
        "accuracy": per_tenant_acc,
        "metrics": {
            "loss": jnp.sum(losses.astype(jnp.float32) * mask) / denom,
            "accuracy": jnp.sum(per_tenant_acc * mask) / denom,
        },
    }
    if adapted is not None:
        out["adapted"] = stacks[3]
    return out


def make_serve_step(
    cfg: MAMLConfig, ingest: str = "f32", return_adapted: bool = False
):
    """Build the adapt-then-predict serving step.

    Signature: (state, x_s, y_s, x_t, y_t, valid) -> (state, out) where
    the batch arguments carry a leading TENANT axis of some static bucket
    width, ``valid`` is the float32 (bucket,) METRIC mask — 1 for a
    tenant whose query labels are real, 0 for pad tenants AND for
    label-free tenants whose ``y_t`` slot holds fabricated zeros (scoring
    those would poison the aggregate; their predictions are unaffected) —
    the returned state is the input state passed through (donated:
    ``SERVE_DONATE``), and ``out`` holds the per-tenant results —
    ``preds`` (bucket, way * targets, classes) softmax (the query stream
    flattened class-major, the eval path's layout), ``loss`` /
    ``accuracy`` (bucket,) — plus ``metrics``: the masked tenant-mean loss/accuracy
    (masked-out tenants contribute exactly zero; all-masked dispatches
    report 0 by the clamped denominator).

    The per-tenant math is the eval program's verbatim — same
    ``_task_learner`` (first order, ``number_of_evaluation_steps_per_iter``
    inner steps, final-step-only loss weights), same matmul-precision
    scope — so serving predictions are bit-exact with
    ``make_eval_step`` / ``make_eval_multi_step`` outputs at the same
    tenant width (tests/test_serving.py).

    ``ingest='uint8'`` accepts raw uint8 pixel batches and decodes them
    on device through the device-pipeline LUT
    (``ops.device_pipeline.make_decoder`` — bit-exact with the host
    decode by construction), cutting per-dispatch H2D pixel bytes 4x.
    The decoded batches are barrier-materialized before the adapt body,
    so the decode can never fuse into the per-tenant task bodies: the
    downstream program consumes the same batch-shaped inputs as the f32
    program and the uint8-vs-f32 bit-exactness contract holds structurally
    (the same discipline — and the same reason — as the indexed train
    factories).

    ``return_adapted`` (the adapted-params cache) adds ``out['adapted']``:
    the per-tenant post-adaptation fast weights, each leaf (bucket, ...) —
    the exact arrays the final target forward consumed, which is what
    makes a later ``make_predict_step`` dispatch over them bit-exact with
    this full adaptation.
    """
    if ingest not in ("f32", "uint8"):
        raise ValueError(
            f"make_serve_step ingest must be 'f32' or 'uint8', got "
            f"{ingest!r} (the index ingest is make_serve_step_indexed)"
        )
    num_steps = cfg.number_of_evaluation_steps_per_iter
    learner = _task_learner(
        cfg, num_steps, second_order=False, return_adapted=return_adapted
    )
    loss_weights = jnp.asarray(msl_lib.final_step_only(num_steps))
    decode = (
        device_pipeline.make_decoder(cfg) if ingest == "uint8" else None
    )

    def serve_step(state: MetaState, x_s, y_s, x_t, y_t, valid):
        # same per-step precision scoping as train/eval (see train_step)
        with jax.default_matmul_precision(cfg.resolved_matmul_precision):
            if decode is not None:
                x_s, x_t = decode(x_s), decode(x_t)
                x_s, y_s, x_t, y_t = jax.lax.optimization_barrier(
                    (x_s, y_s, x_t, y_t)
                )
            losses, aux = _map_tasks(
                lambda xs, ys, xt, yt: learner(
                    state.net, state.lslr, state.bn, xs, ys, xt, yt,
                    loss_weights
                ),
                cfg.task_axis_mode, x_s, y_s, x_t, y_t,
            )
            out = _serve_outputs(
                losses, aux[0], aux[2], valid,
                adapted=aux[4] if return_adapted else None,
            )
            return state, out

    return serve_step


def make_serve_step_indexed(
    cfg: MAMLConfig, shots: int, return_adapted: bool = False
):
    """The index-ingest serving step (``serving_ingest='index'``).

    Signature: (state, store, gather, valid) -> (state, out) — the
    resident uint8 store is a program parameter exactly like the indexed
    train factories (never donated: it is a registry-owned invariant
    reused by every dispatch), ``gather`` is the (bucket, way,
    shots + targets) int32 store-row tensor, and per-dispatch H2D drops
    to the index tensor + mask (<1KB at paper geometry). Labels never
    cross H2D: sample (i, j) carries label i by construction (slot iota
    — ``ops.device_pipeline.make_serve_expander``). The expanded batch is
    barrier-materialized before the adapt body (see ``make_serve_step``'s
    uint8 note), so the body is the f32 program's verbatim and
    index-vs-f32 bit-exactness holds structurally. ``shots`` is static —
    one compiled program per (bucket, shots), like the pixel ingests.
    ``out`` is ``make_serve_step``'s contract unchanged (incl.
    ``return_adapted``).
    """
    num_steps = cfg.number_of_evaluation_steps_per_iter
    learner = _task_learner(
        cfg, num_steps, second_order=False, return_adapted=return_adapted
    )
    loss_weights = jnp.asarray(msl_lib.final_step_only(num_steps))
    expand = device_pipeline.make_serve_expander(cfg, shots)

    def serve_step(state: MetaState, store, gather, valid):
        with jax.default_matmul_precision(cfg.resolved_matmul_precision):
            x_s, y_s, x_t, y_t = jax.lax.optimization_barrier(
                expand(store, gather)
            )
            losses, aux = _map_tasks(
                lambda xs, ys, xt, yt: learner(
                    state.net, state.lslr, state.bn, xs, ys, xt, yt,
                    loss_weights
                ),
                cfg.task_axis_mode, x_s, y_s, x_t, y_t,
            )
            out = _serve_outputs(
                losses, aux[0], aux[2], valid,
                adapted=aux[4] if return_adapted else None,
            )
            return state, out

    return serve_step


#: donated argnums of ``make_predict_step`` — the same passthrough-state
#: aliasing contract as ``SERVE_DONATE`` (the cached fast weights are NOT
#: donated: they are cache-owned host arrays uploaded per dispatch)
PREDICT_DONATE = (0,)


def _predict_body(cfg: MAMLConfig):
    """The shared predict-only per-tenant body + masked epilogue (see
    ``make_predict_step``): (state, fast, x_t, y_t, valid) -> out, with
    ``x_t`` already decoded float pixels."""
    last_step = cfg.number_of_evaluation_steps_per_iter - 1

    def body(state: MetaState, fast, x_t, y_t, valid):
        _, frozen = partition.split_inner(cfg, state.net)

        def per_tenant(th, xt, yt):
            # same flatten as _task_learner.task_loss
            x = xt.reshape((-1,) + xt.shape[-3:])
            y = yt.reshape(-1)
            logits, _ = vgg.apply(
                cfg, {**frozen, **th}, state.bn, x, last_step,
                training=True,
            )
            return (
                F.cross_entropy(logits, y),
                F.accuracy(logits, y),
                jax.nn.softmax(logits, axis=-1),
            )

        if cfg.task_axis_mode == "map":
            losses, correct, preds = jax.lax.map(
                lambda a: per_tenant(*a), (fast, x_t, y_t)
            )
        else:
            losses, correct, preds = jax.vmap(per_tenant)(fast, x_t, y_t)
        return _serve_outputs(losses, correct, preds, valid)

    return body


def make_predict_step(cfg: MAMLConfig, ingest: str = "f32"):
    """The cache-hit serving program: predict-only, NO inner loop.

    Signature: (state, fast, x_t, y_t, valid) -> (state, out) where
    ``fast`` is the per-tenant adapted fast-weight pytree (each leaf
    (bucket, ...) — a ``make_serve_step(return_adapted=True)`` dispatch's
    ``out['adapted']``, round-tripped through the host adapted-params
    cache), and ``out`` is the serve step's contract minus ``adapted``.

    The per-tenant math is EXACTLY the final target forward of the adapt
    program — ``vgg.apply({**frozen, **fast}, ...)`` at inner-step index
    ``num_eval_steps - 1`` with ``training=True`` — so a cache hit is
    bit-exact with full re-adaptation at the same tenant width: the fast
    weights are the same arrays the adapt program's last forward consumed
    (f32 host round-trip is exact), and batch-norm always normalizes with
    the CURRENT batch's statistics (``ops.functional.batch_norm``), so the
    per-tenant BN running-stat evolution the adapt path tracks — the only
    state this program does not replay — never touches the logits.

    ``ingest='uint8'`` decodes the query batch on device (the serve
    step's LUT prelude + barrier, same bit-exactness argument).

    Cost: forward GEMMs only — no support gradient, no inner-loop chain;
    the op census carries one forward's worth of dot/conv ops and zero
    inner-loop gradient ops (pinned by `cli audit` / the serving tests).
    """
    if ingest not in ("f32", "uint8"):
        raise ValueError(
            f"make_predict_step ingest must be 'f32' or 'uint8', got "
            f"{ingest!r} (the index ingest is make_predict_step_indexed)"
        )
    body = _predict_body(cfg)
    decode = (
        device_pipeline.make_decoder(cfg) if ingest == "uint8" else None
    )

    def predict_step(state: MetaState, fast, x_t, y_t, valid):
        with jax.default_matmul_precision(cfg.resolved_matmul_precision):
            if decode is not None:
                x_t, y_t = jax.lax.optimization_barrier(
                    (decode(x_t), y_t)
                )
            return state, body(state, fast, x_t, y_t, valid)

    return predict_step


def make_predict_step_indexed(cfg: MAMLConfig):
    """The index-ingest predict-only program (cache hits under
    ``serving_ingest='index'``).

    Signature: (state, fast, store, gather, valid) -> (state, out) with
    ``gather`` the (bucket, way, targets) int32 QUERY store rows (no
    support rows — a cache hit ships no support set at all) and labels
    slot iota, exactly like the adapt-side serve expander."""
    body = _predict_body(cfg)
    decode = device_pipeline.make_decoder(cfg)

    def predict_step(state: MetaState, fast, store, gather, valid):
        with jax.default_matmul_precision(cfg.resolved_matmul_precision):
            x_t = decode(store[gather])
            y_t = jax.lax.broadcasted_iota(jnp.int32, gather.shape, 1)
            x_t, y_t = jax.lax.optimization_barrier((x_t, y_t))
            return state, body(state, fast, x_t, y_t, valid)

    return predict_step


# -- device-resident (index-only H2D) step variants -------------------------
#
# ``data_placement='device'``: the split's uint8 image store is resident in
# HBM and the host ships only int32 gather/rot-k tensors per batch; the
# gather -> decode -> rot90 expansion (ops.device_pipeline) runs as a prelude
# inside the same jitted program as the step. ``augment`` is a static trace
# parameter (per-set: train-time Omniglot only), mirroring the host
# ``augment_stack`` gate.


def make_train_step_indexed(cfg: MAMLConfig, second_order: bool, augment: bool,
                            store_mesh=None):
    """Signature: (state, store, gather, rot_k, loss_weights, lr) ->
    (state, metrics) — ``make_train_step`` with the on-device episode
    expansion in front; identical math to the host pixel path.

    ``store_mesh`` (elastic sharded-store tier, ``store_sharding='hosts'``)
    switches the expansion to the masked-gather + host-axis-psum form for a
    store whose row axis is sharded over that mesh's host axis — bit-exact
    with the replicated gather by construction (ops/device_pipeline.py)."""
    step = make_train_step(cfg, second_order, decode_uint8=False)
    expand = device_pipeline.make_index_expander(
        cfg, augment, store_mesh=store_mesh
    )

    def train_step(state: MetaState, store, gather, rot_k, loss_weights, lr):
        x_s, y_s, x_t, y_t = expand(store, gather, rot_k)
        # materialize the expanded batch before the step: the plain
        # factory's batches are program PARAMETERS; without this barrier
        # the gather/decode/rot90 would fuse into the (microbatch-width)
        # task bodies, whose codegen then depends on meta_accum_steps —
        # breaking the accumulation bit-exactness contract for the
        # indexed factories (one batch-sized materialization, the same
        # bytes the expander produces anyway)
        x_s, y_s, x_t, y_t = jax.lax.optimization_barrier(
            (x_s, y_s, x_t, y_t)
        )
        return step(state, x_s, y_s, x_t, y_t, loss_weights, lr)

    return train_step


def make_train_multi_step_indexed(
    cfg: MAMLConfig, second_order: bool, augment: bool, store_mesh=None
):
    """The ``steps_per_dispatch`` twin of ``make_train_step_indexed``: scan
    over a leading k axis of (gather, rot_k) — the resident store is a scan
    invariant, NOT scanned over, so K fused updates still upload only K·(a
    few KB) of indices."""
    step = make_train_step_indexed(cfg, second_order, augment, store_mesh)

    def multi_step(state, store, gather, rot_k, loss_weights, lr):
        def body(st, batch):
            g, r = batch
            st, metrics = step(st, store, g, r, loss_weights, lr)
            return st, metrics

        # unrolled like make_train_multi_step (accum bit-exactness)
        return jax.lax.scan(
            body, state, (gather, rot_k),
            unroll=True if gather.shape[0] <= 8 else 1,
        )

    return multi_step


def make_eval_step_indexed(cfg: MAMLConfig, augment: bool = False,
                           store_mesh=None):
    """Signature: (state, store, gather, rot_k) -> (metrics, preds) — the
    evaluation twin of ``make_train_step_indexed``."""
    step = make_eval_step(cfg, decode_uint8=False)
    expand = device_pipeline.make_index_expander(
        cfg, augment, store_mesh=store_mesh
    )

    def eval_step(state: MetaState, store, gather, rot_k):
        x_s, y_s, x_t, y_t = expand(store, gather, rot_k)
        return step(state, x_s, y_s, x_t, y_t)

    return eval_step


def make_eval_multi_step_indexed(
    cfg: MAMLConfig, with_preds: bool = False, augment: bool = False,
    store_mesh=None,
):
    """The ``eval_batches_per_dispatch`` twin of ``make_eval_step_indexed``
    (same stacked-metrics/preds contract as ``make_eval_multi_step``)."""
    step = make_eval_step_indexed(cfg, augment, store_mesh)

    def multi_eval(state: MetaState, store, gather, rot_k):
        def body(st, batch):
            g, r = batch
            metrics, preds = step(st, store, g, r)
            return st, (metrics, preds if with_preds else None)

        _, (metrics, preds) = jax.lax.scan(body, state, (gather, rot_k))
        return metrics, preds

    return multi_eval
