"""Multi-Step Loss (MSL) importance schedule — MAML++'s per-step loss weights.

Pure re-implementation of ``get_per_step_loss_importance_vector``
(few_shot_learning_system.py:83-103): starts uniform ``1/N`` over the N inner
steps, anneals the non-final weights down by ``epoch / N / anneal_epochs``
each epoch (floored at ``0.03/N``) while the final step's weight absorbs the
difference (capped at ``1 - (N-1) * 0.03/N``).

The reference gates MSL on ``training and epoch < multi_step_loss_num_epochs``
(few_shot_learning_system.py:232) and otherwise uses only the final step's
target loss (:239-244). ``loss_weights_for`` folds that gate in by returning a
one-hot-on-last-step vector when MSL is inactive, so a single weighted-sum
formulation covers both branches with identical numerics.
"""

from __future__ import annotations

import numpy as np


def per_step_loss_importance(
    num_steps: int, multi_step_loss_num_epochs: int, epoch: int
) -> np.ndarray:
    """The annealed per-step weights at a given (integer) epoch."""
    loss_weights = np.ones(num_steps, dtype=np.float32) / num_steps
    decay_rate = 1.0 / num_steps / multi_step_loss_num_epochs
    min_non_final = 0.03 / num_steps
    for i in range(num_steps - 1):
        loss_weights[i] = np.maximum(
            loss_weights[i] - epoch * decay_rate, min_non_final
        )
    loss_weights[-1] = np.minimum(
        loss_weights[-1] + epoch * (num_steps - 1) * decay_rate,
        1.0 - (num_steps - 1) * min_non_final,
    )
    return loss_weights


def final_step_only(num_steps: int) -> np.ndarray:
    """One-hot on the last step: the non-MSL / post-anneal / eval branch
    (few_shot_learning_system.py:239-244)."""
    w = np.zeros(num_steps, dtype=np.float32)
    w[-1] = 1.0
    return w


def loss_weights_for(
    num_steps: int,
    use_msl: bool,
    training: bool,
    epoch: int,
    multi_step_loss_num_epochs: int,
) -> np.ndarray:
    """The weight vector for a given phase/epoch, gate included."""
    if use_msl and training and epoch < multi_step_loss_num_epochs:
        return per_step_loss_importance(num_steps, multi_step_loss_num_epochs, epoch)
    return final_step_only(num_steps)
