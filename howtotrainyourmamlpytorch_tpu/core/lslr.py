"""LSLR — per-parameter, per-step learnable inner-loop learning rates.

Reference: ``LSLRGradientDescentLearningRule``
(inner_loop_optimizers.py:55-113). One learning-rate vector of shape
``(num_inner_steps + 1,)`` per inner-adapted parameter tensor, initialised to
the task learning rate, meta-learned by the outer optimizer when
``learnable_per_layer_per_step_inner_loop_learning_rate``.

Here the whole thing is just a pytree mirroring the adapted-parameter dict —
the update is ``theta - lr[name][step] * grad`` (inner_loop_optimizers.py:
108-113), applied inside the scanned inner step. The ``+1``-th entry is never
indexed (steps run 0..N-1), faithfully preserving the reference's shape.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

LSLRParams = Dict[str, jnp.ndarray]


def init(adapted_param_names, num_inner_steps: int, init_learning_rate: float) -> LSLRParams:
    """One (num_inner_steps + 1,) LR vector per adapted parameter
    (inner_loop_optimizers.py:86-91)."""
    return {
        name: jnp.full((num_inner_steps + 1,), init_learning_rate, jnp.float32)
        for name in adapted_param_names
    }


def update_params(
    weights: Dict[str, jnp.ndarray],
    grads: Dict[str, jnp.ndarray],
    lslr: LSLRParams,
    num_step,
) -> Dict[str, jnp.ndarray]:
    """theta' = theta - lr[name][step] * g (inner_loop_optimizers.py:108-113)."""
    return {
        key: weights[key] - lslr[key][num_step] * grads[key] for key in weights
    }


def sgd_update_params(
    weights: Dict[str, jnp.ndarray],
    grads: Dict[str, jnp.ndarray],
    learning_rate: float,
) -> Dict[str, jnp.ndarray]:
    """Plain fixed-LR gradient descent: theta' = theta - eta * g.

    The reference's ``GradientDescentLearningRule.update_params``
    (inner_loop_optimizers.py:39-52) — defined there but never used by the
    main path (few_shot_learning_system.py:10 imports only LSLR); here it is
    selectable via ``MAMLConfig.inner_loop_optimizer = "sgd"``. Equivalent to
    LSLR with non-learnable LRs all equal to ``eta``.
    """
    return {key: weights[key] - learning_rate * grads[key] for key in weights}
