from . import lslr, maml, msl, partition
