"""Multi-host (pod-scale) mesh construction over ICI + DCN.

The reference's only cross-device mechanism is single-process
``nn.DataParallel`` (few_shot_learning_system.py:73-81); it has no
distributed backend at all (no torch.distributed/NCCL/MPI — SURVEY.md §2.2).
The TPU-native story needs none of that machinery either: the JAX runtime
carries collectives over ICI within a slice and DCN across hosts; this module
just (a) initialises the multi-process runtime from standard env vars and
(b) builds meshes whose axis order keeps the high-traffic task axis on ICI.

Single-process multi-device (one TPU VM, or the virtual CPU mesh used by
tests) needs no initialisation — ``task_mesh`` alone suffices.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import TASK_AXIS

DATA_AXIS = "hosts"  # DCN-spanning axis for multi-host data parallelism


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialise jax.distributed for multi-host runs.

    Arguments default to the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``); on Cloud TPU pods all three
    are auto-detected by jax and may stay None. Returns True when the
    multi-process runtime was initialised, False for single-process runs
    (no coordinator configured).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    num_processes = num_processes or _int_env("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("JAX_PROCESS_ID")
    on_tpu_pod = (
        os.environ.get("TPU_WORKER_HOSTNAMES", "localhost") != "localhost"
    )
    if coordinator_address is None and not on_tpu_pod:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def hybrid_task_mesh(
    devices: Optional[Sequence] = None,
    processes: Optional[int] = None,
) -> Mesh:
    """A 2-D (hosts, tasks) mesh: DCN-spanning host axis x ICI task axis.

    Axis order puts the host axis first, so XLA maps the *minor* (task) axis
    onto ICI neighbours within each slice and only the cross-host reduction
    rides DCN — the outer-gradient psum then does an ICI reduce per slice
    followed by one small DCN all-reduce (the scaling-book recipe for
    DP-over-pods). Degenerates to a (1, n) mesh in single-process runs.

    Real multi-process runs go through ``mesh_utils.create_hybrid_device_mesh``
    (topology-aware; ``jax.devices()`` ordering is not guaranteed
    process-contiguous). The explicit ``processes`` argument exists for
    simulating a host axis on a single-process (virtual-device) mesh in tests.
    """
    devs = list(devices if devices is not None else jax.devices())
    n_proc = processes or jax.process_count()
    if len(devs) % n_proc != 0:
        raise ValueError(
            f"{len(devs)} devices not divisible by {n_proc} processes"
        )
    per_host = len(devs) // n_proc
    if processes is None and jax.process_count() > 1:
        from jax.experimental import mesh_utils

        # granule = PROCESS, not slice: the loader assigns global-batch slice
        # [p*per_host, (p+1)*per_host) to process p, so mesh row p must hold
        # exactly process p's devices for make_array_from_process_local_data
        # to place each host's data on its own chips. (Slice granules would
        # also reject single-slice multi-host pods and multi-process CPU,
        # where n_granules != n_proc.)
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, per_host),
            dcn_mesh_shape=(n_proc, 1),
            devices=devs,
            process_is_granule=True,
        )
    else:
        # single process (incl. simulated hosts): group by (process, id) so
        # rows never mix hosts even if the device list is reordered
        devs = sorted(devs, key=lambda d: (d.process_index, d.id))
        grid = np.asarray(devs).reshape(n_proc, per_host)
    return Mesh(grid, (DATA_AXIS, TASK_AXIS))


def global_batch_sharding(mesh: Mesh):
    """Shard a global task axis over both mesh axes (hosts major)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P((DATA_AXIS, TASK_AXIS)))


def store_row_sharding(mesh: Mesh):
    """Shard a resident store's row axis over the host (DCN) axis,
    replicated across each host's own (task-axis) devices — the elastic
    ``store_sharding='hosts'`` layout: per-host HBM holds store/n_hosts,
    and the on-device gather runs as the masked local gather + hosts-psum
    of ``ops.device_pipeline.make_sharded_gather`` (batch-sized float32
    collective; the store itself never crosses the interconnect)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS))
