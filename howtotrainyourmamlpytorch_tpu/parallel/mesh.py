"""Device-mesh scaling of the meta-batch (task) axis.

TPU-native replacement for the reference's single-process ``nn.DataParallel``
(few_shot_learning_system.py:73-81) and its device-dim weight
repeat/squeeze/sum machinery (:142-158, :201-206,
meta_neural_network_architectures.py:635): the meta-batch's task axis is
sharded over a 1-D ``jax.sharding.Mesh``; parameters are replicated; XLA
inserts the outer-gradient ``psum`` over ICI automatically when the jitted
step reduces over the sharded axis ("computation follows sharding"). The
same code scales to multi-host DCN-spanning meshes via jax.distributed — no
custom communication backend is needed (SURVEY.md §2.2).

Bigger scale knobs live in the config: ``num_devices`` caps the mesh size
(0 = all visible devices); per-device task count = batch_size //
num_devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TASK_AXIS = "tasks"


def task_mesh(num_devices: int = 0, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the task (data-parallel) axis."""
    devs = list(devices if devices is not None else jax.devices())
    if num_devices and num_devices > 0:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (TASK_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (task) axis sharded over the mesh."""
    return NamedSharding(mesh, P(TASK_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _shard_on_axis(mesh: Mesh, arrays, axis: int, sharding: NamedSharding):
    n = len(mesh.devices)
    out = []
    for a in arrays:
        if a.shape[axis] % n != 0:
            raise ValueError(
                f"meta-batch {a.shape[axis]} not divisible by mesh size {n}"
            )
        out.append(jax.device_put(a, sharding))
    return tuple(out)


def shard_stacked_batch(mesh: Mesh, *arrays):
    """Place (k, tasks, ...) stacked batches with the TASK axis (axis 1)
    split over the mesh — the multi-dispatch (steps_per_dispatch) variant of
    ``shard_batch``; the leading axis is the scan-over-steps axis and stays
    replicated."""
    return _shard_on_axis(
        mesh, arrays, 1, NamedSharding(mesh, P(None, TASK_AXIS))
    )


def shard_batch(mesh: Mesh, *arrays):
    """Place batch arrays with the task axis split over the mesh.

    The task count must divide the mesh size — the reference had the same
    constraint implicitly (DataParallel scatters batch over GPUs).
    """
    return _shard_on_axis(mesh, arrays, 0, batch_sharding(mesh))


def replicate_state(mesh: Mesh, tree):
    """Replicate a pytree (params/opt state) across the mesh."""
    sharding = replicated(mesh)
    return jax.device_put(tree, sharding)


def replicate_array(mesh: Mesh, a) -> jax.Array:
    """Replicate one array to every device of the mesh.

    Used for the resident uint8 image store (``data_placement='device'``):
    every device gathers arbitrary rows for its own shard of the task axis,
    so the store must be whole on each device — splitting its image axis
    would turn each step's gather into a cross-device all-gather of the
    very pixels residency exists to stop moving. The per-batch *index*
    tensors are what shard over the task axis (``shard_batch`` /
    ``shard_stacked_batch``, same helpers as the pixel path), and in
    multi-host runs each host samples only its ``shard_id`` slice of every
    global batch, exactly like the pixel loader.
    """
    return jax.device_put(a, replicated(mesh))
