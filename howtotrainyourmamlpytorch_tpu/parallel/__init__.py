from . import mesh
