"""Batched, prefetching, resume-exact episodic data loader.

Replaces the reference's torch ``DataLoader`` wrapper
(``MetaLearningSystemDataLoader`` data.py:555-637) with a thread-pool episode
builder + bounded prefetch queue feeding numpy batches:

* batch = ``num_devices * batch_size * samples_per_iter`` tasks stacked on a
  leading task axis (data.py:580) — the axis the device mesh shards;
* task seeds: ``seed[set] + idx`` with idx sequential from 0 per generator
  (shuffle=False determinism, data.py:544-549,581);
* resume: ``continue_from_iter`` advances the produced-task counter by
  ``current_iter * tasks_per_batch`` (data.py:583-588) and every
  ``get_train_batches`` call advances it by one batch worth (data.py:598-602)
  — both quirks preserved so a resumed run continues the task stream at
  exactly the next unseen task, like the reference;
* val/test streams restart from their fixed seed every call, so validation
  tasks are identical across epochs and the test stream equals the val stream
  (data.py:136-142,538-539) — properties the best-val selection and ensemble
  eval rely on.
"""

from __future__ import annotations

import concurrent.futures
import queue
import threading
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from ..config import MAMLConfig
from . import datasets as ds
from .episodes import Episode, sample_episode

Batch = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]


class FewShotEpisodicDataset:
    """Index + splits + per-set seed state (FewShotLearningDatasetParallel,
    data.py:111-552, minus torch)."""

    def __init__(self, cfg: MAMLConfig, cache_dir: Optional[str] = None):
        self.cfg = cfg
        cache_dir = cache_dir or cfg.cache_dir or "."
        self.init_seed = ds.draw_stream_seeds(cfg)
        self.seed = dict(self.init_seed)
        index, idx_to_label, label_to_idx = ds.load_class_index(cfg, cache_dir)
        self.splits = ds.split_classes(cfg, index, idx_to_label, self.seed["val"])
        if cfg.use_mmap_cache:
            from .preprocess import build_mmap_cache

            self.splits = build_mmap_cache(cfg, self.splits, cache_dir)
        elif cfg.load_into_memory:
            self.splits = ds.preload_to_memory(cfg, self.splits)
        # class-key ordering per set is the dict insertion order — the
        # ordering rng.choice sees in the reference (data.py:486)
        self.class_keys = {
            name: np.array(list(classes.keys()))
            for name, classes in self.splits.items()
        }
        for name, keys in self.class_keys.items():
            if len(keys) < cfg.num_classes_per_set:
                raise ValueError(
                    f"set {name!r} has {len(keys)} classes < "
                    f"num_classes_per_set={cfg.num_classes_per_set}"
                )

    def update_train_seed(self, current_iter: int) -> None:
        """switch_set('train', it): seed = init + it (data.py:536-542)."""
        self.seed["train"] = self.init_seed["train"] + current_iter

    def episode(self, set_name: str, idx: int, augment: bool) -> Episode:
        return sample_episode(
            self.cfg,
            self.splits[set_name],
            self.class_keys[set_name],
            seed=self.seed[set_name] + idx,
            augment=augment,
        )


def _stack(episodes) -> Batch:
    return (
        np.stack([e.x_support for e in episodes]),
        np.stack([e.x_target for e in episodes]),
        np.stack([e.y_support for e in episodes]),
        np.stack([e.y_target for e in episodes]),
        np.array([e.seed for e in episodes], np.int64),
    )


class MetaLearningDataLoader:
    """Batch generators with background prefetch (data.py:555-637).

    Multi-host: each process builds only its slice of every global batch
    (``shard_id``/``num_shards``, defaulting to the JAX process grid). Episode
    seeds are computed from *global* task indices, so the union of all hosts'
    slices is bit-identical to a single-host run — the TPU-native analogue of
    the reference's DataLoader-feeds-DataParallel layout (data.py:580).
    """

    def __init__(self, cfg: MAMLConfig, current_iter: int = 0,
                 cache_dir: Optional[str] = None,
                 shard_id: Optional[int] = None,
                 num_shards: Optional[int] = None):
        self.cfg = cfg
        self.tasks_per_batch = cfg.global_tasks_per_batch
        if num_shards is None:
            if shard_id is not None:
                raise ValueError("shard_id given without num_shards")
            import jax

            num_shards = jax.process_count()
            shard_id = jax.process_index()
        self.shard_id = shard_id or 0
        self.num_shards = max(1, num_shards)
        if self.tasks_per_batch % self.num_shards != 0:
            raise ValueError(
                f"tasks per batch {self.tasks_per_batch} not divisible by "
                f"{self.num_shards} hosts"
            )
        self.tasks_per_shard = self.tasks_per_batch // self.num_shards
        self.dataset = FewShotEpisodicDataset(cfg, cache_dir)
        self.total_train_iters_produced = 0
        self.continue_from_iter(current_iter)

    def continue_from_iter(self, current_iter: int) -> None:
        """Fast-forward the train stream after resume (data.py:583-588)."""
        self.total_train_iters_produced += current_iter * self.tasks_per_batch

    def _batches(
        self, set_name: str, total_batches: int, augment: bool
    ) -> Iterator[Batch]:
        cfg = self.cfg
        dataset = self.dataset
        tpb = self.tasks_per_batch
        workers = max(1, cfg.num_dataprovider_workers)
        prefetch = max(1, cfg.prefetch_batches)
        out: "queue.Queue" = queue.Queue(maxsize=prefetch)
        stop = threading.Event()

        lo = self.shard_id * self.tasks_per_shard
        hi = lo + self.tasks_per_shard

        def producer():
            try:
                with concurrent.futures.ThreadPoolExecutor(workers) as pool:
                    for b in range(total_batches):
                        if stop.is_set():
                            return
                        # this host's slice of the global batch's task range
                        idxs = range(b * tpb + lo, b * tpb + hi)
                        eps = list(
                            pool.map(
                                lambda i: dataset.episode(set_name, i, augment),
                                idxs,
                            )
                        )
                        out.put(_stack(eps))
                out.put(None)
            except BaseException as exc:  # surface worker errors to consumer
                out.put(exc)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        try:
            while True:
                item = out.get()
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def get_train_batches(
        self, total_batches: int, augment_images: bool = False
    ) -> Iterator[Batch]:
        self.dataset.update_train_seed(self.total_train_iters_produced)
        # advanced once per generator CALL, not per batch — reference quirk
        # the resume arithmetic depends on (data.py:598-602)
        self.total_train_iters_produced += self.tasks_per_batch
        return self._batches("train", total_batches, augment_images)

    def get_val_batches(
        self, total_batches: int, augment_images: bool = False
    ) -> Iterator[Batch]:
        return self._batches("val", total_batches, augment_images)

    def get_test_batches(
        self, total_batches: int, augment_images: bool = False
    ) -> Iterator[Batch]:
        return self._batches("test", total_batches, augment_images)
