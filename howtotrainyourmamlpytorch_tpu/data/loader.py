"""Batched, prefetching, resume-exact episodic data loader.

Replaces the reference's torch ``DataLoader`` wrapper
(``MetaLearningSystemDataLoader`` data.py:555-637) with a thread-pool episode
builder + bounded prefetch queue feeding numpy batches:

* batch = ``num_devices * batch_size * samples_per_iter`` tasks stacked on a
  leading task axis (data.py:580) — the axis the device mesh shards;
* task seeds: ``seed[set] + idx`` with idx sequential from 0 per generator
  (shuffle=False determinism, data.py:544-549,581);
* resume: ``continue_from_iter`` advances the produced-task counter by
  ``current_iter * tasks_per_batch`` (data.py:583-588) and every
  ``get_train_batches`` call advances it by one batch worth (data.py:598-602)
  — both quirks preserved so a resumed run continues the task stream at
  exactly the next unseen task, like the reference;
* val/test streams restart from their fixed seed every call, so validation
  tasks are identical across epochs and the test stream equals the val stream
  (data.py:136-142,538-539) — properties the best-val selection and ensemble
  eval rely on.
"""

from __future__ import annotations

import concurrent.futures
import queue
import threading
import time
from typing import Dict, Iterator, NamedTuple, Optional, Tuple, Union

import numpy as np

from ..config import MAMLConfig
from ..resilience import elastic, faults
from ..telemetry import tracing
from . import datasets as ds
from .episodes import Episode, IndexEpisode, sample_episode, sample_episode_indices

Batch = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]


class ProducerCrashedError(RuntimeError):
    """The background episode-producer thread died with an exception. The
    original exception is chained (``__cause__``); every subsequent
    ``get_*_batches`` pull re-raises so a run can never silently train on
    a starved stream."""


class IndexBatch(NamedTuple):
    """A stacked batch of ``IndexEpisode``s — the index-only H2D form the
    ``data_placement='device'`` tier ships instead of pixels (a few KB vs
    ~100 MB of float32 for a Mini-ImageNet 12-task batch).

    ``gather[t, i, j]`` is the flat-store row of task t / episode-class i /
    sample j (columns ``[:spc]`` support, ``[spc:]`` target); ``rot_k`` the
    per-(task, class) rot90 draws. ``set_name``/``augment`` tell the system
    which resident store to gather from and whether the (static) rotation
    branch is traced in. Labels are implicit (sample (t, i, j) has label i)
    and are materialised on device by an iota — see ``target_labels`` for the
    host-side copy the test ensemble needs.
    """

    gather: np.ndarray  # (tasks, n_way, spc + nts) int32
    rot_k: np.ndarray  # (tasks, n_way) int32
    seeds: np.ndarray  # (tasks,) int64
    set_name: str
    augment: bool

    def target_labels(self, num_target_samples: int) -> np.ndarray:
        """(tasks, n_way, nts) int32 — the host-side ``y_target`` twin."""
        tasks, n, _ = self.gather.shape
        return np.tile(
            np.arange(n, dtype=np.int32)[None, :, None],
            (tasks, 1, num_target_samples),
        )


AnyBatch = Union[Batch, IndexBatch]


class FewShotEpisodicDataset:
    """Index + splits + per-set seed state (FewShotLearningDatasetParallel,
    data.py:111-552, minus torch)."""

    def __init__(self, cfg: MAMLConfig, cache_dir: Optional[str] = None):
        self.cfg = cfg
        cache_dir = cache_dir or cfg.cache_dir or "."
        self.init_seed = ds.draw_stream_seeds(cfg)
        self.seed = dict(self.init_seed)
        index, idx_to_label, label_to_idx = ds.load_class_index(cfg, cache_dir)
        self.splits = ds.split_classes(cfg, index, idx_to_label, self.seed["val"])
        # flat uint8 stores (preprocess.FlatStore) back the non-host
        # data_placement tiers; the per-class views served to the pixel path
        # are slices of the same memmap, so all tiers read identical bytes
        self.flat_stores: Dict[str, "FlatStore"] = {}
        if cfg.use_mmap_cache:
            from .preprocess import build_mmap_cache_flat

            self.flat_stores = build_mmap_cache_flat(cfg, self.splits, cache_dir)
            self.splits = {
                name: fs.views() for name, fs in self.flat_stores.items()
            }
        elif cfg.load_into_memory:
            self.splits = ds.preload_to_memory(cfg, self.splits)
        # class-key ordering per set is the dict insertion order — the
        # ordering rng.choice sees in the reference (data.py:486)
        self.class_keys = {
            name: np.array(list(classes.keys()))
            for name, classes in self.splits.items()
        }
        for name, keys in self.class_keys.items():
            if len(keys) < cfg.num_classes_per_set:
                raise ValueError(
                    f"set {name!r} has {len(keys)} classes < "
                    f"num_classes_per_set={cfg.num_classes_per_set}"
                )

    def update_train_seed(self, current_iter: int) -> None:
        """switch_set('train', it): seed = init + it (data.py:536-542)."""
        self.seed["train"] = self.init_seed["train"] + current_iter

    def episode(self, set_name: str, idx: int, augment: bool) -> Episode:
        return sample_episode(
            self.cfg,
            self.splits[set_name],
            self.class_keys[set_name],
            seed=self.seed[set_name] + idx,
            augment=augment,
        )

    def episode_indices(self, set_name: str, idx: int) -> IndexEpisode:
        """The index-only form of ``episode`` (same RNG stream, no pixels) —
        the ``data_placement='device'`` sampler."""
        flat = self.flat_stores[set_name]
        return sample_episode_indices(
            self.cfg,
            flat.offsets,
            flat.sizes,
            self.class_keys[set_name],
            seed=self.seed[set_name] + idx,
        )

    def episode_uint8(self, set_name: str, idx: int, augment: bool) -> Episode:
        """One task's raw uint8 pixels, gathered + rotated on host, decode
        deferred to the device (``data_placement='uint8_stream'``).

        rot90 on integer pixels commutes with the elementwise decode, so
        device-decoding this Episode reproduces the float path bit-exactly
        (and moves 4x fewer H2D bytes).
        """
        cfg = self.cfg
        ie = self.episode_indices(set_name, idx)
        x = self.flat_stores[set_name].data[ie.gather]  # (n, spc+nts, h, w, c)
        if augment and "omniglot" in cfg.dataset_name:
            x = np.stack(
                [
                    np.rot90(x[i], k=int(k), axes=(1, 2))
                    for i, k in enumerate(ie.rot_k)
                ]
            )
        x = np.ascontiguousarray(x)
        spc, nts = cfg.num_samples_per_class, cfg.num_target_samples
        y = np.tile(
            np.arange(cfg.num_classes_per_set, dtype=np.int32)[:, None],
            (1, spc + nts),
        )
        return Episode(
            x_support=x[:, :spc],
            x_target=x[:, spc:],
            y_support=y[:, :spc],
            y_target=y[:, spc:],
            seed=ie.seed,
        )


def _stack(episodes) -> Batch:
    return (
        np.stack([e.x_support for e in episodes]),
        np.stack([e.x_target for e in episodes]),
        np.stack([e.y_support for e in episodes]),
        np.stack([e.y_target for e in episodes]),
        np.array([e.seed for e in episodes], np.int64),
    )


class MetaLearningDataLoader:
    """Batch generators with background prefetch (data.py:555-637).

    Multi-host: each process builds only its slice of every global batch
    (``shard_id``/``num_shards``, defaulting to the JAX process grid). Episode
    seeds are computed from *global* task indices, so the union of all hosts'
    slices is bit-identical to a single-host run — the TPU-native analogue of
    the reference's DataLoader-feeds-DataParallel layout (data.py:580).

    Elastic resume: the episode->process assignment is the pure block
    partition of ``resilience/elastic.py`` (never derived from device
    enumeration), and the experiment state checkpoints a *global* episode
    cursor that ``__init__`` consumes (``episode_cursor=``) — so a run
    resumed on a DIFFERENT process count replays the identical global
    episode sequence, merely re-partitioned (validated against the
    iteration-derived value to catch global-batch-size drift).
    """

    def __init__(self, cfg: MAMLConfig, current_iter: int = 0,
                 cache_dir: Optional[str] = None,
                 shard_id: Optional[int] = None,
                 num_shards: Optional[int] = None,
                 episode_cursor: Optional[int] = None):
        self.cfg = cfg
        self.tasks_per_batch = cfg.global_tasks_per_batch
        if num_shards is None:
            if shard_id is not None:
                raise ValueError("shard_id given without num_shards")
            import jax

            num_shards = jax.process_count()
            shard_id = jax.process_index()
        self.shard_id = shard_id or 0
        self.num_shards = max(1, num_shards)
        # the topology-invariant partition (resilience/elastic.py): this
        # process's contiguous block of every global batch — a pure
        # function of (tasks_per_batch, shard_id, num_shards), so a resume
        # on a different process count re-partitions the SAME global
        # episode sequence instead of silently changing it
        self._shard_lo, self._shard_hi = elastic.shard_slice(
            self.tasks_per_batch, self.shard_id, self.num_shards
        )
        self.tasks_per_shard = self._shard_hi - self._shard_lo
        self.dataset = FewShotEpisodicDataset(cfg, cache_dir)
        self.total_train_iters_produced = 0
        # input-pipeline telemetry (bench.py `input_pipeline` + the per-epoch
        # telemetry `stream` records): cumulative episode-assembly seconds,
        # producer-queue stall seconds (time the producer sat blocked in
        # put() against a full queue), post-put queue depth sum (mean depth
        # ~= prefetch headroom: near-full means the producer outruns the
        # consumer, near-empty means the device is starved), batches
        # produced. Guarded by a lock: train and val producers can overlap.
        self._stats_lock = threading.Lock()
        self.stream_stats = {
            "assembly_s": 0.0, "stall_s": 0.0, "depth_sum": 0.0, "batches": 0,
        }
        self._last_producer_thread: Optional[threading.Thread] = None
        # causal tracing (telemetry/tracing.py): the builder swaps in its
        # run tracer when tracing_level='on'; the default disabled tracer
        # keeps every producer/consumer seam at one attribute check.
        # Producer-thread spans (sample / stack / queue_put) correlate
        # with the consumer_wait spans the pull side emits, so a starved
        # device shows up as consumer_wait intervals opposite a
        # stall-free producer timeline (and vice versa)
        self.tracer = tracing.NULL_TRACER
        # a producer thread's death is latched here and re-raised from
        # every subsequent batch pull (not only the generator that owned
        # the thread): a dead producer means the episode stream is broken
        # for good, and the consumer must fail loudly rather than block on
        # an empty queue until the watchdog fires
        self._producer_error: Optional[BaseException] = None
        if episode_cursor is not None:
            # the checkpointed GLOBAL episode cursor is authoritative: a
            # mismatch with the iteration-derived value means the global
            # batch size changed between the run that wrote the checkpoint
            # and this one — the deterministic stream cannot be continued
            # equivalently, so fail loudly instead of training on a
            # silently different episode sequence
            derived = elastic.episode_cursor_for_iter(
                current_iter, self.tasks_per_batch
            )
            if int(episode_cursor) != derived:
                raise ValueError(
                    f"checkpointed episode cursor {int(episode_cursor)} does "
                    f"not equal current_iter * tasks_per_batch = "
                    f"{current_iter} * {self.tasks_per_batch} = {derived}; "
                    "the global meta-batch size changed since the "
                    "checkpoint was written, which breaks deterministic "
                    "episode-stream resume (restore the original "
                    "batch_size/num_of_gpus/samples_per_iter, or restart "
                    "from_scratch)"
                )
            self.total_train_iters_produced += int(episode_cursor)
        else:
            self.continue_from_iter(current_iter)

    def pop_stream_stats(self) -> Dict[str, float]:
        """Return and reset the cumulative producer telemetry."""
        with self._stats_lock:
            out = dict(self.stream_stats)
            self.stream_stats = {
                "assembly_s": 0.0, "stall_s": 0.0, "depth_sum": 0.0,
                "batches": 0,
            }
        return out

    def continue_from_iter(self, current_iter: int) -> None:
        """Fast-forward the train stream after resume (data.py:583-588)."""
        self.total_train_iters_produced += current_iter * self.tasks_per_batch

    def _episode_builder(self, set_name: str, augment: bool):
        """(build, stack) for the configured placement tier: host float32
        pixels, raw uint8 pixels (device decode), or index-only tensors."""
        placement = self.cfg.data_placement
        dataset = self.dataset
        if placement == "device":
            def stack_indices(eps) -> IndexBatch:
                return IndexBatch(
                    gather=np.stack([e.gather for e in eps]),
                    rot_k=np.stack([e.rot_k for e in eps]),
                    seeds=np.array([e.seed for e in eps], np.int64),
                    set_name=set_name,
                    augment=augment,
                )

            return (
                lambda i: dataset.episode_indices(set_name, i),
                stack_indices,
            )
        if placement == "uint8_stream":
            return (
                lambda i: dataset.episode_uint8(set_name, i, augment),
                _stack,
            )
        return lambda i: dataset.episode(set_name, i, augment), _stack

    def _batches(
        self, set_name: str, total_batches: int, augment: bool
    ) -> Iterator[AnyBatch]:
        cfg = self.cfg
        tpb = self.tasks_per_batch
        workers = max(1, cfg.num_dataprovider_workers)
        prefetch = max(1, cfg.prefetch_batches)
        out: "queue.Queue" = queue.Queue(maxsize=prefetch)
        stop = threading.Event()
        build, stack = self._episode_builder(set_name, augment)

        # this process's block of every global batch — the topology-
        # invariant partition computed once in __init__ (elastic.shard_slice)
        lo, hi = self._shard_lo, self._shard_hi

        def put(item) -> bool:
            # timed/poll put, NOT a bare out.put(): when the consumer
            # abandons the generator while this thread is parked in a
            # blocking put() against a full queue, the consumer-side
            # stop.set() is never observed and the thread leaks forever —
            # poll so `stop` always gets a look-in
            while not stop.is_set():
                try:
                    out.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                tracer = self.tracer
                with concurrent.futures.ThreadPoolExecutor(workers) as pool:
                    for b in range(total_batches):
                        if stop.is_set():
                            return
                        # injectable seam (resilience/faults.py): fires in
                        # THIS thread, once per produced batch — a 'raise'
                        # fault here is the dead-producer scenario
                        faults.fire("producer")
                        # this host's slice of the global batch's task range
                        idxs = range(b * tpb + lo, b * tpb + hi)
                        t0 = time.perf_counter()
                        # producer spans (tracing on): sample = episode
                        # building across the worker pool, stack = the
                        # numpy batch assembly, queue_put = blocked-on-
                        # full-queue time — the producer-side timeline
                        # the consumer_wait spans correlate against
                        sample_span = tracer.start_span(
                            "sample", cat="data", set=set_name, batch=b,
                        )
                        episodes = list(pool.map(build, idxs))
                        tracer.end_span(sample_span)
                        stack_span = tracer.start_span(
                            "stack", cat="data", set=set_name, batch=b,
                        )
                        batch = stack(episodes)
                        tracer.end_span(stack_span)
                        t1 = time.perf_counter()
                        put_span = tracer.start_span(
                            "queue_put", cat="data", set=set_name, batch=b,
                        )
                        if not put(batch):
                            tracer.end_span(put_span, outcome="abandoned")
                            return
                        tracer.end_span(put_span)
                        t2 = time.perf_counter()
                        with self._stats_lock:
                            self.stream_stats["assembly_s"] += t1 - t0
                            self.stream_stats["stall_s"] += t2 - t1
                            self.stream_stats["depth_sum"] += out.qsize()
                            self.stream_stats["batches"] += 1
                put(None)
            except BaseException as exc:  # surface worker errors to consumer
                # latch FIRST: even if the enqueue below never lands (full
                # queue + consumer mid-dispatch, or a consumer that only
                # returns after this thread is gone), the next pull — of
                # this generator or any later one — sees the error instead
                # of blocking on an empty queue until the watchdog fires
                self._producer_error = exc
                put(exc)

        thread = threading.Thread(target=producer, daemon=True)
        self._last_producer_thread = thread  # exposed for tests/diagnostics
        thread.start()
        try:
            while True:
                wait_span = None
                try:
                    while True:
                        try:
                            # timed poll, NOT a bare blocking get: a
                            # producer that died between enqueues (or whose
                            # error enqueue lost the race) would otherwise
                            # park the consumer forever
                            item = out.get(timeout=0.2)
                            break
                        except queue.Empty:
                            if wait_span is None:
                                # a consumer stall span: opened only once
                                # the first poll came up empty, so a hot
                                # queue emits nothing (and the off path is
                                # one attribute check inside start_span)
                                wait_span = self.tracer.start_span(
                                    "consumer_wait", cat="data",
                                    set=set_name,
                                )
                            if self._producer_error is not None:
                                self._raise_producer_error()
                            if not thread.is_alive():
                                # died without latching anything (e.g.
                                # killed interpreter-side): still never
                                # block forever
                                raise ProducerCrashedError(
                                    f"episode producer thread for set "
                                    f"{set_name!r} died without delivering "
                                    "a batch or an error"
                                )
                            continue
                finally:
                    self.tracer.end_span(wait_span)
                if item is None:
                    return
                if isinstance(item, BaseException):
                    self._producer_error = item
                    self._raise_producer_error()
                yield item
        finally:
            stop.set()

    def _raise_producer_error(self):
        exc = self._producer_error
        raise ProducerCrashedError(
            f"episode producer thread crashed: {exc!r}"
        ) from exc

    def _check_producer(self) -> None:
        """Re-raise a latched producer death at the next stream request —
        the consumer-facing half of the dead-producer fix (see
        ``_producer_error``)."""
        if self._producer_error is not None:
            self._raise_producer_error()

    def get_train_batches(
        self, total_batches: int, augment_images: bool = False
    ) -> Iterator[AnyBatch]:
        self._check_producer()
        self.dataset.update_train_seed(self.total_train_iters_produced)
        # advanced once per generator CALL, not per batch — reference quirk
        # the resume arithmetic depends on (data.py:598-602)
        self.total_train_iters_produced += self.tasks_per_batch
        return self._batches("train", total_batches, augment_images)

    def get_val_batches(
        self, total_batches: int, augment_images: bool = False
    ) -> Iterator[AnyBatch]:
        self._check_producer()
        return self._batches("val", total_batches, augment_images)

    def get_test_batches(
        self, total_batches: int, augment_images: bool = False
    ) -> Iterator[AnyBatch]:
        self._check_producer()
        return self._batches("test", total_batches, augment_images)
