from . import datasets, episodes, loader
from .loader import FewShotEpisodicDataset, MetaLearningDataLoader
