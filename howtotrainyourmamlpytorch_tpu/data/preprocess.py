"""Preprocessed, memory-mapped uint8 image cache — the TPU-rate input path.

The reference decodes every image with PIL at episode-sampling time
(data.py:374-395), which would starve a TPU (SURVEY.md §7). Its only remedy
is the full in-RAM float32 preload (data.py:213-230), which costs 4 bytes per
subpixel of host RAM (≈5 GB for Mini-ImageNet at 84×84×3 × 60k images).

This module decodes the dataset ONCE into a disk-backed uint8 memmap (¼ the
RAM-preload footprint, shared between processes by the page cache) and serves
per-class array views from it. Bit-exactness with the PIL path is preserved
because both supported decode pipelines are integer-valued right up to their
final cast:

* Omniglot: ``Image.open(p).resize(LANCZOS)`` yields a binary/uint8 image;
  the reference then casts to float32 WITHOUT rescaling (data.py:383-387), so
  ``uint8 -> float32`` reproduces it exactly;
* ImageNet-family: ``resize().convert("RGB")`` yields uint8 RGB; the
  reference divides by 255 (data.py:389-391), so ``uint8 / 255`` reproduces
  it exactly.

Cache layout per (dataset, set, shape):
  ``<cache_dir>/<dataset>_<set>_<h>x<w>x<c>.u8``    raw (n, h, w, c) uint8
  ``<cache_dir>/<dataset>_<set>_<h>x<w>x<c>.json``  class order/counts + done flag

Builds write pid-suffixed temp files and ``os.replace`` them into place, and
the done-flagged meta lands only after the data file: a killed or truncated
build is rebuilt, never served half-written, and concurrent builders
(multi-process data loading, multi-host on shared storage) each land a
complete identical file instead of interleaving writes. Corrupt/truncated
meta JSON reads as "no cache" rather than crashing the run.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import glob
import json
import os
import time
from typing import Dict, List, NamedTuple

import numpy as np

# temps older than this are swept even if their embedded pid looks alive:
# the pid may be reused by an unrelated process, or belong to another host
# on shared storage. Active builders rewrite their memmap continuously, so
# hours-old mtime means abandoned. Overridable for tests.
_STALE_TEMP_AGE_S = float(os.environ.get("MAML_STALE_TEMP_AGE_S", 6 * 3600))

from ..config import MAMLConfig
from .datasets import ClassIndex
from .episodes import load_image_uint8

# uint8 views are decoded per-sample in episodes.decode_cached; the shared
# integer decode lives in episodes.load_image_uint8 so the PIL path and this
# cache are bit-identical by construction


class FlatStore(NamedTuple):
    """One set's images as a single flat uint8 array plus the class layout.

    ``data`` is the (total, h, w, c) memmap the cache serves per-class views
    of; ``offsets[key] + j`` is the flat row of class ``key``'s j-th image.
    This is the indexable form the device-resident pipeline uploads to HBM
    once (ops/device_pipeline.py): episode sampling then only needs
    ``offsets``/``sizes`` to turn per-class draws into flat gather indices.
    """

    data: np.ndarray  # (total, h, w, c) uint8
    offsets: Dict[str, int]  # class key -> first flat row
    sizes: Dict[str, int]  # class key -> image count

    def views(self) -> Dict[str, np.ndarray]:
        """Per-class array views (the classic ``build_set_cache`` shape)."""
        return {
            key: self.data[off : off + self.sizes[key]]
            for key, off in self.offsets.items()
        }


def _cache_base(cfg: MAMLConfig, cache_dir: str, set_name: str) -> str:
    h, w, c = cfg.im_shape
    return os.path.join(
        cache_dir, f"{cfg.dataset_name}_{set_name}_{h}x{w}x{c}"
    )


def build_set_cache(
    cfg: MAMLConfig, classes: ClassIndex, cache_dir: str, set_name: str,
    workers: int = 8,
) -> Dict[str, np.ndarray]:
    """Build (or reuse) one set's memmap cache; return class -> uint8 view."""
    return build_set_cache_flat(cfg, classes, cache_dir, set_name, workers).views()


def build_set_cache_flat(
    cfg: MAMLConfig, classes: ClassIndex, cache_dir: str, set_name: str,
    workers: int = 8,
) -> FlatStore:
    """Build (or reuse) one set's memmap cache; return its ``FlatStore``.

    Class order and per-class counts are recorded so a cache is only reused
    when it matches the current split exactly.
    """
    base = _cache_base(cfg, cache_dir, set_name)
    data_path, meta_path = base + ".u8", base + ".json"
    h, w, c = cfg.im_shape
    order: List[str] = list(classes.keys())
    counts = [len(classes[k]) for k in order]
    total = sum(counts)

    meta = None
    if os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (json.JSONDecodeError, OSError):
            meta = None  # truncated/corrupt meta == no meta: rebuild
    fresh = not (
        meta
        and meta.get("done")
        and meta.get("classes") == order
        and meta.get("counts") == counts
        and os.path.exists(data_path)
        and os.path.getsize(data_path) == total * h * w * c
    )
    if fresh:
        os.makedirs(cache_dir, exist_ok=True)
        # invalidate any stale meta BEFORE touching the data file: a rebuild
        # killed mid-decode must never be servable under the old meta.
        # A concurrent builder may have removed it first — that's fine.
        with contextlib.suppress(FileNotFoundError):
            os.remove(meta_path)
        # build into pid-suffixed temps and os.replace into place: a killed
        # build leaves only temps (never a half-written live file), and two
        # processes racing on the same cache each land a complete, identical
        # (deterministic decode) file instead of interleaving writes
        # disk hygiene: a SIGKILLed builder leaves its pid-suffixed temps
        # behind forever (finally never ran); sweep stale ones for this cache
        # base before building. A concurrent builder's temp is LIVE, not
        # stale — deleting it would unlink the file under its memmap and
        # crash its os.replace — so only remove temps whose pid is provably
        # dead (ProcessLookupError). EPERM means the pid EXISTS under another
        # uid: treat as alive. Pid liveness is host-local and pids get
        # reused, so additionally remove temps untouched for
        # _STALE_TEMP_AGE_S regardless of pid — covers remote builders on
        # shared storage and pid-reuse leaks; a live builder's memmap writes
        # keep refreshing its temp's mtime long before that threshold.
        now = time.time()  # lint-ok: MP007 compared against file st_mtime, which is wall clock
        for path_base in (data_path, meta_path):
            for stale in glob.glob(f"{path_base}.tmp.*"):
                try:
                    pid = int(stale.rsplit(".", 1)[-1])
                except ValueError:
                    continue  # unrecognized suffix: leave it alone
                dead = False
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    dead = True
                except OSError:  # EPERM et al.: process exists
                    pass
                if not dead:
                    try:
                        age = now - os.path.getmtime(stale)
                    except OSError:
                        continue  # vanished under us: nothing to clean
                    dead = age > _STALE_TEMP_AGE_S
                if dead:
                    with contextlib.suppress(OSError):
                        os.remove(stale)
        data_tmp = f"{data_path}.tmp.{os.getpid()}"
        meta_tmp = f"{meta_path}.tmp.{os.getpid()}"
        try:
            mm = np.memmap(
                data_tmp, mode="w+", dtype=np.uint8, shape=(total, h, w, c)
            )
            jobs = []
            offset = 0
            for key, count in zip(order, counts):
                for j, path in enumerate(classes[key]):
                    jobs.append((offset + j, path))
                offset += count
            last_touch = time.monotonic()
            with concurrent.futures.ThreadPoolExecutor(workers) as pool:
                for idx, arr in pool.map(
                    lambda job: (job[0], load_image_uint8(cfg, job[1])),
                    jobs,
                    chunksize=64,
                ):
                    mm[idx] = arr
                    # memmap stores don't reliably refresh mtime (mmap
                    # writes bypass the file API; NFS especially) — touch
                    # explicitly so the age-based stale sweep above sees a
                    # live build as live
                    if time.monotonic() - last_touch > 60:
                        with contextlib.suppress(OSError):
                            os.utime(data_tmp)
                        last_touch = time.monotonic()
            mm.flush()
            del mm
            ours_landed = True
            try:
                os.replace(data_tmp, data_path)
            except FileNotFoundError:
                # our temp was swept as stale (e.g. this process sat
                # SIGSTOPped past the age threshold while another builder
                # rebuilt the cache). If a right-sized data file is in
                # place, serve it for THIS call only — but do NOT stamp the
                # done meta for a file we didn't write: that would bless a
                # size-matching-but-garbage file forever. The concurrent
                # builder stamps its own meta; absent that, the next call
                # revalidates and rebuilds.
                ours_landed = False
                if not (
                    os.path.exists(data_path)
                    and os.path.getsize(data_path) == total * h * w * c
                ):
                    raise
            if ours_landed:
                with open(meta_tmp, "w") as f:
                    json.dump(
                        {"classes": order, "counts": counts, "done": True}, f
                    )
                os.replace(meta_tmp, meta_path)
        finally:
            for tmp in (data_tmp, meta_tmp):
                with contextlib.suppress(FileNotFoundError):
                    os.remove(tmp)

    mm = np.memmap(data_path, mode="r", dtype=np.uint8, shape=(total, h, w, c))
    offsets: Dict[str, int] = {}
    sizes: Dict[str, int] = {}
    offset = 0
    for key, count in zip(order, counts):
        offsets[key] = offset
        sizes[key] = count
        offset += count
    return FlatStore(data=mm, offsets=offsets, sizes=sizes)


def build_mmap_cache(
    cfg: MAMLConfig,
    splits: Dict[str, ClassIndex],
    cache_dir: str,
) -> Dict[str, Dict[str, np.ndarray]]:
    """Memmap-cache every set of the split (the drop-in alternative to
    ``datasets.preload_to_memory``)."""
    return {
        set_name: store.views()
        for set_name, store in build_mmap_cache_flat(cfg, splits, cache_dir).items()
    }


def build_mmap_cache_flat(
    cfg: MAMLConfig,
    splits: Dict[str, ClassIndex],
    cache_dir: str,
) -> Dict[str, FlatStore]:
    """Memmap-cache every set of the split, keeping the flat form the
    device-resident pipeline needs (set -> ``FlatStore``)."""
    return {
        set_name: build_set_cache_flat(cfg, classes, cache_dir, set_name)
        for set_name, classes in splits.items()
    }
