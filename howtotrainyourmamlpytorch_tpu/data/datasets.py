"""Dataset indexing, caching, splitting, and in-RAM preloading.

Host-side re-implementation of the reference's dataset management
(``FewShotLearningDatasetParallel`` data.py:111-552, minus task sampling
which lives in ``episodes.py``):

* directory walk -> class->filepath index with corrupt-image screening,
  cached as JSON (data.py:302-328, 234-267). The cache is written to a
  configurable ``cache_dir`` instead of ``$DATASET_DIR`` (the reference
  writes next to the dataset — data.py:247-250 — which breaks on read-only
  dataset mounts);
* class splits: pre-split directory layout (train/val/test dirs,
  data.py:178-189) or ratio split over val-seed-shuffled classes
  (data.py:190-211);
* optional full in-RAM preload with a worker pool (data.py:213-230; the
  reference forks a process pool — we use threads, which JAX requires and
  which PIL's GIL-releasing decode parallelizes fine) —
  mandatory for TPU-rate training, where per-episode PIL decoding would
  starve the device (SURVEY.md §7).

Seed discipline is replicated exactly (data.py:132-142): the working seeds
are drawn via ``RandomState(seed).randint(1, 999999)`` and the *test* stream
shares the val seed, so test tasks equal val-sampling with the same stream
(a reference property the eval protocol depends on).
"""

from __future__ import annotations

import concurrent.futures
import json
import os
from typing import Dict, List, Tuple

import numpy as np

from ..config import MAMLConfig
from .episodes import load_image

ClassIndex = Dict[str, List[str]]  # class key -> image file paths


def draw_stream_seeds(cfg: MAMLConfig) -> Dict[str, int]:
    """Initial per-set task-stream seeds (data.py:132-142).

    test deliberately shares val's seed — the reference builds ``init_seed``
    with ``args.val_seed`` for both 'val' and 'test' (data.py:141-142).
    """
    val_seed = int(np.random.RandomState(cfg.val_seed).randint(1, 999999))
    train_seed = int(np.random.RandomState(cfg.train_seed).randint(1, 999999))
    return {"train": train_seed, "val": val_seed, "test": val_seed}


def _cache_paths(cfg: MAMLConfig, cache_dir: str) -> Tuple[str, str, str]:
    os.makedirs(cache_dir, exist_ok=True)
    return (
        os.path.join(cache_dir, f"{cfg.dataset_name}.json"),
        os.path.join(cache_dir, f"map_to_label_name_{cfg.dataset_name}.json"),
        os.path.join(cache_dir, f"label_name_to_map_{cfg.dataset_name}.json"),
    )


def _label_from_path(cfg: MAMLConfig, filepath: str):
    """Class label from folder structure (data.py:363-372)."""
    bits = filepath.split("/")
    label = "/".join(bits[idx] for idx in cfg.indexes_of_folders_indicating_class)
    return int(label) if cfg.labels_as_int else label


def _screen_image(filepath: str):
    """Corrupt-image check (data.py:280-300): openable -> keep."""
    from PIL import Image

    try:
        Image.open(filepath)
        return filepath
    except Exception:
        return None


def scan_dataset(cfg: MAMLConfig) -> Tuple[Dict[str, List[str]], Dict, Dict]:
    """Walk the dataset dir and build the class index (data.py:302-335)."""
    raw_paths: List[str] = []
    labels = set()
    for subdir, _, files in os.walk(cfg.dataset_path):
        for file in files:
            if file.lower().endswith((".jpeg", ".png", ".jpg")):
                filepath = os.path.abspath(os.path.join(subdir, file))
                raw_paths.append(filepath)
                labels.add(_label_from_path(cfg, filepath))
    labels = sorted(labels)
    idx_to_label = {idx: label for idx, label in enumerate(labels)}
    label_to_idx = {label: idx for idx, label in enumerate(labels)}
    index: Dict[str, List[str]] = {str(idx): [] for idx in idx_to_label}
    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
        for ok in ex.map(_screen_image, raw_paths, chunksize=256):
            if ok is not None:
                index[str(label_to_idx[_label_from_path(cfg, ok)])].append(ok)
    return index, idx_to_label, label_to_idx


def load_class_index(cfg: MAMLConfig, cache_dir: str):
    """JSON-cached class index (data.py:234-267), cache under ``cache_dir``."""
    index_file, i2l_file, l2i_file = _cache_paths(cfg, cache_dir)
    if cfg.reset_stored_filepaths and os.path.exists(index_file):
        os.remove(index_file)
    if os.path.exists(index_file):
        with open(index_file) as f:
            index = json.load(f)
        with open(i2l_file) as f:
            idx_to_label = {int(k): v for k, v in json.load(f).items()}
        with open(l2i_file) as f:
            label_to_idx = json.load(f)
        return index, idx_to_label, label_to_idx
    index, idx_to_label, label_to_idx = scan_dataset(cfg)
    with open(index_file, "w") as f:
        json.dump(index, f)
    with open(i2l_file, "w") as f:
        json.dump(idx_to_label, f)
    with open(l2i_file, "w") as f:
        json.dump({str(k): v for k, v in label_to_idx.items()}, f)
    return index, idx_to_label, label_to_idx


def split_classes(
    cfg: MAMLConfig,
    index: ClassIndex,
    idx_to_label: Dict[int, str],
    val_stream_seed: int,
) -> Dict[str, ClassIndex]:
    """Train/val/test class partition (data.py:169-211).

    Pre-split mode: the first path component of the label names the set
    (data.py:178-189). Ratio mode: classes shuffled with the *drawn* val seed
    then cut at the cumulative split fractions (data.py:190-211) — preserving
    class order exactly so task streams match the reference's.
    """
    if cfg.sets_are_pre_split:
        splits: Dict[str, ClassIndex] = {}
        for key, paths in index.items():
            label = idx_to_label[int(key)]
            set_name, class_label = label.split("/")[0], label.split("/")[1]
            splits.setdefault(set_name, {})[class_label] = paths
        return splits
    rng = np.random.RandomState(seed=val_stream_seed)
    keys = list(index.keys())
    order = np.arange(len(keys), dtype=np.int32)
    rng.shuffle(order)
    keys = [keys[i] for i in order]
    total = len(keys)
    n_train = int(cfg.train_val_test_split[0] * total)
    n_val = int(sum(cfg.train_val_test_split[:2]) * total)
    return {
        "train": {k: index[k] for k in keys[:n_train]},
        "val": {k: index[k] for k in keys[n_train:n_val]},
        "test": {k: index[k] for k in keys[n_val:]},
    }


def _load_class(args) -> Tuple[str, np.ndarray]:
    cfg, class_key, paths = args
    images = np.stack([load_image(cfg, p) for p in paths]).astype(np.float32)
    return class_key, images


def preload_to_memory(
    cfg: MAMLConfig, splits: Dict[str, ClassIndex]
) -> Dict[str, Dict[str, np.ndarray]]:
    """Decode every image once into float32 arrays (data.py:213-230)."""
    loaded: Dict[str, Dict[str, np.ndarray]] = {}
    for set_name, classes in splits.items():
        loaded[set_name] = {}
        jobs = [(cfg, k, v) for k, v in classes.items()]
        with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
            for class_key, images in ex.map(_load_class, jobs):
                loaded[set_name][class_key] = images
    return loaded
