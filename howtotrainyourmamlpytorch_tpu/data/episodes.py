"""Episode (task) sampling with the reference's exact RNG discipline.

Re-implementation of ``FewShotLearningDatasetParallel.get_set``
(data.py:478-524) plus image loading (:374-395) and augmentation (:17-108),
producing NHWC numpy arrays ready for the device.

RNG sequence per task, bit-for-bit the reference's
(``np.random.RandomState(seed)``):

1. ``choice(class_keys, num_classes_per_set, replace=False)``  (:486-488)
2. ``shuffle(selected_classes)``                                (:488)
3. ``randint(0, 4, num_classes_per_set)`` rotation k per class  (:489-490)
4. per class: ``choice(class_size, spc + targets, replace=False)`` (:499-500)

Faithful quirks preserved:
* Omniglot pixels are float32 in [0, 255] — ``load_image`` resizes with
  LANCZOS and does NOT rescale (data.py:383-387), and torchvision's ToTensor
  doesn't rescale float arrays;
* ImageNet-family images are /255 then ImageNet-stat normalized regardless of
  the augment flag (data.py:98-106);
* the rotation k is always drawn (advancing the stream) but only applied for
  train-time Omniglot (augment flag, experiment_builder.py:60).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Union

import numpy as np

from ..config import MAMLConfig

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


class Episode(NamedTuple):
    """One few-shot task, NHWC. Shapes: x (n_way, k, h, w, c), y (n_way, k)."""

    x_support: np.ndarray
    x_target: np.ndarray
    y_support: np.ndarray
    y_target: np.ndarray
    seed: int


def load_image_uint8(cfg: MAMLConfig, image_path: str) -> np.ndarray:
    """Decode one image to its integer (pre-cast/pre-scale) uint8 HWC form.

    The single home of the decode pipeline (reference data.py:374-395 up to
    but not including the final dtype cast / 255-division, which
    ``decode_cached`` applies): Omniglot is LANCZOS-resized (1-bit sources
    decode to bool -> 0/1 uint8, the reference's unrescaled values); others
    are resized + RGB-converted. Both the direct PIL path (``load_image``)
    and the mmap cache (preprocess.py) decode through here, so they are
    bit-identical by construction.
    """
    from PIL import Image

    image = Image.open(image_path)
    if "omniglot" in cfg.dataset_name:
        image = image.resize(
            (cfg.image_height, cfg.image_width), resample=Image.LANCZOS
        )
        arr = np.asarray(image)
        if arr.dtype == bool:  # 1-bit PNGs decode to bool
            arr = arr.astype(np.uint8)
        if cfg.image_channels == 1 and arr.ndim == 2:
            arr = arr[:, :, None]
    else:
        image = image.resize((cfg.image_height, cfg.image_width)).convert("RGB")
        arr = np.asarray(image)
    if arr.dtype != np.uint8:
        raise ValueError(
            f"{image_path!r} decodes to {arr.dtype}, not uint8 — only 8-bit "
            f"(or 1-bit) sources are supported, like the reference's datasets"
        )
    return arr


def load_image(cfg: MAMLConfig, image_path: str) -> np.ndarray:
    """Decode one image to float32 HWC (data.py:374-395).

    Omniglot: LANCZOS resize, values left unrescaled (reference quirk).
    Others: resize, RGB, /255.
    """
    return decode_cached(cfg, load_image_uint8(cfg, image_path))


def decode_cached(cfg: MAMLConfig, arr: np.ndarray) -> np.ndarray:
    """Finish decoding a uint8 cache entry to the reference's float values.

    The mmap cache (preprocess.py) stores images in their integer form; the
    reference's final step is a plain float32 cast for Omniglot (data.py:
    383-387 — values stay in their integer range) and /255 for everything
    else (:389-391).
    """
    if "omniglot" in cfg.dataset_name:
        out = arr.astype(np.float32)
    else:
        out = arr.astype(np.float32) / 255.0
    if cfg.reverse_channels:
        # RGB->BGR flip on the decoded-but-unnormalized values, the
        # reference's preprocess_data (data.py:442-457) which runs after
        # load_batch's decode/scale and before get_set's normalization
        out = np.ascontiguousarray(out[..., ::-1])
    return out


def augment_stack(
    cfg: MAMLConfig, images: np.ndarray, k: int, augment: bool
) -> np.ndarray:
    """The rng-free transform rules on an (n, h, w, c) stack — the single
    home of the omniglot/imagenet pipelines (data.py:55-108), shared by the
    per-image path and the vectorized array-store fast path.
    """
    name = cfg.dataset_name
    if "omniglot" in name:
        if augment:
            images = np.rot90(images, k=k, axes=(1, 2))
        return np.ascontiguousarray(images)
    if "imagenet" in name:
        return (images - IMAGENET_MEAN) / IMAGENET_STD
    return images


def augment_image(
    cfg: MAMLConfig,
    image: np.ndarray,
    k: int,
    augment: bool,
    rng: np.random.RandomState = None,
) -> np.ndarray:
    """Per-image transform pipeline (data.py:55-108), HWC in/out.

    Omniglot train: rot90 by k (class-wise). ImageNet family: ImageNet-stat
    normalize (train == eval). CIFAR family: random crop + horizontal flip at
    train time, then mean/std normalize — the reference uses torchvision's
    global RNG for these; we use the episode RNG so tasks stay deterministic.
    """
    if "cifar" not in cfg.dataset_name:
        return augment_stack(cfg, image[None], k, augment)[0]
    if augment and rng is not None:
        padded = np.pad(image, ((4, 4), (4, 4), (0, 0)), mode="constant")
        top = rng.randint(0, 9)
        left = rng.randint(0, 9)
        image = padded[top : top + 32, left : left + 32]
        if rng.randint(0, 2):
            image = image[:, ::-1].copy()
    mean = np.asarray(cfg.classification_mean, np.float32)
    std = np.asarray(cfg.classification_std, np.float32)
    return (image - mean) / std


InMemoryClass = np.ndarray  # (num_images, h, w, c)
ClassStore = Dict[str, Union[list, InMemoryClass]]  # paths or decoded arrays


class IndexEpisode(NamedTuple):
    """One few-shot task as flat-store indices only — the index-only H2D
    form of ``Episode`` (data_placement='device'/'uint8_stream').

    ``gather[i, j]`` is the flat row (into a ``preprocess.FlatStore``) of the
    j-th sample of episode-class i; columns ``[:spc]`` are support,
    ``[spc:]`` target. ``rot_k[i]`` is class i's rot90 draw (always drawn —
    stream parity — applied only for train-time Omniglot). Labels need no
    tensor at all: sample (i, j) has label i by construction.
    """

    gather: np.ndarray  # (n_way, spc + nts) int32
    rot_k: np.ndarray  # (n_way,) int32
    seed: int


def sample_episode_indices(
    cfg: MAMLConfig,
    offsets: Dict[str, int],
    sizes: Dict[str, int],
    class_keys: np.ndarray,
    seed: int,
) -> IndexEpisode:
    """Draw one task as gather indices into a flat store.

    Bit-for-bit the same four-draw RNG discipline as ``sample_episode`` (see
    module docstring) — the per-class draw is over ``sizes[key]``, exactly
    the ``len(store)`` the pixel path uses — so for any seed,
    ``store.data[gather]`` is the pixel path's pre-decode gather, identically.
    CIFAR is excluded (config-time check): its per-image crop/flip draws from
    the episode RNG mid-stream, which an index-only emission cannot replay.
    """
    rng = np.random.RandomState(seed)
    selected = rng.choice(class_keys, size=cfg.num_classes_per_set, replace=False)
    rng.shuffle(selected)
    k_list = rng.randint(0, 4, size=cfg.num_classes_per_set)

    spc, nts = cfg.num_samples_per_class, cfg.num_target_samples
    rows = np.empty((cfg.num_classes_per_set, spc + nts), np.int32)
    for episode_label, class_key in enumerate(selected):
        sample_idx = rng.choice(sizes[class_key], size=spc + nts, replace=False)
        rows[episode_label] = offsets[class_key] + sample_idx
    return IndexEpisode(
        gather=rows, rot_k=k_list.astype(np.int32), seed=seed
    )


def sample_episode(
    cfg: MAMLConfig,
    classes: ClassStore,
    class_keys: np.ndarray,
    seed: int,
    augment: bool,
) -> Episode:
    """Draw one task (data.py:478-524).

    :param classes: class key -> image paths (lazy decode) or a pre-decoded
        (n, h, w, c) array (the in-RAM path, data.py:405-410).
    :param class_keys: the class key list in the reference's ordering —
        MUST match the reference's dict insertion order for stream parity.
    """
    rng = np.random.RandomState(seed)
    selected = rng.choice(class_keys, size=cfg.num_classes_per_set, replace=False)
    rng.shuffle(selected)
    k_list = rng.randint(0, 4, size=cfg.num_classes_per_set)

    spc, nts = cfg.num_samples_per_class, cfg.num_target_samples
    # CIFAR's random crop/flip draws from the episode rng per image, so only
    # the rng-free pipelines take the vectorized fast path
    vectorizable = "cifar" not in cfg.dataset_name
    x_images = []
    y_labels = []
    for episode_label, class_key in enumerate(selected):
        store = classes[class_key]
        sample_idx = rng.choice(len(store), size=spc + nts, replace=False)
        k = int(k_list[episode_label])
        if isinstance(store, np.ndarray) and vectorizable:
            # fast path: one fancy-index gather + the shared stack-level
            # transform (identical rules to the per-image path by
            # construction — augment_image delegates to augment_stack)
            imgs = store[sample_idx]
            if imgs.dtype == np.uint8:  # mmap-cache entries: finish decode
                imgs = decode_cached(cfg, imgs)
            x_images.append(
                np.ascontiguousarray(augment_stack(cfg, imgs, k, augment))
            )
        else:
            imgs = []
            for si in sample_idx:
                if isinstance(store, np.ndarray):
                    img = store[si]
                    if img.dtype == np.uint8:
                        img = decode_cached(cfg, img)
                else:
                    img = load_image(cfg, store[si])
                imgs.append(
                    augment_image(cfg, img, k=k, augment=augment, rng=rng)
                )
            x_images.append(np.stack(imgs))
        y_labels.append(np.full(spc + nts, episode_label, np.int32))

    x = np.stack(x_images).astype(np.float32)  # (n, spc+nts, h, w, c)
    y = np.stack(y_labels)
    return Episode(
        x_support=x[:, :spc],
        x_target=x[:, spc:],
        y_support=y[:, :spc],
        y_target=y[:, spc:],
        seed=seed,
    )
