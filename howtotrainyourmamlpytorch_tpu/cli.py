"""CLI: config -> model -> data -> experiment (ref:
train_maml_system.py:8-15).

Usage:
    python train_maml_system.py --name_of_args_json_file experiment_config/x.json
    python train_maml_system.py --experiment_name foo --dataset_name omniglot_dataset ...

Any MAMLConfig field can be overridden on the command line; a JSON config
file (reference format) supplies the rest.

The ``inspect`` subcommand is the telemetry reader
(tools/telemetry_cli.py): summarize / tail / diff / validate a run's
``logs/telemetry.jsonl`` —

    python -m howtotrainyourmamlpytorch_tpu.cli inspect summary LOG
    python -m howtotrainyourmamlpytorch_tpu.cli inspect diff LOG_A LOG_B

It is dispatched before any jax-importing module loads, so inspection
works on a machine with nothing but the repo and numpy installed.

The ``trace`` subcommand (tools/trace_cli.py — pure stdlib, also
dispatched jax-free) renders a run's schema-v10 ``span`` records as a
loadable Chrome/Perfetto trace-event JSON plus a critical-path summary
(the serving queue/assemble/dispatch/sync latency decomposition per
(program, bucket, shots), the train/data span profile, and any
on-demand device-profile windows):

    python -m howtotrainyourmamlpytorch_tpu.cli trace LOG
    python -m howtotrainyourmamlpytorch_tpu.cli trace LOG --out run.trace.json

The ``slo`` subcommand (tools/slo_cli.py — stdlib plus the jax-free
serving metrics module, also dispatched jax-free) is the offline SLO
report: it replays a serving log's schema-v12 ``deadline`` records
through the same tracker the live ``/metrics`` endpoint runs (miss
rate, error budget, multi-window burn rates, per-replica misses) and
cross-checks the log's end-of-run ``slo`` record against the replay;
``--fleet`` merges a serve-bench ``--fleet`` run's per-host logs
(auto-discovered ``root.hostNN.ext`` siblings) into one ts-sorted
stream replayed through a single tracker, reported per HOST:

    python -m howtotrainyourmamlpytorch_tpu.cli slo LOG [--json]
    python -m howtotrainyourmamlpytorch_tpu.cli slo --fleet GATEWAY_LOG

The ``lint`` subcommand (analysis/lint.py — pure stdlib, also dispatched
jax-free) runs the repo-specific JAX-pitfall linter; the ``audit``
subcommand (tools/audit_cli.py — needs jax) statically verifies the
program contracts (donation / no-transfer / dtype policy / op census) on
the jitted program family — and, with ``--mesh RxC``, the SPMD
performance contracts (sharding / per-axis collective census / static
HBM budget / roofline) with the family compiled under a real hybrid
(data, task) mesh:

    python -m howtotrainyourmamlpytorch_tpu.cli lint
    python -m howtotrainyourmamlpytorch_tpu.cli audit [--pin]
    python -m howtotrainyourmamlpytorch_tpu.cli audit --mesh 1x8 [--pin]

The ``serve-bench`` subcommand (serving/bench.py — needs jax) is the
load generator for the adapt-on-request serving engine: it drives
mixed-bucket synthetic traffic through a ``ServingEngine`` under a
strict retrace gate and prints one JSON line with adaptation-latency
p50/p95, tenants/sec, per-dispatch H2D bytes and cache hit rate
(optionally writing schema-v12 ``serving`` telemetry records with
``--telemetry PATH``; ``--ingest {f32,uint8,index}`` selects the ingest
tier, ``--repeat-tenant-fraction`` mixes adapted-params-cache hits in,
``--export-dir`` warms from AOT artifacts, ``--replicas N`` drives an
N-replica shared-nothing pool through the cache-affinity router — the
line gains aggregate + per-replica throughput — and ``--rollover``
exercises the zero-downtime checkpoint-rollover lifecycle mid-load,
serving/replica.py + router.py + refresh.py). ``--arrival
poisson|bursty|zipf --rate R`` switches it OPEN-LOOP (a fixed-seed
arrival schedule submitted against the wall clock — the queueing-
collapse regime the closed loop cannot produce) and ``--deadline-ms``
arms per-request deadline accounting: deadline records in the log, an
``slo`` block in the line, burn-rate gauges on ``--metrics-port``.
``--fleet H`` scales past one process: H fleet-host subprocesses (one
``ReplicaSet`` + affinity router each, serving/fleet.py) behind one
HTTP gateway (serving/gateway.py — framed binary wire schema reusing
the ingest encodings, fleet-wide consistent-hash cache affinity,
admission control + deadline shedding + priority tiers at the edge,
health-checked membership with deterministic re-homing), driven
open-loop through real sockets; ``--kill-host-at K`` SIGKILLs a host
mid-run to exercise re-homing, and the line gains a ``fleet`` block
(admitted p50/p95/p99, goodput, shed/re-home/stranded counts). The ``serve-export``
subcommand (serving/export.py — needs jax) writes those artifacts: the
warmed (bucket x shots) program ladder serialized to a versioned dir
keyed by device-kind/dtype/config-fingerprint, which a later engine
start deserializes with zero XLA compilations:

    python -m howtotrainyourmamlpytorch_tpu.cli serve-bench --fast
    python -m howtotrainyourmamlpytorch_tpu.cli serve-bench \
        --config experiment_config/exp.json \
        --checkpoint experiment/saved_models --telemetry /tmp/serving.jsonl
    python -m howtotrainyourmamlpytorch_tpu.cli serve-export --fast \
        --out /tmp/serve_artifacts
    python -m howtotrainyourmamlpytorch_tpu.cli serve-bench --fast \
        --export-dir /tmp/serve_artifacts

The ``tune`` subcommand (analysis/autotune.py) is the roofline-driven
step autotuner: it sweeps (conv_impl x pad_channels x remat_policy x
meta_accum_steps) with bench.py's harness (one subprocess per point),
ranks the points by measured step time cross-checked against the static
roofline predictions, and writes the device-kind-keyed ``TUNING.json``
that ``config``'s ``'auto'`` resolution consults — making the measured
winner the default lowering on that hardware:

    python -m howtotrainyourmamlpytorch_tpu.cli tune
    python -m howtotrainyourmamlpytorch_tpu.cli tune --fast --out /tmp/t.json

Exit codes: 0 on success; ``resilience.PREEMPT_EXIT_CODE`` (75) when a
SIGTERM/SIGINT preemption was drained gracefully (emergency checkpoint on
disk — restart with ``continue_from_epoch=latest`` to resume at the exact
iteration); nonzero tracebacks for crashes; 128+signum only for signals
the graceful path could not handle (SIGKILL, or
``handle_preemption_signals=false``).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from .config import MAMLConfig, _coerce_bool


def get_args(argv=None) -> MAMLConfig:
    parser = argparse.ArgumentParser(
        description="TPU-native MAML++ training and inference system"
    )
    parser.add_argument("--name_of_args_json_file", type=str, default="None")
    for f in dataclasses.fields(MAMLConfig):
        if f.name == "name_of_args_json_file":
            continue
        parser.add_argument(f"--{f.name}", type=str, default=None)
    ns = parser.parse_args(argv)
    overrides = {
        k: v for k, v in vars(ns).items()
        if v is not None and k != "name_of_args_json_file"
    }
    # cast strings to the declared field types; bools accept the reference's
    # "true"/"false" strings (parser_utils.py:63-66), lists accept JSON
    types = {f.name: f.type for f in dataclasses.fields(MAMLConfig)}
    for k, v in list(overrides.items()):
        t = str(types.get(k, "str"))
        if t == "int" or t.startswith("Optional[int"):
            overrides[k] = int(v)
        elif t == "float":
            overrides[k] = float(v)
        elif t == "bool":
            coerced = _coerce_bool(v)
            if not isinstance(coerced, bool):
                parser.error(f"--{k} expects 'true' or 'false', got {v!r}")
            overrides[k] = coerced
        elif t.startswith("List[") or t.startswith("Tuple["):
            try:
                parsed = json.loads(v)
            except json.JSONDecodeError:
                parsed = None
            if not isinstance(parsed, list):
                parser.error(
                    f"--{k} expects a JSON list (e.g. \"[0.7, 0.2, 0.1]\"), "
                    f"got {v!r}"
                )
            overrides[k] = parsed
    if ns.name_of_args_json_file != "None":
        return MAMLConfig.from_json_file(ns.name_of_args_json_file, **overrides)
    return MAMLConfig(**overrides)


def main(argv=None):
    args = sys.argv[1:] if argv is None else list(argv)
    if args and args[0] == "inspect":
        # telemetry inspect/diff: pure stdlib + numpy — dispatched before
        # the jax-heavy training imports below
        from .tools.telemetry_cli import main as telemetry_main

        raise SystemExit(telemetry_main(args[1:]))
    if args and args[0] == "trace":
        # span-timeline renderer (Chrome/Perfetto trace + critical-path
        # summary): pure stdlib, dispatched jax-free like inspect
        from .tools.trace_cli import main as trace_main

        raise SystemExit(trace_main(args[1:]))
    if args and args[0] == "slo":
        # offline SLO report (tools/slo_cli.py — stdlib + the jax-free
        # serving.metrics tracker): replays a log's deadline records
        # into error-budget / burn-rate terms, dispatched jax-free
        from .tools.slo_cli import main as slo_main

        raise SystemExit(slo_main(args[1:]))
    if args and args[0] == "lint":
        # repo-specific JAX-pitfall linter: pure stdlib, jax-free
        from .analysis.lint import main as lint_main

        raise SystemExit(lint_main(args[1:]))
    if args and args[0] == "audit":
        # program-contract auditor (compiles programs: needs jax)
        from .tools.audit_cli import main as audit_main

        raise SystemExit(audit_main(args[1:]))
    if args and args[0] == "serve-bench":
        # closed-loop load generator for the adapt-on-request serving
        # engine (serving/bench.py — compiles programs: needs jax)
        from .serving.bench import main as serve_bench_main

        raise SystemExit(serve_bench_main(args[1:]))
    if args and args[0] == "serve-export":
        # AOT-export the serving program ladder to a versioned artifact
        # dir (serving/export.py — compiles programs: needs jax); a
        # later ServingEngine.warmup() deserializes it with ZERO XLA
        # compilations instead of paying the multi-second compile bill
        from .serving.export import main as serve_export_main

        raise SystemExit(serve_export_main(args[1:]))
    if args and args[0] == "tune":
        # roofline-driven step autotuner: jax-free in THIS process (every
        # sweep point is a bench.py subprocess), so dispatch before the
        # jax-heavy training imports below
        from .analysis.autotune import main as tune_main

        raise SystemExit(tune_main(args[1:]))
    from .data.loader import MetaLearningDataLoader
    from .experiment.builder import ExperimentBuilder
    from .experiment.system import MAMLFewShotClassifier
    from .parallel.distributed import initialize_distributed
    from .utils.dataset_tools import maybe_unzip_dataset

    cfg = get_args(args)
    initialize_distributed()  # no-op unless a multi-host coordinator is set
    import jax

    # dataset bootstrap: fail fast before paying model init; on pods only the
    # primary extracts (shared DATASET_DIR). The outcome (incl. the
    # cache-invalidation flag a re-extraction sets) is broadcast so non-primary
    # hosts fail alongside the primary instead of hanging at a barrier, and so
    # every host agrees on whether to rebuild the path-index cache.
    bootstrap_err = None
    if jax.process_index() == 0:
        try:
            maybe_unzip_dataset(cfg)
        except Exception as exc:
            bootstrap_err = exc
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        ok, reset = multihost_utils.broadcast_one_to_all(
            np.array(
                [bootstrap_err is None, cfg.reset_stored_filepaths], np.int32
            )
        )
        cfg.reset_stored_filepaths = bool(reset)
        if not ok:
            raise (
                bootstrap_err
                if bootstrap_err is not None
                else RuntimeError("dataset bootstrap failed on the primary host")
            )
    elif bootstrap_err is not None:
        raise bootstrap_err
    model = MAMLFewShotClassifier(cfg)
    builder = ExperimentBuilder(cfg, model, MetaLearningDataLoader)
    builder.run_experiment()


if __name__ == "__main__":
    main()
