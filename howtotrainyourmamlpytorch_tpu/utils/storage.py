"""Experiment folders, CSV/JSON metrics storage.

Functional equivalent of the reference's ``utils/storage.py`` (:1-128):
``build_experiment_folder`` (:49-66) creates ``saved_models/ logs/
visual_outputs/``; ``save_statistics`` (:18-29) appends rows to a summary
CSV; ``save_to_json``/``load_from_json`` (:8-16) mirror the JSON metrics
dump (experiment_builder.py:364-365).
"""

from __future__ import annotations

import csv
import json
import os
from typing import Dict, Iterable, List, Tuple

from ..resilience import faults


def build_experiment_folder(experiment_name: str, root: str = ".") -> Tuple[str, str, str]:
    """Create <root>/<name>/{saved_models,logs,visual_outputs} (ref :49-66)."""
    base = os.path.abspath(os.path.join(root, experiment_name))
    saved_models = os.path.join(base, "saved_models")
    logs = os.path.join(base, "logs")
    samples = os.path.join(base, "visual_outputs")
    for d in (saved_models, logs, samples):
        os.makedirs(d, exist_ok=True)
    return saved_models, logs, samples


def save_statistics(
    log_dir: str,
    line_to_add: Iterable,
    filename: str = "summary_statistics.csv",
    create: bool = False,
) -> str:
    """Append one row (header row when ``create``) to the stats CSV (ref :18-29)."""
    faults.fire("stats_write")  # injectable seam (resilience/faults.py)
    summary_filename = os.path.join(log_dir, filename)
    mode = "w" if create else "a"
    with open(summary_filename, mode) as f:
        writer = csv.writer(f)
        writer.writerow(list(line_to_add))
    return summary_filename


def load_statistics(log_dir: str, filename: str = "summary_statistics.csv") -> Dict[str, List[str]]:
    """Read the stats CSV back into {column: [values]} (ref :31-46)."""
    path = os.path.join(log_dir, filename)
    with open(path) as f:
        rows = list(csv.reader(f))
    if not rows or not rows[0]:
        # name the cause instead of the reference's bare rows[0] IndexError:
        # an empty/headerless stats CSV means a crash truncated it (or a
        # foreign file landed under logs/) and resume cannot trust it
        raise ValueError(
            f"stats CSV {path} is empty or has no header row — it was "
            "likely truncated by a crash mid-write; delete it (or resume "
            "with continue_from_epoch='from_scratch') to regenerate"
        )
    keys = rows[0]
    data: Dict[str, List[str]] = {k: [] for k in keys}
    for row in rows[1:]:
        for k, v in zip(keys, row):
            data[k].append(v)
    return data


def save_to_json(filename: str, dict_to_store: dict) -> None:
    """Atomic JSON dump: write a sibling tmp file, fsync, ``os.replace``.

    ``summary_statistics.json`` is rewritten whole every epoch; a crash
    mid-write under the old truncate-in-place form left invalid JSON that
    broke resume. The tmp+replace swap means readers only ever see the old
    or the new complete file.
    """
    faults.fire("json_write")  # injectable seam (resilience/faults.py)
    path = os.path.abspath(filename)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dict_to_store, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_from_json(filename: str) -> dict:
    with open(filename) as f:
        return json.load(f)
