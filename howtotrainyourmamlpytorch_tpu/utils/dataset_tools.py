"""Dataset bootstrap: unpack-and-validate before training starts.

Re-implementation of the reference's ``utils/dataset_tools.py:4-56``
(``maybe_unzip_dataset`` / ``unzip_file``): if the dataset directory is
missing, extract ``$DATASET_DIR/<name>.tar.bz2``; then validate the image
file count for the known datasets (Omniglot 1623x20, Mini-ImageNet 100x600,
dataset_tools.py:36-38) and delete-and-retry once on mismatch (:49-51).

Differences from the reference: extraction uses Python's ``tarfile`` instead
of shelling out to ``tar -I pbzip2`` (no external binary dependency; bz2 is
stdlib), and the re-extract loop is bounded (one retry) instead of unbounded
recursion.
"""

from __future__ import annotations

import os
import shutil
import tarfile

EXPECTED_COUNTS = {
    "omniglot_dataset": 1623 * 20,
    "mini_imagenet": 100 * 600,
    "mini_imagenet_pkl": 3,
}

_IMAGE_EXTS = (".jpeg", ".jpg", ".png", ".pkl")


def count_dataset_files(dataset_path: str) -> int:
    total = 0
    for _, _, files in os.walk(dataset_path):
        total += sum(1 for f in files if f.lower().endswith(_IMAGE_EXTS))
    return total


def expected_count(dataset_name: str):
    """Known-dataset file count, or None for user datasets (:41-47)."""
    if dataset_name == "omniglot_dataset":
        return EXPECTED_COUNTS["omniglot_dataset"]
    if "mini_imagenet_pkl" in dataset_name:
        return EXPECTED_COUNTS["mini_imagenet_pkl"]
    if "mini_imagenet" in dataset_name:
        return EXPECTED_COUNTS["mini_imagenet"]
    return None


def unzip_file(archive_path: str, dest_dir: str) -> None:
    """Extract a .tar.bz2 archive (dataset_tools.py:54-56)."""
    with tarfile.open(archive_path, "r:bz2") as tf:
        tf.extractall(dest_dir, filter="data")


def maybe_unzip_dataset(cfg) -> None:
    """Ensure ``cfg.dataset_path`` exists with the right file count.

    Mutates ``cfg.reset_stored_filepaths`` to True after a fresh extraction
    so stale path caches are rebuilt (dataset_tools.py:27).
    """
    dataset_path = cfg.dataset_path.rstrip("/")
    dataset_dir = os.environ.get(
        "DATASET_DIR", os.path.dirname(dataset_path) or "."
    )
    archive = os.path.join(dataset_dir, f"{cfg.dataset_name}.tar.bz2")
    expected = expected_count(cfg.dataset_name)
    for attempt in range(2):
        if not os.path.exists(dataset_path):
            if not os.path.exists(archive):
                raise FileNotFoundError(
                    f"dataset folder {dataset_path!r} missing and no archive "
                    f"at {os.path.abspath(archive)}; place the dataset as "
                    f"explained in README.md"
                )
            print(f"[dataset] extracting {archive} -> {dataset_dir}", flush=True)
            unzip_file(archive, dataset_dir)
            cfg.reset_stored_filepaths = True
            if not os.path.exists(dataset_path):
                raise RuntimeError(
                    f"extracted {archive} but {dataset_path!r} still does not "
                    f"exist — the archive's top-level folder must be named "
                    f"{os.path.basename(dataset_path)!r}"
                )
        if expected is None:
            return  # user-provided dataset: no count contract
        total = count_dataset_files(dataset_path)
        if total == expected:
            return
        if not os.path.exists(archive):
            # never delete the user's only copy: re-extraction is impossible
            raise RuntimeError(
                f"dataset {cfg.dataset_name!r} has {total} files, expected "
                f"{expected}, and no archive exists at "
                f"{os.path.abspath(archive)} to re-extract from; refusing to "
                f"delete the existing folder"
            )
        print(
            f"[dataset] file count {total} != expected {expected}; "
            f"removing and re-extracting", flush=True,
        )
        shutil.rmtree(dataset_path, ignore_errors=True)
    raise RuntimeError(
        f"dataset {cfg.dataset_name!r} failed count validation after "
        f"re-extraction (expected {expected})"
    )
