"""Tracing / profiling hooks — first-class, unlike the reference.

The reference's observability is tqdm strings and an ``epoch_run_time``
column (experiment_builder.py:131-132,233); there is no profiler integration
anywhere (SURVEY.md §5). Here:

* ``maybe_trace`` — context manager starting a JAX/XLA profiler trace
  (viewable in TensorBoard / Perfetto) when a trace dir is configured;
* ``TraceWindow`` — scheduled trace capture: profile train iterations
  [M, M+N) of a chosen epoch without code edits (config
  ``profile_epoch`` / ``profile_start_step`` / ``profile_num_steps``);
* ``StepTimer`` — cheap host-side wall-clock stats per training iteration,
  surfaced as ``train_iters_per_sec`` / ``train_step_time_ms`` epoch metrics.
"""

from __future__ import annotations

import contextlib
import random
import time
from typing import Callable, Dict, Iterator, Optional


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Wrap a region in a jax.profiler trace when ``trace_dir`` is set."""
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class TraceWindow:
    """Schedules ONE jax profiler trace window over the train loop.

    The window covers iterations ``[start_step, start_step + num_steps)``
    of epoch ``epoch``; ``epoch=-1`` means "of THIS run", i.e. counted by
    the run-local step counter regardless of resume epoch — the legacy
    ``profile_trace_dir`` behaviour (iteration 0 is compile, so
    ``start_step`` defaults to 1 upstream). Chunked dispatch
    (``steps_per_dispatch``) advances counters by k per call, so every
    comparison is ``>=``, never ``==``; the stop side counts steps actually
    observed since the trace started. ``on_event(action, **fields)`` (when
    given) reports start/stop transitions to the telemetry sink.
    """

    def __init__(
        self,
        trace_dir: str,
        num_steps: int = 5,
        epoch: int = -1,
        start_step: int = 1,
        on_event: Optional[Callable[..., None]] = None,
    ):
        self.trace_dir = trace_dir
        self.num_steps = max(1, int(num_steps))
        self.epoch = int(epoch)
        self.start_step = max(0, int(start_step))
        self.on_event = on_event
        self.active = False
        self.done = False
        self._start_basis = 0

    def _start(self, basis: int) -> None:
        import jax

        jax.profiler.start_trace(self.trace_dir)
        self.active = True
        self._start_basis = basis
        if self.on_event is not None:
            self.on_event("start", trace_dir=self.trace_dir, at_step=basis)

    def _stop(self, sync: Optional[Callable[[], None]]) -> None:
        import jax

        if sync is not None:
            # dispatches are asynchronous — drain the device before stopping
            # so the trace actually contains the profiled steps
            sync()
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        if self.on_event is not None:
            self.on_event("stop", trace_dir=self.trace_dir)

    def step(
        self,
        epoch: int,
        step_in_epoch: int,
        step_in_run: int,
        sync: Optional[Callable[[], None]] = None,
    ) -> None:
        """Call before each train dispatch with the pre-dispatch counters."""
        if not self.trace_dir or self.done:
            return
        if self.epoch < 0:
            basis, in_window_epoch = step_in_run, True
        else:
            basis, in_window_epoch = step_in_epoch, epoch == self.epoch
        if not self.active:
            if in_window_epoch and basis >= self.start_step:
                self._start(basis)
        elif not in_window_epoch or basis >= self._start_basis + self.num_steps:
            # left the target epoch, or captured the requested steps
            self._stop(sync)

    def close(self, sync: Optional[Callable[[], None]] = None) -> None:
        """Stop a still-open window (run ended/paused/raised mid-capture) —
        the trace only materialises at stop."""
        if self.active:
            self._stop(sync)


class StepTimer:
    """Rolling per-step wall-time statistics (host-side, negligible cost).

    Keeps a bounded reservoir of per-step durations for percentiles: the
    first ``RESERVOIR`` steps of an epoch are stored exactly (epochs are
    100-500 iterations, so in practice every step), later ones replace a
    random slot — p50/p95/p99 stay representative at any epoch length.
    """

    RESERVOIR = 4096

    def __init__(self) -> None:
        self._last: Optional[float] = None
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        self._samples: list = []
        self._rng = random.Random(0)

    def tick(self) -> None:
        """Call once per completed step."""
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self.count += 1
            self.total += dt
            self.min = min(self.min, dt)
            self.max = max(self.max, dt)
            if len(self._samples) < self.RESERVOIR:
                self._samples.append(dt)
            else:  # reservoir sampling: replace slot j only if j lands in it
                j = self._rng.randrange(self.count)
                if j < self.RESERVOIR:
                    self._samples[j] = dt
        self._last = now

    def reset(self) -> None:
        self.__init__()

    def _percentile(self, sorted_samples, q: float) -> float:
        idx = min(
            len(sorted_samples) - 1, int(round(q * (len(sorted_samples) - 1)))
        )
        return sorted_samples[idx]

    def summary(self, prefix: str = "train") -> Dict[str, float]:
        if self.count == 0:
            return {}
        mean = self.total / self.count
        out = {
            f"{prefix}_step_time_ms": mean * 1e3,
            f"{prefix}_step_time_min_ms": self.min * 1e3,
            f"{prefix}_step_time_max_ms": self.max * 1e3,
            f"{prefix}_iters_per_sec": 1.0 / mean if mean > 0 else 0.0,
        }
        if self._samples:
            s = sorted(self._samples)
            for q, name in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                out[f"{prefix}_step_time_{name}_ms"] = (
                    self._percentile(s, q) * 1e3
                )
        return out
