"""Tracing / profiling hooks — first-class, unlike the reference.

The reference's observability is tqdm strings and an ``epoch_run_time``
column (experiment_builder.py:131-132,233); there is no profiler integration
anywhere (SURVEY.md §5). Here:

* ``maybe_trace`` — context manager starting a JAX/XLA profiler trace
  (viewable in TensorBoard / Perfetto) when a trace dir is configured;
* ``TraceWindow`` — scheduled trace capture: profile train iterations
  [M, M+N) of a chosen epoch without code edits (config
  ``profile_epoch`` / ``profile_start_step`` / ``profile_num_steps``);
* ``OnDemandProfiler`` — RUNTIME-triggered capture: touching
  ``logs/PROFILE_REQUEST`` (optionally containing a step count) or
  sending SIGUSR2 arms a ``jax.profiler`` trace over the NEXT N train
  steps or serving dispatches — no restart, no config change — and
  reports start/stop (with the run's causal-tracing ``trace_id``) to
  telemetry so the device profile links back to the host span timeline;
* ``StepTimer`` — cheap host-side wall-clock stats per training iteration,
  surfaced as ``train_iters_per_sec`` / ``train_step_time_ms`` epoch metrics.
"""

from __future__ import annotations

import contextlib
import os
import random
import signal as _signal
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Wrap a region in a jax.profiler trace when ``trace_dir`` is set."""
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class TraceWindow:
    """Schedules ONE jax profiler trace window over the train loop.

    The window covers iterations ``[start_step, start_step + num_steps)``
    of epoch ``epoch``; ``epoch=-1`` means "of THIS run", i.e. counted by
    the run-local step counter regardless of resume epoch — the legacy
    ``profile_trace_dir`` behaviour (iteration 0 is compile, so
    ``start_step`` defaults to 1 upstream). Chunked dispatch
    (``steps_per_dispatch``) advances counters by k per call, so every
    comparison is ``>=``, never ``==``; the stop side counts steps actually
    observed since the trace started. ``on_event(action, **fields)`` (when
    given) reports start/stop transitions to the telemetry sink.
    """

    def __init__(
        self,
        trace_dir: str,
        num_steps: int = 5,
        epoch: int = -1,
        start_step: int = 1,
        on_event: Optional[Callable[..., None]] = None,
    ):
        self.trace_dir = trace_dir
        self.num_steps = max(1, int(num_steps))
        self.epoch = int(epoch)
        self.start_step = max(0, int(start_step))
        self.on_event = on_event
        self.active = False
        self.done = False
        self._start_basis = 0

    def _start(self, basis: int) -> None:
        import jax

        jax.profiler.start_trace(self.trace_dir)
        self.active = True
        self._start_basis = basis
        if self.on_event is not None:
            self.on_event("start", trace_dir=self.trace_dir, at_step=basis)

    def _stop(self, sync: Optional[Callable[[], None]]) -> None:
        import jax

        if sync is not None:
            # dispatches are asynchronous — drain the device before stopping
            # so the trace actually contains the profiled steps
            sync()
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        if self.on_event is not None:
            self.on_event("stop", trace_dir=self.trace_dir)

    def step(
        self,
        epoch: int,
        step_in_epoch: int,
        step_in_run: int,
        sync: Optional[Callable[[], None]] = None,
    ) -> None:
        """Call before each train dispatch with the pre-dispatch counters."""
        if not self.trace_dir or self.done:
            return
        if self.epoch < 0:
            basis, in_window_epoch = step_in_run, True
        else:
            basis, in_window_epoch = step_in_epoch, epoch == self.epoch
        if not self.active:
            if in_window_epoch and basis >= self.start_step:
                self._start(basis)
        elif not in_window_epoch or basis >= self._start_basis + self.num_steps:
            # left the target epoch, or captured the requested steps
            self._stop(sync)

    def close(self, sync: Optional[Callable[[], None]] = None) -> None:
        """Stop a still-open window (run ended/paused/raised mid-capture) —
        the trace only materialises at stop."""
        if self.active:
            self._stop(sync)


#: the trigger filename an operator touches under the run's logs dir
PROFILE_REQUEST_FILENAME = "PROFILE_REQUEST"


class OnDemandProfiler:
    """Runtime-triggered ``jax.profiler`` windows over dispatches.

    The scheduled ``TraceWindow`` needs the window chosen BEFORE the run;
    this is the live-incident counterpart: while a run (or a serving
    process) is misbehaving NOW, the operator either

    * writes the trigger file — ``echo 8 > logs/PROFILE_REQUEST``
      (contents: the dispatch count; empty = ``default_steps``) — or
    * sends ``SIGUSR2`` (when ``install_signal_handler()`` was called,
      main-thread processes only),

    and the NEXT ``step()`` call starts a profiler trace capturing that
    many dispatches into ``out_root/ondemand_<k>/``, stopping (after an
    optional ``sync`` drain, so the trace actually contains the
    dispatches) without any restart or config change. ``on_event`` gets
    ``('start'|'stop', trace_dir=..., steps=..., trace_id=...)`` —
    wired to the telemetry ``trace`` record, the ``trace_id`` (the run's
    causal-tracing id) is what links the device profile to the host span
    timeline in ``cli trace``.

    ``step()`` is called once per dispatch from the hot loop: the idle
    cost is one ``os.path.exists`` stat (~µs against ms-scale
    dispatches) plus a flag check. ``profiler_module`` is injectable for
    tests; default resolves ``jax.profiler`` lazily at first trigger.
    """

    def __init__(
        self,
        request_path: str,
        out_root: str,
        default_steps: int = 5,
        on_event: Optional[Callable[..., None]] = None,
        trace_id: Optional[str] = None,
        profiler_module: Any = None,
    ):
        self.request_path = request_path
        self.out_root = out_root
        self.default_steps = max(1, int(default_steps))
        self.on_event = on_event
        self.trace_id = trace_id
        self._profiler = profiler_module
        self.active = False
        self.captures = 0
        self.trace_dir: Optional[str] = None
        self._remaining = 0
        self._signal_pending = False
        self._disabled_reason: Optional[str] = None
        self._installed_signum: Optional[int] = None
        self._previous_handler: Any = None

    # -- triggers ----------------------------------------------------------

    def install_signal_handler(self, signum: int = _signal.SIGUSR2) -> bool:
        """SIGUSR2 arms a ``default_steps`` window; main thread only
        (``signal.signal``'s constraint). Returns False (and changes
        nothing) off the main thread. The handler only sets a flag — all
        profiler work happens at the next ``step()``, never in signal
        context."""
        if threading.current_thread() is not threading.main_thread():
            return False

        def _on_signal(signum_, frame):
            self._signal_pending = True

        self._previous_handler = _signal.signal(signum, _on_signal)
        self._installed_signum = signum
        return True

    def uninstall_signal_handler(self) -> None:
        """Restore the handler ``install_signal_handler`` displaced, so a
        finished run (or a test harness driving builders back to back)
        never leaks a handler that keeps this profiler alive. No-op when
        never installed or off the main thread."""
        if self._installed_signum is None:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        # signal.signal returned None when the prior handler was not
        # installed from Python — the process default is the only safe
        # restoration target there
        previous = self._previous_handler
        if previous is None:
            previous = _signal.SIG_DFL
        _signal.signal(self._installed_signum, previous)
        self._installed_signum = None
        self._previous_handler = None

    def trigger(self, num_steps: Optional[int] = None) -> None:
        """Programmatic arm (what the signal handler and tests use)."""
        self._signal_pending = True
        if num_steps is not None:
            self.default_steps = max(1, int(num_steps))

    def _poll_request(self) -> Optional[int]:
        """Consume the trigger file; returns the requested step count or
        None. A file that cannot be removed disables the file trigger
        (it would re-arm every step forever) with one stderr note — ONLY
        the file trigger: the signal/programmatic arm checks first, so
        SIGUSR2 keeps working on a broken logs dir."""
        if self._signal_pending:
            self._signal_pending = False
            return self.default_steps
        if self._disabled_reason is not None:
            return None
        if not os.path.exists(self.request_path):
            return None
        steps = self.default_steps
        try:
            with open(self.request_path) as f:
                content = f.read().strip()
            if content:
                steps = max(1, int(content))
        except (OSError, ValueError):
            pass  # unreadable/garbled request: capture the default window
        try:
            os.remove(self.request_path)
        except OSError as e:
            self._disabled_reason = repr(e)
            print(
                f"[profiling] cannot consume {self.request_path} ({e!r}); "
                "on-demand file trigger disabled for this run",
                file=sys.stderr,
                flush=True,
            )
            return None
        return steps

    # -- the per-dispatch hook ---------------------------------------------

    def step(self, sync: Optional[Callable[[], None]] = None) -> None:
        """Call once per dispatch, BEFORE enqueueing it. Starts an armed
        window, counts dispatches while one is open, and stops it (after
        ``sync``, so asynchronous dispatches land in the trace) once the
        requested count has been captured."""
        if self.active:
            self._remaining -= 1
            if self._remaining <= 0:
                self._stop(sync)
            return
        steps = self._poll_request()
        if steps is not None:
            self._start(steps)

    def close(self, sync: Optional[Callable[[], None]] = None) -> None:
        """Stop a still-open window (run ended mid-capture) — the trace
        only materialises at stop."""
        if self.active:
            self._stop(sync)

    # -- internals ---------------------------------------------------------

    def _profiler_mod(self):
        if self._profiler is None:
            import jax

            self._profiler = jax.profiler
        return self._profiler

    def _start(self, steps: int) -> None:
        self.trace_dir = os.path.join(
            self.out_root, f"ondemand_{self.captures:02d}"
        )
        try:
            self._profiler_mod().start_trace(self.trace_dir)
        except Exception as e:  # noqa: BLE001 - a diagnostic trigger must
            # never crash the run it was asked to inspect
            print(f"[profiling] on-demand trace start failed: {e!r}",
                  file=sys.stderr, flush=True)
            self.trace_dir = None
            return
        self.active = True
        self.captures += 1
        self._remaining = steps
        if self.on_event is not None:
            self.on_event(
                "start", trace_dir=self.trace_dir, steps=steps,
                trace_id=self.trace_id, on_demand=True,
            )

    def _stop(self, sync: Optional[Callable[[], None]]) -> None:
        if sync is not None:
            # dispatches are asynchronous — drain the device before
            # stopping so the trace actually contains the profiled steps
            sync()
        try:
            self._profiler_mod().stop_trace()
        except Exception as e:  # noqa: BLE001 - see _start
            print(f"[profiling] on-demand trace stop failed: {e!r}",
                  file=sys.stderr, flush=True)
        self.active = False
        if self.on_event is not None:
            self.on_event(
                "stop", trace_dir=self.trace_dir, trace_id=self.trace_id,
                on_demand=True,
            )


class StepTimer:
    """Rolling per-step wall-time statistics (host-side, negligible cost).

    Keeps a bounded reservoir of per-step durations for percentiles: the
    first ``RESERVOIR`` steps of an epoch are stored exactly (epochs are
    100-500 iterations, so in practice every step), later ones replace a
    random slot — p50/p95/p99 stay representative at any epoch length.
    """

    RESERVOIR = 4096

    def __init__(self) -> None:
        self._last: Optional[float] = None
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        self._samples: list = []
        self._rng = random.Random(0)

    def tick(self) -> None:
        """Call once per completed step."""
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self.count += 1
            self.total += dt
            self.min = min(self.min, dt)
            self.max = max(self.max, dt)
            if len(self._samples) < self.RESERVOIR:
                self._samples.append(dt)
            else:  # reservoir sampling: replace slot j only if j lands in it
                j = self._rng.randrange(self.count)
                if j < self.RESERVOIR:
                    self._samples[j] = dt
        self._last = now

    def reset(self) -> None:
        self.__init__()

    def _percentile(self, sorted_samples, q: float) -> float:
        idx = min(
            len(sorted_samples) - 1, int(round(q * (len(sorted_samples) - 1)))
        )
        return sorted_samples[idx]

    def summary(self, prefix: str = "train") -> Dict[str, float]:
        if self.count == 0:
            return {}
        mean = self.total / self.count
        out = {
            f"{prefix}_step_time_ms": mean * 1e3,
            f"{prefix}_step_time_min_ms": self.min * 1e3,
            f"{prefix}_step_time_max_ms": self.max * 1e3,
            f"{prefix}_iters_per_sec": 1.0 / mean if mean > 0 else 0.0,
        }
        if self._samples:
            s = sorted(self._samples)
            for q, name in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                out[f"{prefix}_step_time_{name}_ms"] = (
                    self._percentile(s, q) * 1e3
                )
        return out
