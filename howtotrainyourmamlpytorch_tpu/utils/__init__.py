from . import storage
