"""Deterministic retry/backoff for the experiment layer's I/O seams.

A TPU-pod run crosses a networked filesystem at every checkpoint save,
stats-CSV append and JSON mirror write; any of those can fail transiently
(NFS hiccup, GCS 5xx surfaced as OSError, disk-pressure ENOSPC that a
cleaner resolves seconds later). ``RetryPolicy`` absorbs such failures:

* retries **OSError only** — the transient I/O class (and the class the
  fault injector's ``oserror`` action raises). Logic errors
  (``RuntimeError`` etc.) propagate immediately: retrying a bug is how
  silent corruption happens;
* exponential backoff with **no jitter**: ``backoff_s * factor**(attempt-1)``
  capped at ``max_backoff_s``. Deterministic by design — the kill/resume
  equivalence tests (and any log diff) must see the same sequence every
  run; a fleet-thundering-herd concern would belong to the scheduler
  restarting whole runs, not to these per-file writes;
* an ``observer(site, attempt, max_attempts, error, backoff_s)`` hook per
  failed attempt — the builder wires it to a telemetry ``retry`` record
  plus a flight-recorder note, so a run that limped through N transient
  faults says so in its own log;
* after ``max_attempts`` failures raises ``RetriesExhaustedError`` (the
  original exception chained). The *caller* decides essentialness: the
  builder halts cleanly on an exhausted checkpoint save (data loss
  otherwise) and degrades on an exhausted stats write (skip the row, warn,
  keep training — the telemetry twin still has the epoch record).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional


class RetriesExhaustedError(RuntimeError):
    """All retry attempts for one I/O seam failed; ``site``, ``attempts``
    and the last error ride on the exception (and ``__cause__`` chains it)."""

    def __init__(self, site: str, attempts: int, last_error: BaseException):
        super().__init__(
            f"I/O seam {site!r} failed {attempts} attempt(s); "
            f"last error: {last_error!r}"
        )
        self.site = site
        self.attempts = attempts
        self.last_error = last_error


class RetryPolicy:
    """Bounded retry with deterministic exponential backoff (module doc).

    ``sleep`` is injectable so tests assert the exact backoff sequence
    without waiting it out; ``observer`` is the per-attempt telemetry hook.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        backoff_s: float = 0.5,
        factor: float = 2.0,
        max_backoff_s: float = 30.0,
        sleep: Callable[[float], None] = time.sleep,
        observer: Optional[Callable[..., None]] = None,
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if backoff_s < 0 or factor < 1.0 or max_backoff_s < 0:
            raise ValueError(
                "backoff_s/max_backoff_s must be >= 0 and factor >= 1, got "
                f"backoff_s={backoff_s}, factor={factor}, "
                f"max_backoff_s={max_backoff_s}"
            )
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self.factor = float(factor)
        self.max_backoff_s = float(max_backoff_s)
        self.sleep = sleep
        self.observer = observer

    @classmethod
    def from_config(cls, cfg, **overrides: Any) -> "RetryPolicy":
        kwargs = dict(
            max_attempts=cfg.io_retry_attempts,
            backoff_s=cfg.io_retry_backoff_s,
            factor=cfg.io_retry_backoff_factor,
        )
        kwargs.update(overrides)
        return cls(**kwargs)

    def backoff_for(self, attempt: int) -> float:
        """Seconds slept after failed attempt ``attempt`` (1-based)."""
        return min(
            self.backoff_s * self.factor ** (attempt - 1), self.max_backoff_s
        )

    def call(self, fn: Callable[[], Any], site: str) -> Any:
        """Run ``fn`` under the policy; returns its value, raises
        ``RetriesExhaustedError`` (cause chained) once the budget is spent.
        Only ``OSError`` is retried — anything else propagates on attempt 1.
        """
        last: Optional[OSError] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except OSError as e:
                last = e
                if attempt >= self.max_attempts:
                    break
                delay = self.backoff_for(attempt)
                if self.observer is not None:
                    try:
                        self.observer(
                            site=site,
                            attempt=attempt,
                            max_attempts=self.max_attempts,
                            error=repr(e),
                            backoff_s=delay,
                        )
                    except Exception:  # noqa: BLE001 - telemetry must never
                        pass           # turn a recoverable fault fatal
                if delay > 0:
                    self.sleep(delay)
        # the exhausted attempt is observed too, so the log's last `retry`
        # record shows attempt == max_attempts (the CLI counts tell the
        # whole story without cross-referencing the crash)
        if self.observer is not None:
            try:
                self.observer(
                    site=site,
                    attempt=self.max_attempts,
                    max_attempts=self.max_attempts,
                    error=repr(last),
                    backoff_s=0.0,
                )
            except Exception:  # noqa: BLE001
                pass
        raise RetriesExhaustedError(site, self.max_attempts, last) from last
