"""Resilience: fault injection, retrying I/O, preemption handling.

The training loop's headline operational property is that *induced failure
is a tested input*: every I/O seam the experiment layer crosses
(checkpoint save/restore, summary CSV/JSON writes, the loader producer
thread, the builder's dispatch loop) can be made to fail deterministically
via a ``fault_spec`` string (:mod:`resilience.faults`), transient failures
are absorbed by a deterministic retry/backoff policy
(:mod:`resilience.retry`), and a SIGTERM/SIGINT preemption drains pending
checkpoints, writes a resumable emergency checkpoint and exits with
``PREEMPT_EXIT_CODE`` so the scheduler can restart the run at the exact
iteration (the builder's preemption path + ``PreemptedError``).

Everything here is host-side: with ``fault_spec`` unset the injector is
``None`` and every seam is a single attribute check — the jitted device
programs are untouched by construction (tested).
"""

from .elastic import (  # noqa: F401
    DrainCoordinator,
    episode_cursor_for_iter,
    process_for_index,
    shard_slice,
)
from .faults import (  # noqa: F401
    FAULT_ACTIONS,
    FAULT_SITES,
    Fault,
    FaultInjector,
    active_injector,
    fire,
    install,
    parse_fault_spec,
    tick,
    uninstall,
)
from .retry import (  # noqa: F401
    RetriesExhaustedError,
    RetryPolicy,
)

#: exit code of a preemption-triggered graceful shutdown (EX_TEMPFAIL:
#: "temporary failure, retry" — distinct from crash codes and from the
#: 128+signum codes of an *unhandled* signal, so schedulers and the
#: chaos tests can tell "preempted cleanly, resume me" from "died")
PREEMPT_EXIT_CODE = 75


class PreemptedError(SystemExit):
    """Raised by the builder at the dispatch boundary after a SIGTERM/SIGINT
    preemption has been drained to disk (emergency checkpoint written,
    telemetry ``preemption`` record emitted).

    A ``SystemExit`` subclass carrying ``PREEMPT_EXIT_CODE``: uncaught, the
    process exits with the distinct preemption code (``except Exception``
    blocks can't swallow it); tests catch it by name in-process.
    """

    def __init__(self, signum: int, iter_at_preempt: int,
                 checkpoint_path: str):
        super().__init__(PREEMPT_EXIT_CODE)
        self.signum = int(signum)
        self.iter_at_preempt = int(iter_at_preempt)
        self.checkpoint_path = checkpoint_path

    def __str__(self) -> str:  # SystemExit.__str__ would print just "75"
        return (
            f"preempted by signal {self.signum} at iter "
            f"{self.iter_at_preempt}; resumable checkpoint: "
            f"{self.checkpoint_path}"
        )
