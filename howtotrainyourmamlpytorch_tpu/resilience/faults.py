"""Deterministic fault injection for the training loop's I/O seams.

A ``fault_spec`` string names *where*, *what* and *when* to fail::

    ckpt_save:oserror@iter=40,producer:raise@batch=10,signal:sigterm@iter=55

Grammar (comma-separated entries)::

    entry   := site ":" action "@" key "=" value ["x" repeat]
    site    := ckpt_save | ckpt_finalize | ckpt_restore | stats_write
             | json_write | producer | signal | barrier | drain_poll
    action  := oserror | raise | sigterm | sigint | sigkill
    key     := iter | call | batch          (batch is an alias of call)
    repeat  := how many consecutive triggers fire (default 1)

Sites are the named host-side seams the experiment layer crosses:

* ``ckpt_save``     — checkpoint save initiation (sync + async paths);
* ``ckpt_finalize`` — the async save's background finalizer, just before
  the tmp -> final swap (kill here to test crash-safe swaps);
* ``ckpt_restore``  — checkpoint load;
* ``stats_write``   — a ``summary_statistics.csv`` row append;
* ``json_write``    — the ``summary_statistics.json`` mirror write;
* ``producer``      — the loader's background episode-producer thread,
  once per produced batch (``batch=N`` = the N-th batch any producer of
  the process builds, 1-based);
* ``signal``        — evaluated at the builder's dispatch boundary
  (``tick``), not at a seam call: delivers the named signal to the own
  process, modelling a TPU-pod preemption notice (sigterm), an operator
  interrupt (sigint) or a hard kill (sigkill);
* ``barrier``       — the cross-process synchronization points of the
  collective checkpoint path (``experiment/checkpoint.py``: the pre-save
  tmp-clean barrier and the post-swap follower wait), once per barrier
  crossing per process — a sigkill here dies *inside* a checkpoint
  barrier, the scenario the bounded follower wait exists for;
* ``drain_poll``    — the elastic drain coordinator's dispatch-boundary
  poll (``resilience/elastic.py``), once per boundary in multi-process
  runs — faults here exercise a broken coordination filesystem.

Conditions: ``call=N`` (``batch=N``) matches the N-th invocation of that
seam, counted per site across the whole process — deterministic because
every seam is driven by the deterministic train loop. ``iter=N`` matches
once the builder has *completed* iteration N (the builder publishes its
counter via :func:`tick` after each dispatch). ``xK`` makes the fault
fire on K consecutive matches (e.g. ``ckpt_save:oserror@call=1x2`` fails
the first two save attempts — below a 3-attempt retry budget the run
must recover and complete).

Actions ``oserror`` (an ``OSError`` — the *retryable* class the
:mod:`resilience.retry` policy absorbs) and ``raise`` (a ``RuntimeError``
— never retried, models a logic bug) raise at the seam; the signal
actions ``os.kill`` the own pid.

With no spec installed, every seam is ``if _active is None: return`` —
one module-global attribute check, zero allocations; and since injection
lives entirely in host code, the jitted device programs are bit-identical
with and without a spec (tested in ``tests/test_faults.py``).
"""

from __future__ import annotations

import os
import signal as _signal
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

FAULT_SITES = (
    "ckpt_save",
    "ckpt_finalize",
    "ckpt_restore",
    "stats_write",
    "json_write",
    "producer",
    "signal",
    "barrier",
    "drain_poll",
)

FAULT_ACTIONS = ("oserror", "raise", "sigterm", "sigint", "sigkill")

_CONDITION_KEYS = ("iter", "call", "batch")

_SIGNALS = {
    "sigterm": _signal.SIGTERM,
    "sigint": _signal.SIGINT,
    "sigkill": _signal.SIGKILL,
}


class InjectedFaultError(OSError):
    """The ``oserror`` action: an OSError subclass so the retry policy and
    every ``except OSError`` seam treat it exactly like a real transient
    I/O failure, while postmortems can still tell it was injected."""


@dataclass
class Fault:
    site: str
    action: str
    cond_key: str  # 'iter' | 'call' ('batch' normalizes to 'call')
    cond_value: int
    repeat: int = 1
    fired: int = field(default=0, compare=False)

    def spec(self) -> str:
        """The entry's canonical spec string (round-trips through parse)."""
        key = "batch" if self.site == "producer" else self.cond_key
        out = f"{self.site}:{self.action}@{key}={self.cond_value}"
        if self.repeat != 1:
            out += f"x{self.repeat}"
        return out


def parse_fault_spec(spec: str) -> List[Fault]:
    """Parse a ``fault_spec`` string; raises ``ValueError`` naming the
    offending entry on any grammar violation (config-time validation runs
    this, so a typo'd spec fails the run before any training happens)."""
    faults: List[Fault] = []
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        head, sep, cond = entry.partition("@")
        site, sep2, action = head.partition(":")
        if not sep or not sep2:
            raise ValueError(
                f"fault_spec entry {entry!r} must look like "
                "'site:action@key=value[xN]'"
            )
        if site not in FAULT_SITES:
            raise ValueError(
                f"fault_spec entry {entry!r}: unknown site {site!r} "
                f"(known: {', '.join(FAULT_SITES)})"
            )
        if action not in FAULT_ACTIONS:
            raise ValueError(
                f"fault_spec entry {entry!r}: unknown action {action!r} "
                f"(known: {', '.join(FAULT_ACTIONS)})"
            )
        if site == "signal":
            if action not in _SIGNALS:
                raise ValueError(
                    f"fault_spec entry {entry!r}: site 'signal' takes a "
                    "signal action (sigterm|sigint|sigkill)"
                )
        elif action in _SIGNALS and action != "sigkill":
            # sigkill at a seam is legal (kill mid-finalize); delivering a
            # *handled* signal from an arbitrary seam would race the
            # handler against the seam's own control flow
            raise ValueError(
                f"fault_spec entry {entry!r}: {action} is only valid at "
                "site 'signal' (the dispatch boundary)"
            )
        key, sep3, value = cond.partition("=")
        repeat = 1
        if "x" in value:
            value, _, rep = value.partition("x")
            try:
                repeat = int(rep)
            except ValueError:
                raise ValueError(
                    f"fault_spec entry {entry!r}: repeat count {rep!r} "
                    "is not an integer"
                ) from None
        if not sep3 or key not in _CONDITION_KEYS:
            raise ValueError(
                f"fault_spec entry {entry!r}: condition must be one of "
                f"{'/'.join(_CONDITION_KEYS)}=N"
            )
        try:
            cond_value = int(value)
        except ValueError:
            raise ValueError(
                f"fault_spec entry {entry!r}: condition value {value!r} "
                "is not an integer"
            ) from None
        if cond_value < 0 or repeat < 1:
            raise ValueError(
                f"fault_spec entry {entry!r}: condition value must be >= 0 "
                "and repeat >= 1"
            )
        faults.append(Fault(
            site=site,
            action=action,
            cond_key="call" if key == "batch" else key,
            cond_value=cond_value,
            repeat=repeat,
        ))
    return faults


class FaultInjector:
    """Holds the parsed faults plus the per-site call counters and the
    builder-published iteration counter. All entry points are lock-guarded:
    the loader producer fires from its own thread while the train loop
    ticks."""

    def __init__(self, faults: List[Fault]):
        self.faults = list(faults)
        self._calls: Dict[str, int] = {}
        self._iter = -1
        self._lock = threading.Lock()

    # -- trigger evaluation -------------------------------------------------

    def fire(self, site: str) -> None:
        """One seam invocation: advance the site counter, trigger matching
        faults (raise / signal). Called by the seams themselves."""
        with self._lock:
            self._calls[site] = self._calls.get(site, 0) + 1
            n = self._calls[site]
            due = [
                f for f in self.faults
                if f.site == site and f.fired < f.repeat and (
                    (f.cond_key == "call"
                     and f.cond_value <= n < f.cond_value + f.repeat)
                    or (f.cond_key == "iter" and self._iter >= f.cond_value)
                )
            ]
            for f in due:
                f.fired += 1
        for f in due:
            self._execute(f)

    def tick(self, current_iter: int) -> None:
        """The builder's dispatch-boundary heartbeat: publish the completed
        iteration count (``iter=N`` conditions compare against it) and
        evaluate the pseudo-site ``signal`` faults."""
        with self._lock:
            self._iter = int(current_iter)
            due = [
                f for f in self.faults
                if f.site == "signal" and f.fired < f.repeat
                and f.cond_key == "iter" and self._iter >= f.cond_value
            ]
            for f in due:
                f.fired += 1
        for f in due:
            self._execute(f)

    def _execute(self, f: Fault) -> None:
        if f.action == "oserror":
            raise InjectedFaultError(
                f"injected fault {f.spec()!r} (deterministic test fault, "
                "not a real I/O failure)"
            )
        if f.action == "raise":
            raise RuntimeError(f"injected fault {f.spec()!r}")
        # signal actions: deliver to the own process. SIGKILL is never
        # handled — the process dies here, which is the point.
        os.kill(os.getpid(), _SIGNALS[f.action])


# -- module-level seam API ----------------------------------------------------
#
# The seams (storage.py, checkpoint.py, loader.py, builder.py) call these
# module functions so that with no spec installed the cost is one global
# read. The injector is process-wide state, like the checkpoint barrier:
# faults model process-level failures.

_active: Optional[FaultInjector] = None


def install(spec: str) -> Optional[FaultInjector]:
    """Install the process-wide injector from a spec string ('' or
    whitespace uninstalls). Returns the injector (None when empty)."""
    global _active
    faults = parse_fault_spec(spec or "")
    _active = FaultInjector(faults) if faults else None
    return _active


def uninstall() -> None:
    global _active
    _active = None


def active_injector() -> Optional[FaultInjector]:
    return _active


def fire(site: str) -> None:
    """Seam hook: no-op (one global read) unless an injector is installed."""
    if _active is not None:
        _active.fire(site)


def tick(current_iter: int) -> None:
    """Builder dispatch-boundary hook (see ``FaultInjector.tick``)."""
    if _active is not None:
        _active.tick(current_iter)
