"""Elastic multi-host training: coordinated preemption drain + the
topology-invariant episode schedule.

Two pieces turn the single-process preemption story (PR 6) into a
pod-grade one:

**Coordinated drain.** On a pod, the scheduler SIGTERMs *one* worker (or
each worker at a slightly different instant). Draining only the signalled
process would wedge every other process in the next collective; draining
each process at *its own* next dispatch boundary would have them reach the
collective emergency checkpoint at different iterations — a deadlock. The
:class:`DrainCoordinator` is the lightweight cross-process agreement seam:

* any signalled worker publishes a **drain request** (an atomic JSON file
  in a shared coordination directory — the experiment directory is already
  the shared-filesystem rendezvous the collective checkpoints rely on);
* the **primary** polls for requests at its dispatch boundaries and
  publishes a **drain commit** naming the agreed iteration
  ``drain_iter = primary_iter + margin`` — the margin
  (``drain_margin_iters``) covers host-loop skew (bounded to ~1 dispatch
  by the one-step-lag sync) plus one polling interval, so every process
  observes the commit *before* reaching ``drain_iter``;
* every process (primary included) polls for the commit at its dispatch
  boundaries and keeps training until ``current_iter >= drain_iter``, then
  runs the ordinary preemption drain — the emergency checkpoint is the
  *collective* ``save_checkpoint`` at the same iteration on every process,
  written once, and every process exits ``PREEMPT_EXIT_CODE``.

If a process somehow overshoots the committed iteration (a pathologically
slow shared filesystem), it drains at its own next boundary and says so
loudly; the collective save then fails *diagnosably* via the bounded
follower wait in ``experiment/checkpoint.py`` instead of hanging forever.
Every poll crosses the ``drain_poll`` fault-injection seam and every
publish the same atomic tmp+rename discipline as the checkpoints.

**Topology-invariant episode schedule.** Episode seeds are a pure function
of ``(base seed, global episode index)`` (data/episodes.py), so the only
topology-dependent thing about the stream is which *process* builds which
index. The schedule below fixes that as a pure function too:

* the global episode cursor advances ``tasks_per_batch`` per iteration
  (``episode_cursor_for_iter``) and is checkpointed in the experiment
  state, so a resumed run re-derives nothing from the current topology;
* within each global batch, process ``p`` of ``P`` owns the contiguous
  index block ``[p * tpb/P, (p+1) * tpb/P)`` (``shard_slice`` /
  ``process_for_index``). The *block* partition — rather than
  ``global_index % P`` striding — is deliberate: the global device batch
  is assembled process-major (``make_array_from_process_local_data``), so
  a block partition reproduces the exact global task order of a
  single-process run for ANY process count. The resumed global episode
  sequence is therefore bit-identical to the uninterrupted one,
  re-partitioned — a striding partition would permute tasks inside the
  batch and change the gradient all-reduce order, breaking bit-equivalence
  across topologies.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, Optional, Tuple

from . import faults

#: file names inside the coordination directory (atomic JSON, tmp+rename)
DRAIN_REQUEST_FILE = "drain_request.json"
DRAIN_COMMIT_FILE = "drain_commit.json"


# -- topology-invariant episode schedule (pure functions) --------------------


def episode_cursor_for_iter(current_iter: int, tasks_per_batch: int) -> int:
    """The global episode cursor after ``current_iter`` completed
    iterations: the index of the next unconsumed episode. Pure function of
    the iteration count and the *global* batch size — no topology input."""
    return int(current_iter) * int(tasks_per_batch)


def shard_slice(
    tasks_per_batch: int, shard_id: int, num_shards: int
) -> Tuple[int, int]:
    """Process ``shard_id``'s contiguous block ``[lo, hi)`` of each global
    batch's task indices. Requires ``num_shards`` to divide
    ``tasks_per_batch`` (the global batch re-partitions exactly)."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if not 0 <= shard_id < num_shards:
        raise ValueError(
            f"shard_id {shard_id} out of range for {num_shards} shards"
        )
    if tasks_per_batch % num_shards != 0:
        raise ValueError(
            f"global batch of {tasks_per_batch} tasks not divisible by "
            f"{num_shards} processes, so it cannot re-partition; elastic "
            "resume requires every anticipated process count to divide the "
            "global batch"
        )
    per = tasks_per_batch // num_shards
    return shard_id * per, (shard_id + 1) * per


def process_for_index(
    global_index: int, tasks_per_batch: int, num_shards: int
) -> int:
    """Which process builds global episode ``global_index`` under the block
    partition — the inverse of ``shard_slice``, usable at restore time for
    any process count."""
    per = tasks_per_batch // num_shards
    if tasks_per_batch % num_shards != 0:
        raise ValueError(
            f"{tasks_per_batch} tasks not divisible by {num_shards} shards"
        )
    return (int(global_index) % int(tasks_per_batch)) // per


# -- coordinated preemption drain --------------------------------------------


def _atomic_write_json(path: str, payload: Dict[str, Any]) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    """None when absent or (transiently) unreadable — the atomic writes
    make a *parsed* file always complete."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class DrainCoordinator:
    """The file-based drain agreement seam (see module docstring).

    One instance per process per run; all instances point at the same
    shared ``coord_dir``. Every entry point is idempotent and cheap: a
    boundary poll with nothing published is one ``os.path.exists``.
    """

    def __init__(
        self,
        coord_dir: str,
        process_index: int,
        process_count: int,
        margin_iters: int = 4,
        run_tag: str = "",
    ):
        self.coord_dir = str(coord_dir)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.margin_iters = max(1, int(margin_iters))
        self.is_primary = self.process_index == 0
        # run scoping: the builder tags the coordinator with the resume
        # iteration, so a request/commit published by a PREVIOUS incarnation
        # of this experiment (which the drain consumed, or a crash stranded)
        # does not preempt the resumed run — every process derives the
        # same tag from the same checkpoint. The primary additionally
        # clears its own tag's leftovers at construction (a re-resume from
        # the exact same iteration after a crash mid-drain); a follower
        # that cached such a leftover before the sweep re-validates
        # against the filesystem at drain time (``should_drain``).
        self.run_tag = str(run_tag)
        self._requested = False
        self._commit: Optional[Dict[str, Any]] = None
        if self.is_primary:
            self.clear()

    # paths -----------------------------------------------------------------

    def _tagged(self, filename: str) -> str:
        if not self.run_tag:
            return os.path.join(self.coord_dir, filename)
        stem, ext = os.path.splitext(filename)
        return os.path.join(self.coord_dir, f"{stem}_{self.run_tag}{ext}")

    @property
    def request_path(self) -> str:
        return self._tagged(DRAIN_REQUEST_FILE)

    @property
    def commit_path(self) -> str:
        return self._tagged(DRAIN_COMMIT_FILE)

    def clear(self) -> None:
        """Drop this run-tag's coordination files (primary: at
        construction, and once a drain has been fully consumed — every
        process has observed the commit by the time the collective
        emergency checkpoint completes, so post-drain removal can strand
        nobody). Also forgets any cached state."""
        for path in (self.request_path, self.commit_path):
            try:
                os.remove(path)
            except OSError:
                pass
        self._commit = None
        self._requested = False

    # protocol --------------------------------------------------------------

    def request_drain(self, signum: int, current_iter: int) -> bool:
        """Publish this process's drain request. Called at the dispatch
        boundary after a SIGTERM/SIGINT latched, from ANY process; the
        primary's next poll turns it into a commit. Returns True on the
        first (publishing) call, False on idempotent repeats — but
        re-publishes if the file vanished (a request racing the primary's
        construction-time stale-file sweep must not be silently dropped;
        the signalled process re-asserts it every boundary until the
        commit lands)."""
        if self._requested and os.path.exists(self.request_path):
            return False
        os.makedirs(self.coord_dir, exist_ok=True)
        _atomic_write_json(
            self.request_path,
            {
                "process_index": self.process_index,
                "signal": int(signum),
                "iter": int(current_iter),
            },
        )
        self._requested = True
        return True

    def poll(self, current_iter: int) -> Optional[Dict[str, Any]]:
        """The dispatch-boundary poll: returns the drain commit once one
        exists (cached thereafter — the filesystem is read at most once per
        boundary until the commit lands). On the primary, an observed
        request (or the primary's own) is promoted to a commit at
        ``current_iter + margin_iters``."""
        faults.fire("drain_poll")  # chaos-injectable seam (resilience/faults)
        if self._commit is not None:
            return self._commit
        commit = (
            _read_json(self.commit_path)
            if os.path.exists(self.commit_path)
            else None
        )
        if commit is None and self.is_primary:
            request = (
                _read_json(self.request_path)
                if os.path.exists(self.request_path)
                else None
            )
            if request is not None:
                commit = {
                    "drain_iter": int(current_iter) + self.margin_iters,
                    "signal": int(request.get("signal", 15)),
                    "requested_by": int(request.get("process_index", -1)),
                    "requested_at_iter": int(request.get("iter", -1)),
                    "committed_at_iter": int(current_iter),
                }
                os.makedirs(self.coord_dir, exist_ok=True)
                _atomic_write_json(self.commit_path, commit)
        if commit is not None:
            self._commit = commit
        return self._commit

    def should_drain(self, current_iter: int) -> Optional[Dict[str, Any]]:
        """The boundary check the builder's train loop calls: the commit,
        once ``current_iter`` has reached the agreed drain iteration (None
        otherwise — keep training). An overshoot (first sight of the commit
        already past ``drain_iter``) drains immediately with a loud
        warning: the collective checkpoint then either succeeds (every
        process overshot identically) or fails diagnosably at the bounded
        follower wait."""
        commit = self._commit if self._commit is not None else self.poll(
            current_iter
        )
        if commit is None:
            return None
        drain_iter = int(commit["drain_iter"])
        if current_iter < drain_iter:
            return None
        # re-validate against the filesystem before acting: a follower
        # whose very first poll raced the primary's construction-time
        # stale-file sweep may have CACHED a previous same-tag
        # incarnation's commit — if the file is GONE now (the sweep won),
        # forget it instead of draining a run nobody preempted. Only true
        # absence withdraws the commit; a transient read error keeps it
        # (the fail-safe direction for an already-agreed drain — dropping
        # it on an EIO blip would strand this process out of the
        # collective emergency checkpoint).
        try:
            os.stat(self.commit_path)
        except FileNotFoundError:
            self._commit = None
            return None
        except OSError:
            pass
        if current_iter > drain_iter:
            print(
                f"[elastic] process {self.process_index} overshot the "
                f"committed drain iteration {drain_iter} (now at "
                f"{current_iter}): draining here; raise drain_margin_iters "
                "if the shared filesystem propagates this slowly",
                file=sys.stderr,
                flush=True,
            )
        return commit
