#!/bin/bash
# TPU tunnel watcher: poll until the axon tunnel is UP, then seize it.
#
# On the first successful probe this runs, in order, logging everything under
# $ARTIFACT_DIR (default /root/repo/.round4):
#   1. bench.py at the full flagship config  -> BENCH_TPU.json line
#      (bench.py itself records BENCH_BASELINE.json on a TPU backend)
#   2. bench_sweep.py dtype x remat grid     -> SWEEP_TPU.txt
#   3. bench.py with BENCH_TRACE_DIR set     -> profiler trace artifact
#   4. full-width Omniglot 20-way 1-shot MAML++ training (64 filters,
#      5 inner steps — experiment_config/omniglot_maml++-omniglot_1_20_8_0.1_64_0.json)
#      in the background, kill-safe checkpoints under /tmp/omniglot_20way_64f
#
# A CPU training run can register its pid in $CPU_TRAIN_PIDFILE; it is
# SIGSTOPped while TPU work runs (1-core host: the trainer would starve the
# TPU host loop) and SIGCONTed if the seizure fails so nothing is lost.
#
# Usage: nohup bash script_generation_tools/tpu_watch.sh >/dev/null 2>&1 &

set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
ARTIFACT_DIR="${ARTIFACT_DIR:-$REPO/.round5}"
CPU_TRAIN_PIDFILE="${CPU_TRAIN_PIDFILE:-/tmp/round5_cpu_train.pid}"
PROBE_INTERVAL="${PROBE_INTERVAL:-600}"
PROBE_TIMEOUT="${PROBE_TIMEOUT:-240}"
LOG="$ARTIFACT_DIR/tpu_watch.log"
mkdir -p "$ARTIFACT_DIR"

say() { echo "$(date +%F\ %T) $*" >> "$LOG"; }

cpu_trainer_signal() {  # STOP or CONT the registered CPU trainer, if any
    local sig="$1"
    if [[ -f "$CPU_TRAIN_PIDFILE" ]]; then
        local pid
        pid=$(cat "$CPU_TRAIN_PIDFILE")
        if kill -0 "$pid" 2>/dev/null; then
            kill "-$sig" "$pid" 2>/dev/null && say "sent SIG$sig to CPU trainer $pid"
        fi
    fi
}

probe() {  # 0 iff the default backend is a real TPU
    local out rc
    out=$(set -o pipefail; timeout "$PROBE_TIMEOUT" python -c \
        "import jax; d=jax.devices(); print(d[0].platform, d[0].device_kind, len(d))" \
        2>/dev/null | tail -1)
    rc=$?  # pipefail inside the substitution: timeout/python status wins
    say "probe: ${out:-DOWN(rc=$rc; 124=timeout)}"
    [[ "$out" == tpu* ]]
}

seize() {
    say "TPU UP — seizing"
    cpu_trainer_signal STOP

    say "[1/4] bench.py flagship"
    if ! timeout 5400 python "$REPO/bench.py" \
            > "$ARTIFACT_DIR/BENCH_TPU.json" 2> "$ARTIFACT_DIR/BENCH_TPU.err"; then
        say "bench.py FAILED (see BENCH_TPU.err) — releasing"
        cpu_trainer_signal CONT
        return 1
    fi
    say "bench.py: $(tail -1 "$ARTIFACT_DIR/BENCH_TPU.json")"

    say "[2/4] bench_sweep.py"
    timeout 10800 python "$REPO/script_generation_tools/bench_sweep.py" \
        --steps 20 > "$ARTIFACT_DIR/SWEEP_TPU.txt" 2>&1 \
        || say "bench_sweep FAILED (non-fatal, see SWEEP_TPU.txt)"

    say "[3/4] profiler trace"
    BENCH_TRACE_DIR="$ARTIFACT_DIR/trace" BENCH_TIMED_STEPS=5 \
        timeout 3600 python "$REPO/bench.py" \
        > "$ARTIFACT_DIR/BENCH_TRACE.json" 2>> "$ARTIFACT_DIR/BENCH_TPU.err" \
        || say "trace capture FAILED (non-fatal)"

    say "[4/4] launching full-width Omniglot 20-way training"
    DATASET_DIR=/root/reference nohup python "$REPO/train_maml_system.py" \
        --name_of_args_json_file "$REPO/experiment_config/omniglot_maml++-omniglot_1_20_8_0.1_64_0.json" \
        --experiment_name /tmp/omniglot_20way_64f \
        --use_mmap_cache true --load_into_memory false \
        >> "$ARTIFACT_DIR/train_64f_tpu.log" 2>&1 &
    local train_pid=$!
    say "training pid $train_pid (log: train_64f_tpu.log)"
    # health-check: a startup crash must not leave the CPU trainer STOPped
    # with nothing running
    sleep 120
    if ! kill -0 "$train_pid" 2>/dev/null; then
        say "TPU training died at startup (see train_64f_tpu.log) — releasing"
        cpu_trainer_signal CONT
        return 1
    fi
    return 0
}

say "watcher started (interval ${PROBE_INTERVAL}s, timeout ${PROBE_TIMEOUT}s)"
while true; do
    if probe; then
        if seize; then
            say "seizure complete — watcher exiting (training continues in background)"
            exit 0
        fi
    fi
    sleep "$PROBE_INTERVAL"
done
