"""Throughput sweep over the TPU-native perf knobs.

Runs ``bench.py`` (fresh process per point, so each gets a clean XLA
compilation environment) across {compute_dtype} x {use_remat(/remat_policy)}
and prints a ranked table plus the best point's copy-pasteable env settings.
Use on real TPU hardware to pick the flagship bench configuration.

    python script_generation_tools/bench_sweep.py [--steps 20] [--batch 8]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_point(env_overrides: dict, timeout: int) -> dict:
    env = dict(os.environ, **{k: str(v) for k, v in env_overrides.items()})
    try:
        out = subprocess.run(
            [sys.executable, "bench.py"], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        # one slow point must not discard the rest of the sweep
        return {"error": f"timeout after {timeout}s"}
    if out.returncode != 0:
        return {"error": out.stderr.strip().splitlines()[-1] if out.stderr else "?"}
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=20, help="timed steps per point")
    ap.add_argument("--batch", type=int, default=0, help="meta-batch override (0 = bench default)")
    ap.add_argument("--timeout", type=int, default=900, help="per-point timeout (s)")
    args = ap.parse_args()

    grid = [("false", "full"), ("true", "full"), ("true", "save_conv")]
    dtypes = ("float32", "bfloat16")
    if os.environ.get("BENCH_SWEEP_GRID") == "smoke":
        # CI/smoke mode: one remat point per dtype proves the subprocess
        # plumbing without six compiles
        grid = [("false", "full")]
    points = []
    for dtype in dtypes:
        for remat, policy in grid:
            ov = {
                "BENCH_COMPUTE_DTYPE": dtype,
                "BENCH_USE_REMAT": remat,
                "BENCH_REMAT_POLICY": policy,
                "BENCH_TIMED_STEPS": args.steps,
                # sweeps rank TRAIN throughput; the epoch-boundary tail
                # (eval compile + checkpoint write) and the input-pipeline
                # tiers would only slow every point without changing the
                # ranking
                "BENCH_SKIP_EPOCH_BOUNDARY": "1",
                "BENCH_SKIP_INPUT_PIPELINE": "1",
                "BENCH_SKIP_TELEMETRY_OVERHEAD": "1",
            }
            if args.batch:
                ov["BENCH_BATCH_SIZE"] = args.batch
            label = f"remat={remat}" + (f"/{policy}" if remat == "true" else "")
            print(f"... dtype={dtype} {label}", flush=True)
            res = run_point(ov, args.timeout)
            points.append((dtype, label, res, ov))

    ok = [p for p in points if "value" in p[2]]
    ok.sort(key=lambda p: -p[2]["value"])
    print(f"\n{'dtype':<10} {'remat':<16} {'tasks/s/chip':>13}")
    for d, r, x, _ in ok:
        print(f"{d:<10} {r:<16} {x['value']:>13.3f}")
    for d, r, x, _ in points:
        if "error" in x:
            print(f"{d:<10} {r:<16} ERROR: {x['error']}")
    if ok:
        d, r, x, ov = ok[0]
        env_line = " ".join(
            f"{k}={v}" for k, v in ov.items() if k != "BENCH_TIMED_STEPS"
        )
        print(f"\nbest ({x['value']} {x['unit']}): {env_line}")


if __name__ == "__main__":
    main()
