"""Throughput sweep over the TPU-native perf knobs.

Runs ``bench.py`` (fresh process per point, so each gets a clean XLA
compilation environment) across {compute_dtype} x {use_remat(/remat_policy)}
— and, with ``--lowering``, across {conv_impl} x {pad_channels} (the
task-batched GEMM conv vs the native grouped conv, with and without MXU
channel padding) — and prints a ranked table plus the best point's
copy-pasteable env settings. Use on real TPU hardware to pick the flagship
bench configuration.

    python script_generation_tools/bench_sweep.py [--steps 20] [--batch 8]
    python script_generation_tools/bench_sweep.py --lowering
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_point(env_overrides: dict, timeout: int) -> dict:
    env = dict(os.environ, **{k: str(v) for k, v in env_overrides.items()})
    try:
        out = subprocess.run(
            [sys.executable, "bench.py"], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        # one slow point must not discard the rest of the sweep
        return {"error": f"timeout after {timeout}s"}
    if out.returncode != 0:
        return {"error": out.stderr.strip().splitlines()[-1] if out.stderr else "?"}
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=20, help="timed steps per point")
    ap.add_argument("--batch", type=int, default=0, help="meta-batch override (0 = bench default)")
    ap.add_argument("--timeout", type=int, default=900, help="per-point timeout (s)")
    ap.add_argument(
        "--lowering", action="store_true",
        help="sweep conv_impl x pad_channels (step lowering) instead of "
             "compute_dtype x remat",
    )
    args = ap.parse_args()

    # common skips: sweeps rank TRAIN throughput; the epoch-boundary tail
    # (eval compile + checkpoint write) and the input-pipeline tiers would
    # only slow every point without changing the ranking
    base_ov = {
        "BENCH_TIMED_STEPS": args.steps,
        "BENCH_SKIP_EPOCH_BOUNDARY": "1",
        "BENCH_SKIP_INPUT_PIPELINE": "1",
        "BENCH_SKIP_TELEMETRY_OVERHEAD": "1",
        "BENCH_SKIP_HEALTH_OVERHEAD": "1",
    }
    smoke = os.environ.get("BENCH_SWEEP_GRID") == "smoke"
    points = []
    if args.lowering:
        # the MXU-saturation grid: native grouped conv vs the task-batched
        # GEMM lowering, each with channel padding off / auto / an explicit
        # full-lane multiple
        conv_impls = ("lax", "gemm", "im2col")
        pads = ("off", "tile", "128")
        if smoke:
            conv_impls, pads = ("gemm",), ("off", "tile")
        for impl in conv_impls:
            for pad in pads:
                ov = dict(
                    base_ov, BENCH_CONV_IMPL=impl, BENCH_PAD_CHANNELS=pad
                )
                if args.batch:
                    ov["BENCH_BATCH_SIZE"] = args.batch
                label = f"pad={pad}"
                print(f"... conv_impl={impl} {label}", flush=True)
                points.append((impl, label, run_point(ov, args.timeout), ov))
        col = "conv_impl"
    else:
        grid = [("false", "full"), ("true", "full"), ("true", "save_conv")]
        dtypes = ("float32", "bfloat16")
        if smoke:
            # CI/smoke mode: one remat point per dtype proves the subprocess
            # plumbing without six compiles
            grid = [("false", "full")]
        for dtype in dtypes:
            for remat, policy in grid:
                ov = dict(
                    base_ov,
                    BENCH_COMPUTE_DTYPE=dtype,
                    BENCH_USE_REMAT=remat,
                    BENCH_REMAT_POLICY=policy,
                )
                if args.batch:
                    ov["BENCH_BATCH_SIZE"] = args.batch
                label = f"remat={remat}" + (
                    f"/{policy}" if remat == "true" else ""
                )
                print(f"... dtype={dtype} {label}", flush=True)
                points.append((dtype, label, run_point(ov, args.timeout), ov))
        col = "dtype"

    ok = [p for p in points if "value" in p[2]]
    ok.sort(key=lambda p: -p[2]["value"])
    print(f"\n{col:<10} {'point':<16} {'tasks/s/chip':>13}")
    for d, r, x, _ in ok:
        print(f"{d:<10} {r:<16} {x['value']:>13.3f}")
    for d, r, x, _ in points:
        if "error" in x:
            print(f"{d:<10} {r:<16} ERROR: {x['error']}")
    if ok:
        d, r, x, ov = ok[0]
        env_line = " ".join(
            f"{k}={v}" for k, v in ov.items() if k != "BENCH_TIMED_STEPS"
        )
        print(f"\nbest ({x['value']} {x['unit']}): {env_line}")


if __name__ == "__main__":
    main()
