"""Throughput sweep over the TPU-native perf knobs.

Runs ``bench.py`` (fresh process per point, so each gets a clean XLA
compilation environment) across {compute_dtype} x {use_remat} and prints a
ranked table plus the best point's env settings. Use on real TPU hardware to
pick the flagship bench configuration.

    python script_generation_tools/bench_sweep.py [--steps 20] [--batch 8]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_point(env_overrides: dict, timeout: int) -> dict:
    env = dict(os.environ, **{k: str(v) for k, v in env_overrides.items()})
    try:
        out = subprocess.run(
            [sys.executable, "bench.py"], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        # one slow point must not discard the rest of the sweep
        return {"error": f"timeout after {timeout}s"}
    if out.returncode != 0:
        return {"error": out.stderr.strip().splitlines()[-1] if out.stderr else "?"}
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=20, help="timed steps per point")
    ap.add_argument("--batch", type=int, default=0, help="meta-batch override (0 = bench default)")
    ap.add_argument("--timeout", type=int, default=900, help="per-point timeout (s)")
    args = ap.parse_args()

    points = []
    for dtype in ("float32", "bfloat16"):
        for remat in ("true", "false"):
            ov = {
                "BENCH_COMPUTE_DTYPE": dtype,
                "BENCH_USE_REMAT": remat,
                "BENCH_TIMED_STEPS": args.steps,
            }
            if args.batch:
                ov["BENCH_BATCH_SIZE"] = args.batch
            print(f"... dtype={dtype} remat={remat}", flush=True)
            res = run_point(ov, args.timeout)
            points.append((dtype, remat, res))

    ok = [(d, r, x) for d, r, x in points if "value" in x]
    ok.sort(key=lambda p: -p[2]["value"])
    print(f"\n{'dtype':<10} {'remat':<6} {'tasks/s/chip':>13}")
    for d, r, x in ok:
        print(f"{d:<10} {r:<6} {x['value']:>13.3f}")
    for d, r, x in points:
        if "error" in x:
            print(f"{d:<10} {r:<6} ERROR: {x['error']}")
    if ok:
        d, r, x = ok[0]
        print(
            f"\nbest: BENCH_COMPUTE_DTYPE={d} BENCH_USE_REMAT={r} "
            f"-> {x['value']} {x['unit']}"
        )


if __name__ == "__main__":
    main()
