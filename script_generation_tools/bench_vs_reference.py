"""Same-host throughput head-to-head: this framework vs the PyTorch reference.

Runs the identical second-order MAML++ outer step (same task shapes, same
mechanism set: LSLR + MSL + per-step BN) through BOTH implementations on the
same machine and prints one JSON line with meta-tasks/sec for each and the
ratio. The reference publishes no throughput numbers (BASELINE.md), so this
is the only direct perf comparison available without TPU hardware — run it
on a quiet machine.

The reference implementation is loaded from ``$REFERENCE_DIR`` (default
``/root/reference``) via its own ``get_args`` (patched argv + a temp JSON in
its config format, exactly how its launcher builds the args object); nothing
from the reference is copied here.

SECURITY NOTE: the reference half imports and executes the reference
checkout's code *in this process* with full user privileges. The reference
tree is third-party content — only run this explicit opt-in benchmark
against a checkout you trust, or pass ``--skip-reference`` to measure just
our half.

    JAX_PLATFORMS=cpu python script_generation_tools/bench_vs_reference.py \
        [--filters 16] [--steps 3] [--batch 4] [--way 5] [--shot 1] \
        [--timed 10] [--skip-reference]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

REFERENCE_DIR = os.environ.get("REFERENCE_DIR", "/root/reference")


def _task_batch(b, n, s, t, h, w, c, seed=0):
    rng = np.random.RandomState(seed)
    x_s = rng.randn(b, n, s, h, w, c).astype(np.float32)
    x_t = rng.randn(b, n, t, h, w, c).astype(np.float32)
    y_s = np.tile(np.arange(n, dtype=np.int64)[None, :, None], (b, 1, s))
    y_t = np.tile(np.arange(n, dtype=np.int64)[None, :, None], (b, 1, t))
    return x_s, x_t, y_s, y_t


def time_ours(a) -> float:
    """Steady-state meta-tasks/sec of our jitted second-order train step."""
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.core import maml, msl
    import jax

    cfg = MAMLConfig(
        dataset_name="omniglot_dataset",
        image_height=28, image_width=28, image_channels=1,
        num_classes_per_set=a.way, num_samples_per_class=a.shot,
        num_target_samples=1, batch_size=a.batch,
        cnn_num_filters=a.filters, num_stages=4, max_pooling=True,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True, second_order=True,
        number_of_training_steps_per_iter=a.steps,
        number_of_evaluation_steps_per_iter=a.steps,
        use_remat=a.remat,
        task_axis_mode=a.task_mode,
        conv_impl=a.conv_impl,
    )
    state = maml.init_state(cfg)
    x_s, x_t, y_s, y_t = _task_batch(
        a.batch, a.way, a.shot, 1, 28, 28, 1
    )
    y_s, y_t = y_s.astype(np.int32), y_t.astype(np.int32)
    weights = np.asarray(
        msl.loss_weights_for(a.steps, True, True, 0,
                             cfg.multi_step_loss_num_epochs)
    )
    step = jax.jit(
        maml.make_train_step(cfg, second_order=True), donate_argnums=(0,)
    )
    def sync(m):
        # scalar fetch of a value data-dependent on the last step: over the
        # remote-TPU tunnel block_until_ready returns before execution
        # finishes (same rationale as bench.py's sync)
        jax.block_until_ready(state.net)
        float(np.asarray(m["loss"]))

    for _ in range(2):  # compile + settle
        state, m = step(state, x_s, y_s, x_t, y_t, weights, 1e-3)
    sync(m)
    t0 = time.perf_counter()
    for _ in range(a.timed):
        state, m = step(state, x_s, y_s, x_t, y_t, weights, 1e-3)
    sync(m)
    return a.timed * a.batch / (time.perf_counter() - t0)


def time_reference(a) -> float:
    """Steady-state meta-tasks/sec of the reference's run_train_iter on the
    same config (ref few_shot_learning_system.py:338-369)."""
    sys.path.insert(0, REFERENCE_DIR)
    # same-host CPU comparison: hide any GPU (async CUDA timing would need
    # explicit synchronization and would not be same-device anyway) and make
    # the reference's $DATASET_DIR path join work without a real dataset
    os.environ.setdefault("CUDA_VISIBLE_DEVICES", "")
    os.environ.setdefault("DATASET_DIR", tempfile.gettempdir())
    import torch

    torch.set_num_threads(1)

    cfg = {
        "batch_size": a.batch,
        "image_height": 28, "image_width": 28, "image_channels": 1,
        "gpu_to_use": 0, "num_dataprovider_workers": 1,
        "max_models_to_save": 5,
        "dataset_name": "omniglot_dataset", "dataset_path": "omniglot_dataset",
        "reset_stored_paths": False, "experiment_name": "bench_ref",
        "train_seed": 0, "val_seed": 0,
        "train_val_test_split": [0.71, 0.03, 0.26],
        "indexes_of_folders_indicating_class": [-3, -2],
        "sets_are_pre_split": False, "load_into_memory": False,
        "init_inner_loop_learning_rate": 0.1,
        "multi_step_loss_num_epochs": 15,
        "minimum_per_task_contribution": 0.01,
        "num_evaluation_tasks": 40,
        "learnable_per_layer_per_step_inner_loop_learning_rate": True,
        "enable_inner_loop_optimizable_bn_params": False,
        "total_epochs": 100, "total_iter_per_epoch": 100,
        "continue_from_epoch": -2,
        "evaluate_on_test_set_only": False,
        "max_pooling": True, "per_step_bn_statistics": True,
        "learnable_batch_norm_momentum": False,
        "evalute_on_test_set_only": False,
        "learnable_bn_gamma": True, "learnable_bn_beta": True,
        "weight_decay": 0.0, "dropout_rate_value": 0.0,
        "min_learning_rate": 1e-5, "meta_learning_rate": 1e-3,
        "total_epochs_before_pause": 100,
        "first_order_to_second_order_epoch": -1,
        "norm_layer": "batch_norm",
        "cnn_num_filters": a.filters, "num_stages": 4, "conv_padding": True,
        "number_of_training_steps_per_iter": a.steps,
        "number_of_evaluation_steps_per_iter": a.steps,
        "cnn_blocks_per_stage": 1,
        "num_classes_per_set": a.way, "num_samples_per_class": a.shot,
        "num_target_samples": 1,
        "second_order": True, "use_multi_step_loss_optimization": True,
    }
    with tempfile.NamedTemporaryFile(
        "w", suffix=".json", delete=False
    ) as f:
        json.dump(cfg, f)
        cfg_path = f.name
    argv_backup = sys.argv
    sys.argv = ["bench_vs_reference", "--name_of_args_json_file", cfg_path]
    try:
        from utils.parser_utils import get_args

        args, device = get_args()
    finally:
        sys.argv = argv_backup
        os.unlink(cfg_path)
    device = torch.device("cpu")
    from few_shot_learning_system import MAMLFewShotClassifier

    model = MAMLFewShotClassifier(
        args=args, device=device,
        im_shape=(2, args.image_channels, args.image_height,
                  args.image_width),
    )
    x_s, x_t, y_s, y_t = _task_batch(
        a.batch, a.way, a.shot, 1, 28, 28, 1
    )
    # reference layout is channels-first: (b, n, s, c, h, w)
    x_s = np.moveaxis(x_s, -1, 3)
    x_t = np.moveaxis(x_t, -1, 3)
    batch = (x_s, x_t, y_s, y_t)
    for _ in range(2):  # settle (no compile, but first-iter allocs)
        model.run_train_iter(data_batch=batch, epoch=0)
    t0 = time.perf_counter()
    for _ in range(a.timed):
        model.run_train_iter(data_batch=batch, epoch=0)
    return a.timed * a.batch / (time.perf_counter() - t0)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--filters", type=int, default=16)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--way", type=int, default=5)
    ap.add_argument("--shot", type=int, default=1)
    ap.add_argument("--timed", type=int, default=10)
    ap.add_argument(
        "--remat", action=argparse.BooleanOptionalAction, default=False,
        help="jax.checkpoint the inner step (a TPU memory/FLOPs trade; "
        "wasteful on CPU, so off by default here)",
    )
    ap.add_argument(
        "--task-mode", default="map", choices=("vmap", "map"),
        help="'map' (sequential tasks, ordinary convs) is the CPU-host fast "
        "path; 'vmap' is the TPU default (grouped convs for the MXU)",
    )
    ap.add_argument(
        "--conv-impl", default="auto", choices=("auto", "lax", "im2col"),
        help="conv lowering for our half (config.conv_impl); 'auto' picks "
        "im2col on CPU",
    )
    ap.add_argument("--skip-reference", action="store_true")
    a = ap.parse_args()

    ours = time_ours(a)
    ref = None
    if not a.skip_reference:
        if not os.path.isdir(REFERENCE_DIR):
            print(f"reference not found at {REFERENCE_DIR}", file=sys.stderr)
        else:
            ref = time_reference(a)
    print(
        json.dumps(
            {
                "config": f"omniglot {a.way}way-{a.shot}shot "
                          f"{a.filters}f/{a.steps}steps/b{a.batch}",
                "remat": a.remat,
                "task_mode": a.task_mode,
                "ours_tasks_per_sec": round(ours, 3),
                "reference_tasks_per_sec": round(ref, 3) if ref else None,
                "speedup_vs_reference": round(ours / ref, 2) if ref else None,
                "host": "cpu (same machine; torch pinned to 1 thread, "
                        "CUDA hidden)",
            }
        )
    )


if __name__ == "__main__":
    main()
