"""Experiment config + launch-script generator.

TPU-native re-design of the reference's offline toolchain
(script_generation_tools/generate_configs.py:1-136 + generate_scripts.py:1-45):
instead of `$var$` text substitution over JSON templates, experiments are
built as typed ``MAMLConfig`` objects and serialized, so every generated file
is schema-checked at generation time. Outputs keep the reference layout:

* ``experiment_config/<algo>-<experiment_name>.json`` — one per grid point
  (same hyper-grid as the reference: 3 seeds x {omniglot spc{1,5} way{20,5}
  bs8 ilr0.1 f64, mini-imagenet spc{1,5} way5 bs2 ilr0.01 f48} x
  {maml, maml++} = 36 configs);
* ``experiment_scripts/<config>_few_shot.sh`` — one TPU launch script per
  config (no CUDA_VISIBLE_DEVICES; device selection is JAX's job).

Run from the repo root:  python script_generation_tools/generate_experiments.py

Deliberate deviation: generated configs set ``task_learning_rate`` to the
grid's inner-loop LR explicitly. The reference's configs write the dead key
``init_inner_loop_learning_rate`` while the code silently reads
``task_learning_rate`` (default 0.1) — see SURVEY.md §5. Setting the live key
makes the intent explicit and is backward-compatible (the reference honours
JSON ``task_learning_rate`` too).
"""

from __future__ import annotations

import dataclasses
import json
import os
import stat
import sys
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig

SEEDS = [0, 1, 2]

# hyper-grid (generate_configs.py:30-36)
GRID = {
    "omniglot": dict(
        num_samples_per_class_range=[1, 5],
        num_classes_range=[20, 5],
        batch_size_range=[8],
        init_inner_loop_learning_rate_range=[0.1],
        num_filters=[64],
    ),
    "mini-imagenet": dict(
        num_samples_per_class_range=[1, 5],
        num_classes_range=[5],
        batch_size_range=[2],
        init_inner_loop_learning_rate_range=[0.01],
        num_filters=[48],
    ),
}

# the three booleans that separate MAML from MAML++ (SURVEY.md §2.3)
ALGO_FLAGS = {
    "maml": dict(
        learnable_per_layer_per_step_inner_loop_learning_rate=False,
        per_step_bn_statistics=False,
        use_multi_step_loss_optimization=False,
    ),
    "maml++": dict(
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        per_step_bn_statistics=True,
        use_multi_step_loss_optimization=True,
    ),
}

# per-dataset template bodies (experiment_template_config/*.json)
DATASET_BASE = {
    "omniglot": dict(
        dataset_name="omniglot_dataset",
        dataset_path="omniglot_dataset",
        image_height=28, image_width=28, image_channels=1,
        num_target_samples=1,
        sets_are_pre_split=False,
        train_val_test_split=[0.70918052988, 0.03080714725, 0.2606284658],
        indexes_of_folders_indicating_class=[-3, -2],
        load_into_memory=True,
        multi_step_loss_num_epochs=10,
        min_learning_rate=0.00001,
        total_epochs_before_pause=100,
    ),
    "mini-imagenet": dict(
        dataset_name="mini_imagenet_full_size",
        dataset_path="mini_imagenet_full_size",
        image_height=84, image_width=84, image_channels=3,
        num_target_samples=15,
        sets_are_pre_split=True,
        train_val_test_split=[0.64, 0.16, 0.20],
        indexes_of_folders_indicating_class=[-3, -2],
        load_into_memory=True,
        multi_step_loss_num_epochs=15,
        min_learning_rate=0.001,  # mini-imagenet template: no real annealing
        total_epochs_before_pause=101,
    ),
}

SHARED = dict(
    num_dataprovider_workers=4,
    max_models_to_save=5,
    num_evaluation_tasks=600,
    enable_inner_loop_optimizable_bn_params=False,
    total_epochs=100,
    total_iter_per_epoch=500,
    max_pooling=True,
    learnable_bn_gamma=True,
    learnable_bn_beta=True,
    meta_learning_rate=0.001,
    first_order_to_second_order_epoch=-1,
    norm_layer="batch_norm",
    num_stages=4,
    conv_padding=True,
    number_of_training_steps_per_iter=5,
    number_of_evaluation_steps_per_iter=5,
    second_order=True,
    val_seed=0,
)

SCRIPT_TEMPLATE = """#!/bin/sh
# TPU launch script (generated). Usage: ./{name} [extra CLI overrides]
cd "$(dirname "$0")/.."
export DATASET_DIR="${{DATASET_DIR:-datasets/}}"
python train_maml_system.py --name_of_args_json_file experiment_config/{config} "$@"
"""


def grid_points(spec: Dict[str, List]) -> List[Dict]:
    points = [{}]
    for key, choices in spec.items():
        points = [
            {**p, key.replace("_range", ""): c} for p in points for c in choices
        ]
    return points


def write_experiment(cfg_dir: str, script_dir: str, stem: str, fields: Dict) -> str:
    """Schema-check one experiment and write its config JSON + launch script."""
    unknown = set(fields) - MAMLConfig.known_keys()
    assert not unknown, f"unknown config keys: {unknown}"
    cfg = MAMLConfig(**fields)  # schema check
    cfg_path = os.path.join(cfg_dir, stem + ".json")
    with open(cfg_path, "w") as f:
        json.dump(
            {k: v for k, v in dataclasses.asdict(cfg).items() if k in fields},
            f, indent=2, sort_keys=True,
        )
    script_name = stem + "_few_shot.sh"
    script_path = os.path.join(script_dir, script_name)
    with open(script_path, "w") as f:
        f.write(SCRIPT_TEMPLATE.format(name=script_name, config=stem + ".json"))
    os.chmod(
        script_path,
        os.stat(script_path).st_mode | stat.S_IXUSR | stat.S_IXGRP | stat.S_IXOTH,
    )
    return cfg_path


def main(root: str = ".") -> List[str]:
    cfg_dir = os.path.join(root, "experiment_config")
    script_dir = os.path.join(root, "experiment_scripts")
    os.makedirs(cfg_dir, exist_ok=True)
    os.makedirs(script_dir, exist_ok=True)
    written = []
    for seed in SEEDS:
        for ds_name, spec in GRID.items():
            for point in grid_points(spec):
                for algo, flags in ALGO_FLAGS.items():
                    experiment_name = "{}_{}_{}".format(
                        ds_name,
                        "_".join(str(v) for v in point.values()),
                        seed,
                    )
                    fields = dict(SHARED)
                    fields.update(DATASET_BASE[ds_name])
                    fields.update(flags)
                    fields.update(
                        experiment_name=experiment_name,
                        train_seed=seed,
                        batch_size=point["batch_size"],
                        num_classes_per_set=point["num_classes"],
                        num_samples_per_class=point["num_samples_per_class"],
                        init_inner_loop_learning_rate=point[
                            "init_inner_loop_learning_rate"
                        ],
                        task_learning_rate=point["init_inner_loop_learning_rate"],
                        cnn_num_filters=point["num_filters"],
                    )
                    stem = f"{ds_name}_{algo}-{experiment_name}"
                    written.append(
                        write_experiment(cfg_dir, script_dir, stem, fields)
                    )

    # TPU-scale extra (beyond the reference's 36-point grid): the
    # large-meta-batch pod config from BASELINE.json — >=256 tasks sharded
    # over the chip mesh, mmap-cached input path
    fields = dict(SHARED)
    fields.update(DATASET_BASE["mini-imagenet"])
    fields.update(ALGO_FLAGS["maml++"])
    # experiment_name == file stem, preserving the grid's 1:1 mapping of
    # config file to experiment logs folder
    large_batch_stem = "mini-imagenet_maml++-tpu_large_batch_256"
    fields.update(
        experiment_name=large_batch_stem,
        train_seed=0,
        batch_size=256,
        num_classes_per_set=5,
        num_samples_per_class=5,
        init_inner_loop_learning_rate=0.01,
        task_learning_rate=0.01,
        cnn_num_filters=48,
        load_into_memory=False,
        use_mmap_cache=True,
        # divisible by the 256-task meta-batch (600 would silently truncate
        # to 512 evaluated tasks)
        num_evaluation_tasks=512,
    )
    written.append(
        write_experiment(
            cfg_dir, script_dir, large_batch_stem, fields,
        )
    )
    print(f"wrote {len(written)} configs to {cfg_dir} (+ scripts)")
    return written


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--output_root", default=".",
        help="directory receiving experiment_config/ and experiment_scripts/",
    )
    main(ap.parse_args().output_root)
