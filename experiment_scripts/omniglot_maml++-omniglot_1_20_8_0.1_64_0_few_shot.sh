#!/bin/sh
# TPU launch script (generated). Usage: ./omniglot_maml++-omniglot_1_20_8_0.1_64_0_few_shot.sh [extra CLI overrides]
cd "$(dirname "$0")/.."
export DATASET_DIR="${DATASET_DIR:-datasets/}"
python train_maml_system.py --name_of_args_json_file experiment_config/omniglot_maml++-omniglot_1_20_8_0.1_64_0.json "$@"
