#!/bin/sh
# TPU launch script (generated). Usage: ./mini-imagenet_maml++-tpu_large_batch_256_few_shot.sh [extra CLI overrides]
cd "$(dirname "$0")/.."
export DATASET_DIR="${DATASET_DIR:-datasets/}"
python train_maml_system.py --name_of_args_json_file experiment_config/mini-imagenet_maml++-tpu_large_batch_256.json "$@"
