"""Benchmark: meta-tasks/sec on the flagship MAML++ config.

Measures the steady-state throughput of the jitted second-order MAML++
train step (Mini-ImageNet 5-way 5-shot shapes, 48-filter 4-stage backbone,
5 inner steps — the reference's headline config) with synthetic on-device
data, so it isolates device compute from input-pipeline effects.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no throughput numbers (BASELINE.md), so
``vs_baseline`` is measured against our own recorded first-round number
when present (BENCH_BASELINE.json), else 1.0.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from __graft_entry__ import _flagship_cfg
from howtotrainyourmamlpytorch_tpu.core import maml, msl

WARMUP_STEPS = int(os.environ.get("BENCH_WARMUP_STEPS", 3))
TIMED_STEPS = int(os.environ.get("BENCH_TIMED_STEPS", 20))


def main() -> None:
    import jax

    n_chips = max(1, len(jax.devices()))
    overrides = {}
    for key in ("batch_size", "cnn_num_filters", "image_height", "image_width",
                "number_of_training_steps_per_iter"):
        if f"BENCH_{key.upper()}" in os.environ:
            overrides[key] = int(os.environ[f"BENCH_{key.upper()}"])
    if "BENCH_COMPUTE_DTYPE" in os.environ:
        overrides["compute_dtype"] = os.environ["BENCH_COMPUTE_DTYPE"]
    if "BENCH_REMAT_POLICY" in os.environ:
        overrides["remat_policy"] = os.environ["BENCH_REMAT_POLICY"]
    if "BENCH_USE_REMAT" in os.environ:
        raw = os.environ["BENCH_USE_REMAT"].lower()
        if raw not in ("true", "false", "0", "1"):
            raise SystemExit(f"BENCH_USE_REMAT must be a bool, got {raw!r}")
        overrides["use_remat"] = raw in ("true", "1")
    # constant per-chip work: 8 tasks/chip unless overridden
    overrides.setdefault("batch_size", 8 * n_chips)
    cfg = _flagship_cfg(**overrides)
    state = maml.init_state(cfg)
    b = cfg.batch_size
    n, s, t = (
        cfg.num_classes_per_set,
        cfg.num_samples_per_class,
        cfg.num_target_samples,
    )
    h, w, c = cfg.im_shape
    rng = np.random.RandomState(0)
    x_s = jax.device_put(rng.randn(b, n, s, h, w, c).astype(np.float32))
    x_t = jax.device_put(rng.randn(b, n, t, h, w, c).astype(np.float32))
    y_s = jax.device_put(
        np.tile(np.arange(n, dtype=np.int32)[None, :, None], (b, 1, s))
    )
    y_t = jax.device_put(
        np.tile(np.arange(n, dtype=np.int32)[None, :, None], (b, 1, t))
    )
    weights = np.asarray(
        msl.loss_weights_for(
            cfg.number_of_training_steps_per_iter, True, True, 0,
            cfg.multi_step_loss_num_epochs,
        )
    )
    if n_chips > 1 and cfg.batch_size % n_chips == 0:
        # shard the task axis so every chip actually works; tasks/s/chip is
        # then global throughput / chips
        from howtotrainyourmamlpytorch_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.task_mesh(n_chips)
        state = mesh_lib.replicate_state(mesh, state)
        x_s, y_s, x_t, y_t = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    step = jax.jit(maml.make_train_step(cfg, second_order=True))

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, x_s, y_s, x_t, y_t, weights, 1e-3)
    jax.block_until_ready(state.net)

    start = time.perf_counter()
    for _ in range(TIMED_STEPS):
        state, metrics = step(state, x_s, y_s, x_t, y_t, weights, 1e-3)
    jax.block_until_ready(state.net)
    elapsed = time.perf_counter() - start

    tasks_per_sec = TIMED_STEPS * b / elapsed / n_chips

    baseline = 0.0
    if os.path.exists("BENCH_BASELINE.json"):
        with open("BENCH_BASELINE.json") as f:
            baseline = float(json.load(f).get("value", 0.0))
    vs_baseline = tasks_per_sec / baseline if baseline > 0 else 1.0

    print(
        json.dumps(
            {
                "metric": "meta_tasks_per_sec_per_chip",
                "value": round(tasks_per_sec, 3),
                "unit": "tasks/s/chip",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
