"""Benchmark: meta-tasks/sec + MFU on the flagship MAML++ config.

Measures the steady-state throughput of the jitted second-order MAML++
train step (Mini-ImageNet 5-way 5-shot shapes, 48-filter 4-stage backbone,
5 inner steps — the reference's headline config) with synthetic on-device
data, so it isolates device compute from input-pipeline effects.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (plus
informational extras: mfu, backend, n_chips, and the epoch_boundary block —
fused-validation + checkpoint wall seconds, the serial tail the fused eval
dispatch and async checkpointing amortize).  The reference publishes no
throughput numbers (BASELINE.md), so ``vs_baseline`` is measured against our
own recorded baseline when present and knob-comparable
(BENCH_BASELINE.json); with no comparable baseline it is null — never 1.0,
which trend tooling would misread as "no change".

Backend selection is defensive: the requested backend is first initialized
in a *subprocess with a timeout*, because a stalled TPU tunnel hangs (or
raises from) ``jax.devices()`` in-process with no way to recover — that is
what produced round 1's rc=1/no-number artifact.  On probe failure we fall
back to the CPU backend so the driver always records a parsable line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", 150))

# On a non-TPU backend (tunnel down -> CPU fallback) the point of the run is
# recording *a* parsable line, not a meaningful flagship number: the full
# 84x84/48-filter/5-step second-order workload takes over an hour on a 1-core
# host and would stall the driver. Shrink every knob the user didn't pin.
_CPU_FALLBACK_DEFAULTS = {
    "BENCH_WARMUP_STEPS": "1",
    "BENCH_TIMED_STEPS": "3",
    "BENCH_BATCH_SIZE": "2",
    "BENCH_CNN_NUM_FILTERS": "16",
    "BENCH_IMAGE_HEIGHT": "28",
    "BENCH_IMAGE_WIDTH": "28",
    "BENCH_NUMBER_OF_TRAINING_STEPS_PER_ITER": "3",
    # remat trades FLOPs for memory — right on HBM-bound MXUs, pure
    # overhead on a CPU host (measured in .round4/SWEEP_CPU.txt)
    "BENCH_USE_REMAT": "false",
}

# Best-known TPU lowering, from the round-5 on-hardware sweep
# (.round5/SWEEP_TPU.txt + batch scaling): bf16 on the MXU, save_conv remat
# (keep conv outputs, recompute the elementwise tail), batch 12/chip — the
# v5e-16GB HBM ceiling for the second-order flagship step (14 OOMs).
# Explicit BENCH_* env vars always win; these are setdefault-only.
_TPU_DEFAULTS = {
    "BENCH_COMPUTE_DTYPE": "bfloat16",
    "BENCH_USE_REMAT": "true",
    "BENCH_REMAT_POLICY": "save_conv",
}
_TPU_TASKS_PER_CHIP = 12

# Peak dense-matmul FLOPs/chip now lives in analysis/roofline.py
# (DEVICE_PEAKS) — ONE table shared with the static roofline/MFU model and
# the SPMD auditor, so the MFU this bench quotes and the MFU the roofline
# predicts can never disagree about what "peak" means. Imported in main()
# next to the other analysis helpers.


def _probe_backend() -> None:
    """Initialize the default JAX backend in a throwaway subprocess; on
    timeout/error force this process onto the CPU backend before jax loads."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # align jax.config with the env var: sitecustomize may have pinned
        # the tunnel backend at interpreter start regardless of JAX_PLATFORMS
        from __graft_entry__ import force_cpu_backend

        force_cpu_backend()
        return
    code = "import jax; d = jax.devices(); print(d[0].platform)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=PROBE_TIMEOUT,
            capture_output=True,
        )
        ok = r.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        from __graft_entry__ import force_cpu_backend

        # full force (env + jax.config.update), not just env vars: the
        # sandbox's sitecustomize pins jax_platforms to the tunnel backend at
        # interpreter start, so the env var alone is ignored and the
        # in-process device query would sit in the tunnel's retry-sleep loop
        force_cpu_backend()
        print(
            "bench: default backend unavailable, falling back to CPU",
            file=sys.stderr,
        )


def forward_flops_per_image(cfg) -> float:
    """Analytic forward-pass FLOPs (2·MACs) for one image through the
    backbone of ref meta_neural_network_architectures.py:545-689: num_stages
    3x3 convs (stride 1 + 2x2 maxpool when max_pooling, else stride 2),
    flatten (or global avg-pool) -> linear head."""
    h, w = cfg.image_height, cfg.image_width
    cin = cfg.image_channels
    flops = 0.0
    for _ in range(cfg.num_stages):
        if cfg.max_pooling:
            flops += 2.0 * h * w * 9 * cin * cfg.cnn_num_filters
            h, w = h // 2, w // 2
        else:
            h, w = (h + 1) // 2, (w + 1) // 2
            flops += 2.0 * h * w * 9 * cin * cfg.cnn_num_filters
        cin = cfg.cnn_num_filters
    feat = h * w * cfg.cnn_num_filters if cfg.max_pooling else cfg.cnn_num_filters
    flops += 2.0 * feat * cfg.num_classes_per_set
    return flops


def train_flops_per_task(cfg, second_order: bool = True) -> float:
    """Analytic FLOPs for one task in the second-order MAML++ train step.

    Inner loop: per step, support fwd (F_s) + support grad (~2·F_s) +
    target fwd for MSL (F_t) -> T = steps·(3·F_s + F_t) forward-equivalent
    FLOPs.  The outer backward differentiates through the entire unrolled
    graph (ref few_shot_learning_system.py:138 create_graph=True), costing
    ~2·T more; first-order drops that to ~2·F_t-ish but we keep the model
    simple and only quote MFU for the second-order flagship step.
    """
    f_img = forward_flops_per_image(cfg)
    f_s = f_img * cfg.num_classes_per_set * cfg.num_samples_per_class
    f_t = f_img * cfg.num_classes_per_set * cfg.num_target_samples
    steps = cfg.number_of_training_steps_per_iter
    inner = steps * (3.0 * f_s + f_t)
    return inner * (3.0 if second_order else 1.5)


def _peak_flops(device_kind: str, dtype: str) -> float | None:
    """Published peak FLOPs/s for the quoted MFU — None for unknown
    hardware and for the roofline table's nominal (CPU) entries."""
    from howtotrainyourmamlpytorch_tpu.analysis.roofline import peak_flops

    return peak_flops(device_kind, dtype)


def _devices_or_cpu():
    """In-process ``jax.devices()`` with a last-ditch CPU retry.

    The subprocess probe can pass and the in-process init still fail (flaky
    tunnel) — that exact sequence produced round 2's rc=1.  An unguarded
    device query must never sit on the bench hot path.
    """
    import jax

    try:
        return jax.devices()
    except Exception as e:  # noqa: BLE001 - any backend failure -> CPU
        print(f"bench: in-process backend init failed ({e!r}); "
              "retrying on CPU", file=sys.stderr)
        from __graft_entry__ import force_cpu_backend

        force_cpu_backend()
        return jax.devices()


INIT_TIMEOUT = float(os.environ.get("BENCH_INIT_TIMEOUT", 240))


def _devices_watchdogged():
    """``_devices_or_cpu`` with a hard wall-clock bound.

    The tunnel backend has failed four distinct ways across rounds: hang at
    init, raise fast, probe-pass-then-raise, and probe-pass-then-sleep in a
    retry loop (possibly holding jax's backend lock, which no in-process
    recovery can break).  If device init doesn't settle in INIT_TIMEOUT
    seconds, re-exec this benchmark on the CPU backend in a fresh process,
    relay its output line, and exit with its return code — the driver gets a
    parsable line no matter which way the tunnel failed.
    """
    import threading

    result: list = []

    def target():
        try:
            result.append(_devices_or_cpu())
        except BaseException as e:  # noqa: BLE001 - relayed below
            result.append(e)

    t = threading.Thread(target=target, daemon=True)
    t.start()
    t.join(INIT_TIMEOUT)
    if t.is_alive():
        print(
            f"bench: device init still blocked after {INIT_TIMEOUT:.0f}s; "
            "re-executing on the CPU backend",
            file=sys.stderr,
        )
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env,
                capture_output=True,
                text=True,
                # the fallback run must itself be bounded or a wedged env
                # defeats the always-emit-a-line goal; 3x the init budget
                # plus slack covers the reduced-workload run comfortably
                timeout=3 * INIT_TIMEOUT + 600,
            )
            stderr, stdout, rc = r.stderr, r.stdout, r.returncode
        except subprocess.TimeoutExpired as e:
            def _txt(v):
                return v.decode() if isinstance(v, bytes) else (v or "")
            stderr = _txt(e.stderr)
            # keep any partial output: the child may have printed its result
            # line and then wedged in teardown — exactly the mode this
            # watchdog exists for
            stdout = _txt(e.stdout)
            rc = 1
            stderr += "\nbench: CPU re-exec timed out as well; giving up\n"
        sys.stderr.write(stderr)
        lines = stdout.strip().splitlines()
        if lines:
            print(lines[-1], flush=True)
        # os._exit skips stdio flushing — with block-buffered pipes the one
        # parsable line would be lost; flush both streams explicitly first
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    if isinstance(result[0], BaseException):
        raise result[0]
    return result[0]


def _time_epoch_boundary(cfg, state, batch, reduced: bool) -> dict:
    """Wall-clock the epoch boundary: the fused validation sweep plus one
    (async) checkpoint write — the serial tail that caps end-to-end epoch
    time once the train path is fused (``steps_per_dispatch``).

    val_seconds: BENCH_VAL_BATCHES eval batches dispatched in
    ``eval_batches_per_dispatch``-sized fused chunks (compile excluded).
    ckpt_seconds: one full epoch save (epoch-N write + host-side ``latest``
    clone) from save-start to the durability barrier; ckpt_blocking_seconds
    is the device->host copy alone — the part the train loop actually waits
    on, the rest overlaps the next epoch's training.
    """
    import shutil
    import tempfile

    import jax

    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    val_batches = int(
        os.environ.get("BENCH_VAL_BATCHES", "2" if reduced else "8")
    )
    ebpd = int(
        os.environ.get(
            "BENCH_EVAL_BATCHES_PER_DISPATCH", "2" if reduced else "4"
        )
    )
    ebpd = max(1, min(ebpd, val_batches))
    n_dispatches = max(1, val_batches // ebpd)
    host = [np.asarray(a) for a in batch]
    stacked = tuple(np.stack([a] * ebpd) for a in host)
    sharding = getattr(batch[0], "sharding", None)
    if sharding is not None and getattr(sharding, "mesh", None) is not None:
        # same placement the real eval driver uses (incl. divisibility check)
        from howtotrainyourmamlpytorch_tpu.parallel import mesh as mesh_lib

        stacked = mesh_lib.shard_stacked_batch(sharding.mesh, *stacked)
    else:
        stacked = jax.device_put(stacked)
    eval_multi = jax.jit(maml.make_eval_multi_step(cfg, with_preds=False))
    metrics, _ = eval_multi(state, *stacked)  # compile + warmup
    jax.block_until_ready(metrics["loss"])
    start = time.perf_counter()
    for _ in range(n_dispatches):
        metrics, _ = eval_multi(state, *stacked)
    float(np.asarray(metrics["loss"])[-1])  # tunnel-proof sync (see sync())
    val_seconds = time.perf_counter() - start

    tmp_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        start = time.perf_counter()
        ckpt.save_checkpoint_async(
            tmp_dir, "train_model", 1, state,
            {"current_iter": 0}, clone_to="latest",
        )
        blocking = time.perf_counter() - start
        ckpt.wait_for_pending()
        ckpt_seconds = time.perf_counter() - start
    finally:
        shutil.rmtree(tmp_dir, ignore_errors=True)
    return {
        "seconds": round(val_seconds + ckpt_seconds, 4),
        "val_seconds": round(val_seconds, 4),
        "ckpt_seconds": round(ckpt_seconds, 4),
        "ckpt_blocking_seconds": round(blocking, 4),
        "val_batches": n_dispatches * ebpd,
        "eval_batches_per_dispatch": ebpd,
    }


def _measure_input_pipeline(cfg, reduced: bool) -> dict | None:
    """Three-tier input-pipeline measurement (ISSUE 2): per placement tier
    (host float32 / uint8_stream / device index-only), the H2D payload bytes
    per step, host episode-assembly ms per step, and producer-queue stall ms
    per step, on a small synthetic on-disk dataset with the benchmark's
    image shape and task geometry.

    The payload is measured from the loader's actually-emitted arrays (not
    modeled), so the uint8 4x and index-only <<1 MB claims are checked
    against real batches. Informational like ``epoch_boundary`` — never part
    of baseline comparability. Best-effort: any failure returns None with a
    note on stderr rather than killing the bench line.
    """
    import shutil
    import tempfile

    try:
        from PIL import Image
    except ImportError:
        print("bench: PIL unavailable, skipping input_pipeline", file=sys.stderr)
        return None
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        IndexBatch,
        MetaLearningDataLoader,
    )

    n_batches = int(
        os.environ.get("BENCH_INPUT_PIPELINE_BATCHES", "2" if reduced else "3")
    )
    n_way = cfg.num_classes_per_set
    per_class = cfg.num_samples_per_class + cfg.num_target_samples + 2
    h, w, c = cfg.im_shape
    root = tempfile.mkdtemp(prefix="bench_input_")
    try:
        rng = np.random.RandomState(0)
        data_dir = os.path.join(root, "mini_imagenet_bench")
        for ci in range(n_way + 1):
            d = os.path.join(data_dir, "train", f"n{ci:04d}")
            os.makedirs(d, exist_ok=True)
            for j in range(per_class):
                arr = rng.randint(0, 255, (h, w, c), dtype=np.uint8)
                img = arr[:, :, 0] if c == 1 else arr
                Image.fromarray(img, "L" if c == 1 else "RGB").save(
                    os.path.join(d, f"im{j}.png")
                )
        tiers = {}
        for placement in ("host", "uint8_stream", "device"):
            pcfg = cfg.replace(
                dataset_name="mini_imagenet_bench",
                dataset_path=data_dir,
                sets_are_pre_split=True,
                indexes_of_folders_indicating_class=[-3, -2],
                use_mmap_cache=True,
                data_placement=placement,
                cache_dir=os.path.join(root, "cache"),
                prefetch_batches=2,
            )
            loader = MetaLearningDataLoader(
                pcfg, cache_dir=os.path.join(root, "cache"),
                shard_id=0, num_shards=1,
            )
            loader.pop_stream_stats()
            h2d_bytes = 0
            for batch in loader.get_train_batches(total_batches=n_batches):
                if isinstance(batch, IndexBatch):
                    h2d_bytes += batch.gather.nbytes + batch.rot_k.nbytes
                else:
                    h2d_bytes += sum(int(a.nbytes) for a in batch[:4])
            stats = loader.pop_stream_stats()
            denom = max(1, stats["batches"])
            tiers[placement] = {
                "h2d_bytes_per_step": int(h2d_bytes / n_batches),
                "assembly_ms_per_step": round(
                    stats["assembly_s"] / denom * 1e3, 3
                ),
                "producer_stall_ms_per_step": round(
                    stats["stall_s"] / denom * 1e3, 3
                ),
            }
        return {"tasks_per_step": cfg.global_tasks_per_batch, **tiers}
    except Exception as e:  # noqa: BLE001 - informational metric only
        print(f"bench: input_pipeline measurement failed ({e!r})",
              file=sys.stderr)
        return None
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _measure_step_overhead(
    cfg, mesh, batch, weights, off_ms_per_step: float, reduced: bool,
    *, name: str, cfg_override: dict, on_ms_key: str, steps_env: str,
) -> dict | None:
    """Step-time cost of an optional in-step feature (``cfg_override``
    applied to the flagship config) vs. the plain step, tracked in the
    bench line like ``epoch_boundary``.

    The 'off' arm IS the main timed loop (the flagship step is built with
    the feature off); only the feature arm is compiled and timed here,
    with the same donation and tunnel-proof sync protocol — one harness
    for every overhead metric, so a fix to the timing protocol cannot
    leave two measurements disagreeing. Informational — never part of
    baseline comparability. Best-effort: any failure returns None with a
    stderr note rather than killing the bench line.
    """
    import jax

    from howtotrainyourmamlpytorch_tpu.core import maml

    steps_n = int(os.environ.get(steps_env, "2" if reduced else "10"))
    try:
        fcfg = cfg.replace(**cfg_override)
        state = maml.init_state(fcfg)
        if mesh is not None:
            from howtotrainyourmamlpytorch_tpu.parallel import mesh as mesh_lib

            state = mesh_lib.replicate_state(mesh, state)
        step = jax.jit(
            maml.make_train_step(fcfg, second_order=True),
            donate_argnums=maml.TRAIN_DONATE,
        )
        x_s, y_s, x_t, y_t = batch
        state, m = step(state, x_s, y_s, x_t, y_t, weights, 1e-3)  # compile
        jax.block_until_ready(state.net)
        float(np.asarray(m["loss"]))
        start = time.perf_counter()
        for _ in range(steps_n):
            state, m = step(state, x_s, y_s, x_t, y_t, weights, 1e-3)
        jax.block_until_ready(state.net)
        float(np.asarray(m["loss"]))  # tunnel-proof sync (see sync())
        on_ms = (time.perf_counter() - start) / steps_n * 1e3
        return {
            "off_ms_per_step": round(off_ms_per_step, 3),
            on_ms_key: round(on_ms, 3),
            "overhead_pct": (
                round((on_ms - off_ms_per_step) / off_ms_per_step * 100, 2)
                if off_ms_per_step > 0
                else None
            ),
            "timed_steps": steps_n,
        }
    except Exception as e:  # noqa: BLE001 - informational metric only
        print(f"bench: {name} measurement failed ({e!r})", file=sys.stderr)
        return None


def _measure_telemetry_overhead(
    cfg, mesh, batch, weights, off_ms_per_step: float, reduced: bool
) -> dict | None:
    """On-device training-dynamics collection cost
    (``telemetry_level='dynamics'`` vs. off)."""
    return _measure_step_overhead(
        cfg, mesh, batch, weights, off_ms_per_step, reduced,
        name="telemetry_overhead",
        cfg_override={"telemetry_level": "dynamics"},
        on_ms_key="dynamics_ms_per_step",
        steps_env="BENCH_TELEMETRY_STEPS",
    )


def _measure_health_overhead(
    cfg, mesh, batch, weights, off_ms_per_step: float, reduced: bool
) -> dict | None:
    """On-device anomaly-probe cost (``health_level='monitor'`` vs. off) —
    the training-health monitor's device-side half. The probes are a
    handful of scalar reductions over values the step already holds, so
    this should stay near zero; a regression here means the probe lowering
    grew real work."""
    return _measure_step_overhead(
        cfg, mesh, batch, weights, off_ms_per_step, reduced,
        name="health_overhead",
        cfg_override={"health_level": "monitor"},
        on_ms_key="monitor_ms_per_step",
        steps_env="BENCH_HEALTH_STEPS",
    )


def _measure_tracing_overhead(
    cfg, mesh, batch, weights, reduced: bool
) -> dict | None:
    """Host-side span-emission cost (``tracing_level='on'`` vs off).

    Tracing never touches the jitted program (the span layer is pure
    host bookkeeping around the dispatch), so unlike the telemetry/
    health overheads there is no second executable to build: BOTH arms
    time the SAME compiled step back to back — off (bare loop), then on
    (each dispatch wrapped in a ``train_dispatch`` span emitted to a
    real JSONL sink, exactly what the builder does) — which cancels the
    systematic warmup drift a cross-harness comparison would carry.
    The off arm is taken as the min of two passes (the steadier
    estimator for a noise floor). Asserted <5% in test_bench;
    BENCH_SKIP_TRACING_OVERHEAD=1 skips. Informational — never part of
    baseline comparability.
    """
    import tempfile

    import jax

    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import (
        JsonlSink,
        make_record,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry.tracing import Tracer

    steps_n = int(
        os.environ.get("BENCH_TRACING_STEPS", "4" if reduced else "10")
    )
    tmp = None
    try:
        state = maml.init_state(cfg)
        if mesh is not None:
            from howtotrainyourmamlpytorch_tpu.parallel import (
                mesh as mesh_lib,
            )

            state = mesh_lib.replicate_state(mesh, state)
        step = jax.jit(
            maml.make_train_step(cfg, second_order=True),
            donate_argnums=maml.TRAIN_DONATE,
        )
        x_s, y_s, x_t, y_t = batch

        def run(n, tracer):
            nonlocal state
            m = None
            start = time.perf_counter()
            for _ in range(n):
                with tracer.span("train_dispatch", cat="train"):
                    state, m = step(
                        state, x_s, y_s, x_t, y_t, weights, 1e-3
                    )
            jax.block_until_ready(state.net)
            float(np.asarray(m["loss"]))  # tunnel-proof sync (see sync())
            return (time.perf_counter() - start) / n * 1e3

        from howtotrainyourmamlpytorch_tpu.telemetry.tracing import (
            NULL_TRACER,
        )

        run(1, NULL_TRACER)  # compile + warm
        tmp = tempfile.NamedTemporaryFile(
            suffix=".jsonl", delete=False
        )
        tmp.close()
        sink = JsonlSink(tmp.name)
        tracer = Tracer(
            emit=lambda **f: sink.write(make_record("span", **f))
        )
        # interleave two passes per arm and take each arm's min: the
        # steadier noise-floor estimator, so the quoted overhead_pct is
        # the tracing layer's cost, not scheduler jitter
        off_a = run(steps_n, NULL_TRACER)
        on_a = run(steps_n, tracer)
        off_b = run(steps_n, NULL_TRACER)
        on_b = run(steps_n, tracer)
        sink.close()
        off_ms = min(off_a, off_b)
        on_ms = min(on_a, on_b)
        return {
            "off_ms_per_step": round(off_ms, 3),
            "spans_ms_per_step": round(on_ms, 3),
            "overhead_pct": (
                round((on_ms - off_ms) / off_ms * 100, 2)
                if off_ms > 0 else None
            ),
            "timed_steps": steps_n,
        }
    except Exception as e:  # noqa: BLE001 - informational metric only
        print(f"bench: tracing_overhead measurement failed ({e!r})",
              file=sys.stderr)
        return None
    finally:
        if tmp is not None:
            try:
                os.remove(tmp.name)
            except OSError:
                pass


def _measure_serving(cfg, reduced: bool) -> dict | None:
    """Adapt-on-request serving latency/throughput on the flagship task
    geometry (ROADMAP item 1): a ``ServingEngine`` over a fresh snapshot
    is warmed (every bucket compiled), then driven closed-loop with a
    mixed tenant-group schedule — reporting ``adaptation_latency_ms``
    p50/p95 (end-to-end dispatch: upload + adapt-then-predict + result
    readback), ``tenants_per_sec`` and measured ``h2d_bytes_per_dispatch``
    under the engine's strict zero-retrace gate. ``modes`` adds the
    serving fast-path rows: the uint8 device-decode ingest (same
    protocol, ~4x less H2D) and the adapted-params cache hit path (every
    tenant re-served after its first adaptation — predict-only
    dispatches, no inner loop), so the bench trajectory captures the
    fast-path delta. Informational like ``epoch_boundary`` — never part
    of baseline comparability. Best-effort: any failure returns None with
    a stderr note rather than killing the bench line.
    """
    try:
        from howtotrainyourmamlpytorch_tpu.core import maml
        from howtotrainyourmamlpytorch_tpu.serving.batcher import (
            serve_requests,
        )
        from howtotrainyourmamlpytorch_tpu.serving.bench import _synth_groups
        from howtotrainyourmamlpytorch_tpu.serving.engine import ServingEngine

        rounds = int(
            os.environ.get("BENCH_SERVING_ROUNDS", "1" if reduced else "4")
        )
        scfg = cfg.replace(
            serving_bucket_ladder=[1, 2] if reduced else [1, 4, 8],
            serving_max_tenants_per_dispatch=2 if reduced else 8,
        )
        shots = (scfg.num_samples_per_class,)
        state = maml.init_state(scfg)

        def run_mode(ingest: str, cache_size: int = 0,
                     repeat_pass: bool = False) -> dict:
            engine = ServingEngine(
                scfg, state, ingest=ingest, cache_size=cache_size,
            )
            warmup_s = engine.warmup()
            n_requests = rounds * sum(range(1, engine.max_tenants + 1))
            groups = _synth_groups(
                scfg, shots, n_requests, engine.max_tenants, seed=0,
                ingest=ingest,
            )
            for group in groups:
                serve_requests(engine, group)
            tail_from = len(engine._adapt_ms)
            if repeat_pass:
                # second pass over the SAME tenants: every dispatch is a
                # cache hit (predict-only program); its latency is the
                # fast-path row
                for group in groups:
                    serve_requests(engine, group)
            rollup = engine.rollup()
            out = {
                "adaptation_latency_ms_p50": rollup["adapt_ms_p50"],
                "adaptation_latency_ms_p95": rollup["adapt_ms_p95"],
                # the engine rollup's span-based definition, verbatim
                "tenants_per_sec": rollup["tenants_per_sec"],
                "dispatches": rollup["dispatches"],
                "tenants": rollup["tenants"],
                "retraces": rollup["retraces"],
                "warmup_seconds": round(warmup_s, 3),
                "h2d_bytes_per_dispatch": rollup["h2d_bytes_per_dispatch"],
                "bucket_ladder": list(engine.buckets),
            }
            if repeat_pass:
                tail = list(engine._adapt_ms)[tail_from:]
                out["cache_hit_rate"] = rollup["cache_hit_rate"]
                out["cache_hit_latency_ms_p50"] = (
                    round(float(np.percentile(np.asarray(tail), 50)), 3)
                    if tail else None
                )
            return out

        # the cache must hold every distinct tenant or the repeat pass
        # measures evictions instead of hits
        all_tenants = rounds * sum(
            range(1, scfg.serving_max_tenants_per_dispatch + 1)
        )
        serving = run_mode("f32")
        serving["modes"] = {
            "uint8": run_mode("uint8"),
            "cache_hit": run_mode(
                "f32", cache_size=all_tenants, repeat_pass=True
            ),
        }
        return serving
    except Exception as e:  # noqa: BLE001 - informational metric only
        print(f"bench: serving measurement failed ({e!r})", file=sys.stderr)
        return None


# BENCH_* env vars that change WHAT is measured (workload shapes or
# lowering); a run with any of these set must never refresh the baseline
_WORKLOAD_KNOBS = (
    "BENCH_BATCH_SIZE", "BENCH_CNN_NUM_FILTERS", "BENCH_IMAGE_HEIGHT",
    "BENCH_IMAGE_WIDTH", "BENCH_NUMBER_OF_TRAINING_STEPS_PER_ITER",
    "BENCH_NUMBER_OF_EVALUATION_STEPS_PER_ITER",
    "BENCH_COMPUTE_DTYPE", "BENCH_USE_REMAT", "BENCH_REMAT_POLICY",
    "BENCH_CONV_IMPL", "BENCH_POOL_IMPL", "BENCH_TASK_AXIS_MODE",
    "BENCH_PAD_CHANNELS", "BENCH_META_ACCUM_STEPS",
    "BENCH_BN_STATS_IMPL", "BENCH_IM2COL_HOIST",
)

# The hlo_cost / donation helpers (cost-analysis normalization, optimized-
# HLO op census, aliasing stats) live in analysis/contracts.py — the SAME
# census the program-contract auditor pins in CONTRACTS.json, so bench
# lines and contract audits can never disagree about what the lowering
# contains. Imported inside main() after the backend is settled.


def main() -> None:
    # snapshot BEFORE backend-default knobs are setdefault'ed into the env:
    # only a pristine default-knob run may refresh BENCH_BASELINE.json
    default_knob_run = not any(k in os.environ for k in _WORKLOAD_KNOBS)
    _probe_backend()
    import jax

    devices = _devices_watchdogged()
    backend = devices[0].platform
    device_kind = devices[0].device_kind
    n_chips = max(1, len(devices))
    reduced = backend != "tpu"
    if reduced:
        for key, value in _CPU_FALLBACK_DEFAULTS.items():
            os.environ.setdefault(key, value)
    else:
        for key, value in _TPU_DEFAULTS.items():
            os.environ.setdefault(key, value)
    warmup_steps = int(os.environ.get("BENCH_WARMUP_STEPS", 3))
    timed_steps = int(os.environ.get("BENCH_TIMED_STEPS", 20))
    # deferred until the backend is settled: these imports initialize jax
    from __graft_entry__ import _flagship_cfg
    from howtotrainyourmamlpytorch_tpu.analysis.contracts import (
        cost_analysis_dict,
        donation_stats,
        hlo_cost_breakdown,
    )
    from howtotrainyourmamlpytorch_tpu.analysis.roofline import (
        roofline_report,
    )
    from howtotrainyourmamlpytorch_tpu.core import maml, msl
    overrides = {}
    for key in ("batch_size", "cnn_num_filters", "image_height", "image_width",
                "number_of_training_steps_per_iter",
                "number_of_evaluation_steps_per_iter"):
        if f"BENCH_{key.upper()}" in os.environ:
            overrides[key] = int(os.environ[f"BENCH_{key.upper()}"])
    if "BENCH_COMPUTE_DTYPE" in os.environ:
        overrides["compute_dtype"] = os.environ["BENCH_COMPUTE_DTYPE"]
    if "BENCH_REMAT_POLICY" in os.environ:
        overrides["remat_policy"] = os.environ["BENCH_REMAT_POLICY"]
    # lowering knobs for hardware A/B runs: native conv vs im2col, batched
    # vs sequential task axis (config validates the values)
    if "BENCH_CONV_IMPL" in os.environ:
        overrides["conv_impl"] = os.environ["BENCH_CONV_IMPL"]
    if "BENCH_TASK_AXIS_MODE" in os.environ:
        overrides["task_axis_mode"] = os.environ["BENCH_TASK_AXIS_MODE"]
    if "BENCH_POOL_IMPL" in os.environ:
        overrides["pool_impl"] = os.environ["BENCH_POOL_IMPL"]
    # the PR-16 compute-diet levers: BN statistics pass and invariant
    # im2col hoisting (config validates; pool_impl above is the third)
    if "BENCH_BN_STATS_IMPL" in os.environ:
        overrides["bn_stats_impl"] = os.environ["BENCH_BN_STATS_IMPL"]
    if "BENCH_IM2COL_HOIST" in os.environ:
        overrides["im2col_hoist"] = os.environ["BENCH_IM2COL_HOIST"]
    if "BENCH_PAD_CHANNELS" in os.environ:
        # 'auto' | 'off' | 'tile' | integer multiple (config validates)
        overrides["pad_channels"] = os.environ["BENCH_PAD_CHANNELS"]
    if "BENCH_META_ACCUM_STEPS" in os.environ:
        # task-microbatched gradient accumulation inside the step (must
        # divide the batch — clamped below once the batch is known)
        overrides["meta_accum_steps"] = int(
            os.environ["BENCH_META_ACCUM_STEPS"]
        )
    if "BENCH_USE_REMAT" in os.environ:
        raw = os.environ["BENCH_USE_REMAT"].lower()
        if raw not in ("true", "false", "0", "1"):
            raise SystemExit(f"BENCH_USE_REMAT must be a bool, got {raw!r}")
        overrides["use_remat"] = raw in ("true", "1")
    # constant per-chip work unless overridden: the measured HBM-ceiling
    # batch on TPU, 8/chip elsewhere
    per_chip = _TPU_TASKS_PER_CHIP if backend == "tpu" else 8
    overrides.setdefault("batch_size", per_chip * n_chips)
    # accumulation must divide the batch: clamp a sweep-point accum down
    # to the largest divisor (a 2-task reduced run with accum=4 measures
    # accum=2 and SAYS so in the emitted line) instead of refusing to
    # emit a parsable line
    if overrides.get("meta_accum_steps", 1) > 1:
        accum = min(overrides["meta_accum_steps"], overrides["batch_size"])
        while overrides["batch_size"] % accum != 0:
            accum -= 1
        if accum != overrides["meta_accum_steps"]:
            print(
                f"bench: meta_accum_steps={overrides['meta_accum_steps']} "
                f"does not divide batch {overrides['batch_size']}; "
                f"clamped to {accum}",
                file=sys.stderr,
            )
        overrides["meta_accum_steps"] = accum
    cfg = _flagship_cfg(**overrides)
    state = maml.init_state(cfg)
    b = cfg.batch_size
    n, s, t = (
        cfg.num_classes_per_set,
        cfg.num_samples_per_class,
        cfg.num_target_samples,
    )
    h, w, c = cfg.im_shape
    rng = np.random.RandomState(0)
    x_s = jax.device_put(rng.randn(b, n, s, h, w, c).astype(np.float32))
    x_t = jax.device_put(rng.randn(b, n, t, h, w, c).astype(np.float32))
    y_s = jax.device_put(
        np.tile(np.arange(n, dtype=np.int32)[None, :, None], (b, 1, s))
    )
    y_t = jax.device_put(
        np.tile(np.arange(n, dtype=np.int32)[None, :, None], (b, 1, t))
    )
    weights = np.asarray(
        msl.loss_weights_for(
            cfg.number_of_training_steps_per_iter, True, True, 0,
            cfg.multi_step_loss_num_epochs,
        )
    )
    sharded = n_chips > 1 and cfg.batch_size % n_chips == 0
    mesh = None
    if sharded:
        # shard the task axis so every chip actually works; tasks/s/chip is
        # then global throughput / chips
        from howtotrainyourmamlpytorch_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.task_mesh(n_chips)
        state = mesh_lib.replicate_state(mesh, state)
        x_s, y_s, x_t, y_t = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    # donate the state like the real system does (experiment/system.py) —
    # without it the TPU keeps two copies of params+Adam state alive
    step = jax.jit(
        maml.make_train_step(cfg, second_order=True),
        donate_argnums=maml.TRAIN_DONATE,
    )
    # AOT-compile first so we can read XLA's own FLOPs count for this exact
    # executable (validates the analytic model; see test_flops_model.py),
    # the per-category HLO cost breakdown, and the donation/aliasing stats.
    # The jit call below hits the same executable cache — no double compile.
    xla_flops_per_batch = None
    hlo_cost = None
    donation = None
    compiled = None
    try:
        compiled = step.lower(
            state, x_s, y_s, x_t, y_t, weights, 1e-3
        ).compile()
        ca = cost_analysis_dict(compiled)
        xla_flops_per_batch = float(ca["flops"])
        hlo_cost = hlo_cost_breakdown(compiled, ca)
        donation = donation_stats(compiled, maml.TRAIN_DONATE)
    except Exception as e:  # noqa: BLE001 - cost analysis is best-effort
        print(f"bench: cost_analysis unavailable ({e!r})", file=sys.stderr)

    def sync(m):
        # A 4-byte scalar device_get is the one sync that provably blocks on
        # every backend: over the remote-TPU tunnel, block_until_ready
        # returns before execution finishes (measured: a timed loop "ran" at
        # 40x hardware peak), so timing must anchor on a host fetch of a
        # value that data-depends on the last step.
        jax.block_until_ready(state.net)
        if m is not None:
            float(np.asarray(m["loss"]))

    metrics = None  # BENCH_WARMUP_STEPS=0: nothing to sync yet
    for _ in range(warmup_steps):
        state, metrics = step(state, x_s, y_s, x_t, y_t, weights, 1e-3)
    sync(metrics)

    trace_dir = os.environ.get("BENCH_TRACE_DIR")
    if trace_dir:
        # capture an XLA/TensorBoard profile of the timed region — the
        # artifact the TPU-day analysis starts from
        jax.profiler.start_trace(trace_dir)
    start = time.perf_counter()
    for _ in range(timed_steps):
        state, metrics = step(state, x_s, y_s, x_t, y_t, weights, 1e-3)
    sync(metrics)
    elapsed = time.perf_counter() - start
    if trace_dir:
        jax.profiler.stop_trace()

    # per-chip = per *working* chip: when the batch didn't divide n_chips we
    # ran unsharded on one device, and dividing by idle chips would both
    # understate throughput and skew mfu away from hfu's working-device
    # convention
    tasks_per_sec = timed_steps * b / elapsed / (n_chips if sharded else 1)

    # null when skipped (sweep points rank train throughput only)
    epoch_boundary = None
    if os.environ.get("BENCH_SKIP_EPOCH_BOUNDARY") != "1":
        epoch_boundary = _time_epoch_boundary(
            cfg, state, (x_s, y_s, x_t, y_t), reduced
        )

    # three-tier input pipeline (host / uint8_stream / device): null when
    # skipped or unmeasurable (sweep points rank train throughput only)
    input_pipeline = None
    if os.environ.get("BENCH_SKIP_INPUT_PIPELINE") != "1":
        input_pipeline = _measure_input_pipeline(cfg, reduced)

    # on-device dynamics collection cost (telemetry_level='dynamics' vs
    # off): null when skipped or unmeasurable
    telemetry_overhead = None
    if os.environ.get("BENCH_SKIP_TELEMETRY_OVERHEAD") != "1":
        telemetry_overhead = _measure_telemetry_overhead(
            cfg, mesh, (x_s, y_s, x_t, y_t), weights,
            elapsed / timed_steps * 1e3, reduced,
        )

    # on-device anomaly-probe cost (health_level='monitor' vs off): null
    # when skipped or unmeasurable
    health_overhead = None
    if os.environ.get("BENCH_SKIP_HEALTH_OVERHEAD") != "1":
        health_overhead = _measure_health_overhead(
            cfg, mesh, (x_s, y_s, x_t, y_t), weights,
            elapsed / timed_steps * 1e3, reduced,
        )

    # host-side span-emission cost (tracing_level='on' vs off): null when
    # skipped or unmeasurable
    tracing_overhead = None
    if os.environ.get("BENCH_SKIP_TRACING_OVERHEAD") != "1":
        tracing_overhead = _measure_tracing_overhead(
            cfg, mesh, (x_s, y_s, x_t, y_t), weights, reduced,
        )

    # adapt-on-request serving latency p50/p95 + tenants/sec (serving/):
    # null when skipped or unmeasurable
    serving = None
    if os.environ.get("BENCH_SKIP_SERVING") != "1":
        serving = _measure_serving(cfg, reduced)

    peak = _peak_flops(device_kind, cfg.compute_dtype)
    # mfu: the convention — *algorithmic* model FLOPs (analytic count, no
    # recompute) over peak. hfu: *executed* FLOPs per XLA's cost analysis of
    # this exact executable (includes remat recompute) over peak. The two
    # counts cross-validate: test_flops_model.py pins them within 20% at
    # conv-dominated widths with remat off.
    mfu = (
        round(tasks_per_sec * train_flops_per_task(cfg) / peak, 4)
        if peak
        else None
    )
    # cost_analysis() is PER-DEVICE: on a sharded executable it counts the
    # partitioned module (b / n_chips tasks' worth of work), but when the
    # batch didn't divide the chips we ran unsharded and it covers all b
    tasks_per_executable = b / n_chips if sharded else b
    xla_flops_per_task = (
        xla_flops_per_batch / tasks_per_executable
        if xla_flops_per_batch
        else None
    )
    # hfu: executed FLOPs per second on a working device over peak.
    # xla_flops_per_batch is already the per-device module count, and the
    # per-device module runs once per step whether or not the batch was
    # sharded — so this form needs no sharded/unsharded correction.
    hfu = (
        round(timed_steps * xla_flops_per_batch / elapsed / peak, 4)
        if peak and xla_flops_per_batch
        else None
    )

    # static roofline model of the exact executable the loop timed
    # (analysis/roofline.py): compute- vs memory-bound, predicted MFU/HFU
    # from the same cost-analysis counts, and the ranked decomposition of
    # predicted time into HLO opcode contributors — the roofline's
    # flops_per_task and the xla_flops_per_task above derive from the same
    # surface, so the audit's cross-check can hold them to each other
    roofline = None
    if compiled is not None:
        try:
            roofline = roofline_report(
                compiled,
                device_kind=device_kind,
                dtype=cfg.compute_dtype,
                tasks=max(1, int(tasks_per_executable)),
                model_flops=(
                    train_flops_per_task(cfg) * tasks_per_executable
                ),
            )
        except Exception as e:  # noqa: BLE001 - informational metric only
            print(f"bench: roofline model unavailable ({e!r})",
                  file=sys.stderr)

    result = {
        "metric": "meta_tasks_per_sec_per_chip",
        "value": round(tasks_per_sec, 3),
        "unit": "tasks/s/chip",
        # null = no comparable baseline (none stored, or stale knobs) —
        # distinct from 1.0 = "no change"; replaced below when comparable
        "vs_baseline": None,
        "mfu": mfu,
        "hfu": hfu,
        "xla_flops_per_task": (
            round(xla_flops_per_task) if xla_flops_per_task else None
        ),
        "backend": backend,
        "device_kind": device_kind,
        "n_chips": n_chips,
        "dtype": cfg.compute_dtype,
        "batch_size": b,
        "conv_impl": cfg.resolved_conv_impl,
        "pool_impl": cfg.resolved_pool_impl,
        "pad_channels": cfg.resolved_pad_channels,
        "bn_stats_impl": cfg.resolved_bn_stats_impl,
        "im2col_hoist": cfg.resolved_im2col_hoist,
        "meta_accum_steps": cfg.meta_accum_steps,
        "task_axis_mode": cfg.task_axis_mode,
        "use_remat": cfg.use_remat,
        "remat_policy": cfg.remat_policy if cfg.use_remat else None,
        "matmul_precision": cfg.resolved_matmul_precision,
        "reduced": reduced,
        # per-category HLO cost of the flagship step executable + the
        # donation/aliasing figures (informational — a lowering or aliasing
        # regression is visible here before it shows in throughput)
        "hlo_cost": hlo_cost,
        "donation": donation,
        # the static roofline/MFU model of the timed executable
        # (informational — a lowering that shifts the program across the
        # roofline shows up here before it shows in throughput)
        "roofline": roofline,
        # the serial tail between epochs: fused-val + checkpoint seconds
        # (informational — not part of baseline comparability)
        "epoch_boundary": epoch_boundary,
        # per-tier H2D bytes/step + host assembly/stall ms (informational —
        # not part of baseline comparability)
        "input_pipeline": input_pipeline,
        # step time with telemetry_level='dynamics' vs off (informational —
        # not part of baseline comparability)
        "telemetry_overhead": telemetry_overhead,
        # step time with health_level='monitor' vs off (informational —
        # not part of baseline comparability)
        "health_overhead": health_overhead,
        # step time with spans emitted around each dispatch vs off
        # (informational — not part of baseline comparability; asserted
        # <5% in test_bench)
        "tracing_overhead": tracing_overhead,
        # adapt-on-request serving: adaptation_latency_ms p50/p95 and
        # tenants_per_sec under the strict zero-retrace gate
        # (informational — not part of baseline comparability)
        "serving": serving,
        # pinned workload descriptor: makes round-over-round lines
        # self-describing so a knob-default change can never silently turn
        # the driver series into an apples-to-oranges trend
        # (test_bench.py asserts the reduced-mode shapes never drift)
        "workload": {
            "image": [cfg.image_height, cfg.image_width, cfg.image_channels],
            "filters": cfg.cnn_num_filters,
            "stages": cfg.num_stages,
            "way": cfg.num_classes_per_set,
            "shot": cfg.num_samples_per_class,
            "targets": cfg.num_target_samples,
            "inner_steps": cfg.number_of_training_steps_per_iter,
            "second_order": True,
        },
    }
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_BASELINE.json")
    baseline_rec = None
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline_rec = json.load(f)
    # vs_baseline is a code-change regression signal, so the baseline must
    # match knob-for-knob — same backend, dtype, batch, lowering, remat,
    # precision, and workload shapes. A baseline recorded under different
    # knobs (e.g. the round-4 fp32/batch-8 record after the bf16/batch-12
    # defaults landed) is stale, not a comparison point.
    _COMPARABLE_KEYS = (
        "backend", "dtype", "batch_size", "n_chips", "conv_impl",
        "pool_impl", "pad_channels", "bn_stats_impl", "im2col_hoist",
        "meta_accum_steps", "task_axis_mode", "use_remat", "remat_policy",
        "matmul_precision", "workload",
    )
    comparable = (
        baseline_rec is not None
        and float(baseline_rec.get("value", 0.0)) > 0
        and all(baseline_rec.get(k) == result[k] for k in _COMPARABLE_KEYS)
    )
    if comparable:
        result["vs_baseline"] = round(
            tasks_per_sec / float(baseline_rec["value"]), 3
        )
    elif baseline_rec is not None:
        result["baseline_backend"] = baseline_rec.get("backend")
        # the compute-diet knobs (PR 16) remove bytes and redundant
        # elementwise/reduction work, never model FLOPs: a run that
        # differs from the baseline ONLY in those knobs must agree with
        # it on xla_flops_per_task to ±5% — a bigger drift means a lever
        # silently changed the math, and the line must not be trusted
        _DIET_KNOBS = ("pool_impl", "bn_stats_impl", "im2col_hoist")
        others_match = all(
            baseline_rec.get(k) == result[k]
            for k in _COMPARABLE_KEYS if k not in _DIET_KNOBS
        )
        base_flops = baseline_rec.get("xla_flops_per_task")
        if others_match and base_flops and result["xla_flops_per_task"]:
            ratio = float(result["xla_flops_per_task"]) / float(base_flops)
            if abs(ratio - 1.0) > 0.05:
                raise SystemExit(
                    f"bench: xla_flops_per_task drifted {ratio:.3f}x vs "
                    "baseline across compute-diet knobs (must be within "
                    "±5%: the diet removes bytes, not FLOPs) — "
                    f"{result['xla_flops_per_task']} vs {base_flops}"
                )
            result["flops_vs_baseline"] = round(ratio, 4)

    if backend == "tpu" and not comparable and default_knob_run and \
            os.environ.get("BENCH_NO_BASELINE_WRITE") != "1":
        # first DEFAULT-KNOB TPU run after a flagship-knob change records
        # itself as the new comparison point (the reference publishes no
        # throughput numbers). Sweep/A-B runs (any BENCH_* workload knob
        # set) never touch the baseline — a sweep must not clobber the
        # longitudinal regression signal.
        result["baseline_refreshed"] = True
        baseline_out = {
            k: v for k, v in result.items()
            if k not in ("vs_baseline", "baseline_backend",
                         "baseline_refreshed", "epoch_boundary",
                         "input_pipeline", "telemetry_overhead",
                         "health_overhead", "tracing_overhead",
                         "serving", "hlo_cost",
                         "donation", "roofline")
        }
        with open(baseline_path, "w") as f:
            json.dump(baseline_out, f, indent=1)

    print(json.dumps(result))


if __name__ == "__main__":
    main()
