"""Generate a Mini-ImageNet-SHAPED proxy dataset (synthetic, procedural).

Real Mini-ImageNet images cannot be obtained in this environment (no image
assets anywhere on the container, zero network egress — documented in
RESULTS.md). This builds the closest honest stand-in: a pre-split RGB
dataset with the real dataset's exact structure — 100 classes split
64/16/20 into ``train/ val/ test/`` folders (ref data.py:178-189), 600
JPEG images per class, 84x84x3 — flowing through the *identical* code path
(pre-split indexing, PIL load + /255 + ImageNet-stat normalize, mmap
cache, episodic sampling). Accuracy on it is NOT comparable to the paper's
Mini-ImageNet numbers; throughput and end-to-end behavior are.

Classes are procedurally learnable: each class is a fixed palette + blob
layout + stripe texture (seeded by class id); each image jitters blob
positions, brightness, and noise, so 5-way 5-shot episodes carry real
signal without being trivial.

    python datasets/make_mini_imagenet_proxy.py --out /tmp/proxy_data \
        [--images-per-class 600]
"""

from __future__ import annotations

import argparse
import os

import numpy as np

SPLITS = (("train", 64), ("val", 16), ("test", 20))
SIZE = 84


def _class_spec(rng: np.random.RandomState):
    """Per-class invariants: palette, blob layout, stripe frequency/phase."""
    return {
        "bg": rng.uniform(0.1, 0.9, 3),
        "blobs": [
            (
                rng.uniform(0.15, 0.85, 2),  # center (fractional x, y)
                rng.uniform(0.08, 0.25),  # radius (fraction of image)
                rng.uniform(0, 1, 3),  # color
            )
            for _ in range(rng.randint(2, 5))
        ],
        "freq": rng.uniform(2, 9),
        "phase": rng.uniform(0, 2 * np.pi),
        "angle": rng.uniform(0, np.pi),
    }


def _render(spec, rng: np.random.RandomState) -> np.ndarray:
    yy, xx = np.mgrid[0:SIZE, 0:SIZE] / SIZE
    img = np.broadcast_to(spec["bg"], (SIZE, SIZE, 3)).copy()
    # class stripe texture (fixed orientation/frequency, per-image phase jitter)
    u = xx * np.cos(spec["angle"]) + yy * np.sin(spec["angle"])
    stripes = 0.5 + 0.5 * np.sin(
        2 * np.pi * spec["freq"] * u + spec["phase"] + rng.uniform(-0.5, 0.5)
    )
    img = 0.75 * img + 0.25 * stripes[..., None] * spec["bg"]
    # class blobs, positions jittered per image
    for center, radius, color in spec["blobs"]:
        c = center + rng.uniform(-0.06, 0.06, 2)
        d2 = (xx - c[0]) ** 2 + (yy - c[1]) ** 2
        mask = np.exp(-d2 / (2 * radius**2))[..., None]
        img = img * (1 - mask) + color * mask
    img = img * rng.uniform(0.8, 1.2) + rng.normal(0, 0.03, img.shape)
    return (np.clip(img, 0, 1) * 255).astype(np.uint8)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True)
    ap.add_argument("--images-per-class", type=int, default=600)
    ap.add_argument("--name", default="mini_imagenet_full_size")
    args = ap.parse_args()

    from PIL import Image

    root = os.path.join(args.out, args.name)
    cls = 0
    for split, n_classes in SPLITS:
        for _ in range(n_classes):
            spec_rng = np.random.RandomState(1000 + cls)
            spec = _class_spec(spec_rng)
            d = os.path.join(root, split, f"n{90000000 + cls:08d}")
            os.makedirs(d, exist_ok=True)
            img_rng = np.random.RandomState(500_000 + cls)
            for j in range(args.images_per_class):
                Image.fromarray(_render(spec, img_rng), "RGB").save(
                    os.path.join(d, f"im{j:04d}.jpg"), quality=90
                )
            cls += 1
        print(f"{split}: {n_classes} classes done")
    total = cls * args.images_per_class
    print(f"wrote {total} images under {root}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
