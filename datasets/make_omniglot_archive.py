"""Build the bootstrap-compatible Omniglot archive.

Packs an ``omniglot_dataset/`` folder (1623 character classes x 20 drawings,
``alphabet/character/*.png``) into ``omniglot_dataset.tar.bz2`` with the
top-level folder name the extraction bootstrap expects
(``utils/dataset_tools.py``: archive at ``$DATASET_DIR/<dataset_name>.tar.bz2``
must contain ``<dataset_name>/``).

    python datasets/make_omniglot_archive.py --source /root/reference/datasets/omniglot_dataset
"""

from __future__ import annotations

import argparse
import os
import sys
import tarfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from howtotrainyourmamlpytorch_tpu.utils.dataset_tools import (  # noqa: E402
    EXPECTED_COUNTS,
)

EXPECTED_FILES = EXPECTED_COUNTS["omniglot_dataset"]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--source", required=True,
        help="existing omniglot_dataset folder (e.g. an upstream checkout's "
        "datasets/omniglot_dataset)",
    )
    ap.add_argument(
        "--out", default=os.path.join(os.path.dirname(__file__) or ".",
                                      "omniglot_dataset.tar.bz2"),
    )
    args = ap.parse_args()

    n = sum(
        1
        for _, _, files in os.walk(args.source)
        for f in files
        if f.lower().endswith(".png")
    )
    if n != EXPECTED_FILES:
        print(
            f"warning: {args.source} has {n} PNGs, expected {EXPECTED_FILES} "
            "(the bootstrap's count validation will re-extract and then fail)",
            file=sys.stderr,
        )

    tmp = args.out + ".tmp"
    with tarfile.open(tmp, "w:bz2") as tf:
        # arcname pins the top-level folder name the bootstrap requires
        tf.add(args.source, arcname="omniglot_dataset")
    os.replace(tmp, args.out)
    print(f"wrote {args.out} ({os.path.getsize(args.out) / 1e6:.1f} MB, {n} images)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
