#!/bin/bash
set -u
cd /root/repo
echo "== proxy 48f flagship training (pause after 30 epochs)"
DATASET_DIR=/root/repo/.round5/proxy_data timeout 7200 python train_maml_system.py \
  --experiment_name .round5/experiments/proxy_48f_5way5shot \
  --dataset_name mini_imagenet_full_size --dataset_path mini_imagenet_full_size \
  --sets_are_pre_split true --load_into_memory false \
  --indexes_of_folders_indicating_class "[-3, -2]" \
  --image_height 84 --image_width 84 --image_channels 3 \
  --num_classes_per_set 5 --num_samples_per_class 5 --num_target_samples 15 \
  --batch_size 2 --cnn_num_filters 48 --num_stages 4 --max_pooling true \
  --per_step_bn_statistics true \
  --learnable_per_layer_per_step_inner_loop_learning_rate true \
  --use_multi_step_loss_optimization true --second_order true \
  --number_of_training_steps_per_iter 5 --number_of_evaluation_steps_per_iter 5 \
  --total_epochs 500 --total_iter_per_epoch 100 --multi_step_loss_num_epochs 75 \
  --num_evaluation_tasks 40 --total_epochs_before_pause 30 \
  --use_mmap_cache true --compilation_cache_dir .round5/xla_cache --seed 0 \
  > .round5/train_proxy48f.log 2>&1
echo "proxy training rc=$?"
echo "== resume 20-way 64f"
DATASET_DIR=/root/reference nohup python train_maml_system.py \
  --experiment_name .round5/experiments/omniglot_20way_64f \
  --dataset_name omniglot_dataset --dataset_path datasets/omniglot_dataset \
  --train_val_test_split "[0.70918052988, 0.03080714725, 0.2606284658]" \
  --num_classes_per_set 20 --num_samples_per_class 1 --num_target_samples 1 \
  --batch_size 8 --cnn_num_filters 64 --num_stages 4 --max_pooling true \
  --per_step_bn_statistics true \
  --learnable_per_layer_per_step_inner_loop_learning_rate true \
  --use_multi_step_loss_optimization true --second_order true \
  --number_of_training_steps_per_iter 5 --number_of_evaluation_steps_per_iter 5 \
  --total_epochs 500 --total_iter_per_epoch 100 --multi_step_loss_num_epochs 50 \
  --num_evaluation_tasks 40 --total_epochs_before_pause 400 \
  --use_mmap_cache true --compilation_cache_dir .round5/xla_cache --seed 0 \
  >> .round5/train20_tpu_hp.log 2>&1 &
echo "20-way resumed pid $!"
