#!/bin/bash
set -u
cd /root/repo
DATASET_DIR=/root/reference timeout 3600 python train_maml_system.py \
  --experiment_name .round5/experiments/omniglot_5way_64f \
  --dataset_name omniglot_dataset --dataset_path datasets/omniglot_dataset \
  --train_val_test_split "[0.70918052988, 0.03080714725, 0.2606284658]" \
  --num_classes_per_set 5 --num_samples_per_class 1 --num_target_samples 1 \
  --batch_size 8 --cnn_num_filters 64 --num_stages 4 --max_pooling true \
  --per_step_bn_statistics true \
  --learnable_per_layer_per_step_inner_loop_learning_rate true \
  --use_multi_step_loss_optimization true --second_order true \
  --number_of_training_steps_per_iter 5 --number_of_evaluation_steps_per_iter 5 \
  --total_epochs 500 --total_iter_per_epoch 100 --multi_step_loss_num_epochs 50 \
  --num_evaluation_tasks 40 --total_epochs_before_pause 250 \
  --steps_per_dispatch 20 \
  --use_mmap_cache true --compilation_cache_dir .round5/xla_cache --seed 0 \
  >> .round5/train5way_tpu.log 2>&1
echo "extension rc=$?"
DATASET_DIR=/root/reference timeout 3600 python train_maml_system.py \
  --experiment_name .round5/experiments/omniglot_5way_64f \
  --dataset_name omniglot_dataset --dataset_path datasets/omniglot_dataset \
  --train_val_test_split "[0.70918052988, 0.03080714725, 0.2606284658]" \
  --num_classes_per_set 5 --num_samples_per_class 1 --num_target_samples 1 \
  --batch_size 8 --cnn_num_filters 64 --num_stages 4 --max_pooling true \
  --per_step_bn_statistics true \
  --learnable_per_layer_per_step_inner_loop_learning_rate true \
  --use_multi_step_loss_optimization true --second_order true \
  --number_of_training_steps_per_iter 5 --number_of_evaluation_steps_per_iter 5 \
  --total_epochs 500 --total_iter_per_epoch 100 --multi_step_loss_num_epochs 50 \
  --num_evaluation_tasks 600 --evaluate_on_test_set_only true \
  --use_mmap_cache true --compilation_cache_dir .round5/xla_cache --seed 0 \
  > .round5/ensemble_5way_final2.log 2>&1
echo "ensemble2 rc=$? : $(tail -1 .round5/ensemble_5way_final2.log | cut -c1-100)"
