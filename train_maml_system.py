"""Entry point: config -> model -> data -> experiment (ref:
train_maml_system.py:8-15).

Thin shim over ``howtotrainyourmamlpytorch_tpu.cli`` (also installed as the
``train-maml-system`` console script), kept at the repo root under the
reference's script name so the reference's launch commands work unchanged.

Usage:
    python train_maml_system.py --name_of_args_json_file experiment_config/x.json
    python train_maml_system.py --experiment_name foo --dataset_name omniglot_dataset ...

Any MAMLConfig field can be overridden on the command line; a JSON config
file (reference format) supplies the rest.
"""

from howtotrainyourmamlpytorch_tpu.cli import get_args, main  # noqa: F401

if __name__ == "__main__":
    main()
