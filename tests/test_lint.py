"""The repo-specific JAX-pitfall linter (analysis/lint.py) — jax-free.

Contract: the repo lints itself clean (every violation found during the
lint pass's introduction was fixed or suppressed with a reason), each
rule fires on a minimal bad fixture, reasoned suppressions silence a
rule, and unreasoned suppressions are themselves violations (MP005).
"""

import os
import subprocess
import sys
import textwrap

from howtotrainyourmamlpytorch_tpu.analysis import lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "howtotrainyourmamlpytorch_tpu")


def _write(tmp_path, rel, body):
    """Write a fixture under a fake package tree so path-scoped rules
    (core/, ops/, experiment/builder.py) arm."""
    path = tmp_path / "howtotrainyourmamlpytorch_tpu" / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(body))
    return str(path)


# -- the repo is clean -------------------------------------------------------


def test_repo_lints_clean():
    violations = lint.lint_paths(lint.default_paths())
    assert violations == [], "\n".join(str(v) for v in violations)


def test_default_paths_cover_package_and_bench():
    paths = lint.default_paths()
    assert PACKAGE in paths
    assert os.path.join(REPO_ROOT, "bench.py") in paths


# -- MP001: host ops in traced code ------------------------------------------


def test_mp001_flags_numpy_in_traced_scope(tmp_path):
    path = _write(tmp_path, "core/bad.py", """
        import jax.numpy as jnp
        import numpy as np

        def make_step():
            def step(x):
                y = jnp.sum(x)
                return np.asarray(y) * 2
            return step
    """)
    violations = lint.lint_file(path)
    assert [v.rule for v in violations] == ["MP001"]
    assert "np.asarray" in violations[0].message


def test_mp001_flags_item_and_float_and_print(tmp_path):
    path = _write(tmp_path, "ops/bad.py", """
        import jax.numpy as jnp

        def traced(x):
            s = jnp.mean(x)
            print("loss", float(s))
            return s.item()
    """)
    rules = [v.rule for v in lint.lint_file(path)]
    assert rules == ["MP001", "MP001", "MP001"]


def test_mp001_ignores_host_only_scopes(tmp_path):
    """A scope with no jax math (loss-weight builders, LUT builders) may
    use numpy freely; core/ host helpers stay lintable."""
    path = _write(tmp_path, "core/host.py", """
        import numpy as np

        def loss_weights(n):
            w = np.ones(n, dtype=np.float32) / n
            return np.minimum(w, 1.0)
    """)
    assert lint.lint_file(path) == []


def test_mp001_not_armed_outside_core_ops(tmp_path):
    path = _write(tmp_path, "experiment/whatever.py", """
        import jax.numpy as jnp
        import numpy as np

        def summarize(x):
            return float(np.mean(np.asarray(jnp.sum(x))))
    """)
    assert lint.lint_file(path) == []


# -- MP002: jit without donation at train seams ------------------------------


def test_mp002_flags_undonated_train_jit(tmp_path):
    path = _write(tmp_path, "experiment/bad_jit.py", """
        import jax
        from ..core import maml

        def build(cfg):
            return jax.jit(maml.make_train_step(cfg, True))
    """)
    violations = lint.lint_file(path)
    assert [v.rule for v in violations] == ["MP002"]
    assert "donate_argnums" in violations[0].message


def test_mp002_accepts_donated_train_jit_and_eval_jit(tmp_path):
    path = _write(tmp_path, "experiment/good_jit.py", """
        import jax
        from ..core import maml

        def build(cfg):
            train = jax.jit(
                maml.make_train_step(cfg, True),
                donate_argnums=maml.TRAIN_DONATE,
            )
            evaluate = jax.jit(maml.make_eval_step(cfg))
            return train, evaluate
    """)
    assert lint.lint_file(path) == []


# -- MP003: telemetry schema bypass ------------------------------------------


def test_mp003_flags_handrolled_schema_record(tmp_path):
    path = _write(tmp_path, "telemetry/bad_writer.py", """
        import json

        def emit(f, loss):
            rec = {"schema": 4, "ts": 0.0, "kind": "epoch", "loss": loss}
            f.write(json.dumps(rec))
    """)
    violations = lint.lint_file(path)
    assert [v.rule for v in violations] == ["MP003"]
    assert "make_record" in violations[0].message


def test_mp003_exempts_make_record_home(tmp_path):
    path = _write(tmp_path, "telemetry/sinks.py", """
        def make_record(kind):
            return {"schema": 4, "kind": kind}
    """)
    assert lint.lint_file(path) == []


# -- MP004: unrouted I/O in the builder --------------------------------------


def test_mp004_flags_direct_builder_io(tmp_path):
    path = _write(tmp_path, "experiment/builder.py", """
        def save(self):
            self.model.save_model(self.dir, 1, self.state)
            save_statistics(self.dir, ["a"])
    """)
    rules = [v.rule for v in lint.lint_file(path)]
    assert rules == ["MP004", "MP004"]


def test_mp004_accepts_retry_routed_io(tmp_path):
    path = _write(tmp_path, "experiment/builder.py", """
        def save(self):
            self.retry.call(
                lambda: self.model.save_model(self.dir, 1, self.state),
                site="ckpt_save",
            )
            self._write_stats(
                lambda: save_statistics(self.dir, ["a"]),
                site="stats_write",
            )
    """)
    assert lint.lint_file(path) == []


# -- MP006: non-owning views over restored/foreign memory --------------------


def test_mp006_flags_frombuffer_anywhere(tmp_path):
    path = _write(tmp_path, "data/bad_view.py", """
        import numpy as np

        def read_blob(buf):
            return np.frombuffer(buf, dtype=np.uint8)
    """)
    violations = lint.lint_file(path)
    assert [v.rule for v in violations] == ["MP006"]
    assert "non-owning view" in violations[0].message


def test_mp006_flags_asarray_in_checkpoint_restore_seam(tmp_path):
    path = _write(tmp_path, "experiment/checkpoint.py", """
        import numpy as np

        def load_leaf(restored):
            return np.asarray(restored)
    """)
    violations = lint.lint_file(path)
    assert [v.rule for v in violations] == ["MP006"]


def test_mp006_not_armed_for_asarray_outside_restore_seam(tmp_path):
    """np.asarray elsewhere (metric conversion in the builder/system) is
    legitimate — a jax.Array's __array__ copies to host; only the
    checkpoint restore seam aliases foreign-owned capsules."""
    path = _write(tmp_path, "experiment/builder_helper.py", """
        import numpy as np

        def summarize(v):
            return float(np.asarray(v).mean())
    """)
    assert lint.lint_file(path) == []


def test_mp006_accepts_explicit_owning_copies(tmp_path):
    path = _write(tmp_path, "experiment/checkpoint.py", """
        import numpy as np

        def load_leaf(restored, buf):
            a = np.array(restored)
            b = np.frombuffer(buf, dtype=np.uint8).copy()
            c = np.array(np.frombuffer(buf, dtype=np.uint8))
            return a, b, c
    """)
    assert lint.lint_file(path) == []


def test_mp006_reasoned_suppression_silences(tmp_path):
    path = _write(tmp_path, "data/justified_view.py", """
        import numpy as np

        def peek(buf):
            return np.frombuffer(buf, np.uint8)  # lint-ok: MP006 read-only view consumed before the mmap closes
    """)
    assert lint.lint_file(path) == []


# -- MP005: suppressions need reasons ----------------------------------------


def test_reasoned_suppression_silences_rule(tmp_path):
    path = _write(tmp_path, "core/suppressed.py", """
        import jax.numpy as jnp
        import numpy as np

        def make_step():
            def step(x):
                y = jnp.sum(x)
                return np.asarray(y)  # lint-ok: MP001 host fetch at trace build time, outside jit
            return step
    """)
    assert lint.lint_file(path) == []


def test_unreasoned_suppression_is_mp005(tmp_path):
    path = _write(tmp_path, "core/unreasoned.py", """
        import jax.numpy as jnp
        import numpy as np

        def make_step():
            def step(x):
                return np.asarray(jnp.sum(x))  # lint-ok: MP001
            return step
    """)
    rules = sorted(v.rule for v in lint.lint_file(path))
    # the suppression is rejected (MP005) AND the underlying MP001 stands
    assert rules == ["MP001", "MP005"]


def test_suppression_of_unknown_rule_is_mp005(tmp_path):
    path = _write(tmp_path, "core/unknown_rule.py", """
        X = 1  # lint-ok: MP999 not a rule
    """)
    rules = [v.rule for v in lint.lint_file(path)]
    assert rules == ["MP005"]


def test_suppression_for_wrong_rule_does_not_silence(tmp_path):
    path = _write(tmp_path, "core/wrong_rule.py", """
        import jax.numpy as jnp
        import numpy as np

        def make_step():
            def step(x):
                return np.asarray(jnp.sum(x))  # lint-ok: MP004 wrong rule named
            return step
    """)
    rules = sorted(v.rule for v in lint.lint_file(path))
    assert "MP001" in rules


# -- the CLI -----------------------------------------------------------------


def test_cli_lint_exits_zero_on_repo():
    proc = subprocess.run(
        [sys.executable, "-m", "howtotrainyourmamlpytorch_tpu.cli", "lint"],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 violation(s)" in proc.stderr


def test_cli_lint_exits_nonzero_on_pitfall_fixture(tmp_path):
    fixture = _write(tmp_path, "core/pitfall.py", """
        import jax.numpy as jnp

        def make_step():
            def step(x):
                s = jnp.mean(x)
                print(float(s))
                return s
            return step
    """)
    proc = subprocess.run(
        [sys.executable, "-m", "howtotrainyourmamlpytorch_tpu.cli", "lint",
         fixture],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "MP001" in proc.stdout


def test_cli_lint_json_output(tmp_path):
    fixture = _write(tmp_path, "core/pitfall.py", """
        import jax.numpy as jnp

        def make_step():
            def step(x):
                return jnp.mean(x).item()
            return step
    """)
    import io
    import json as json_mod
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = lint.main([fixture, "--json"])
    assert rc == 1
    payload = json_mod.loads(buf.getvalue())
    assert payload[0]["rule"] == "MP001"


def test_rule_catalogue_lists_all_rules(capsys):
    assert lint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("MP001", "MP002", "MP003", "MP004", "MP005", "MP006",
                 "MP007"):
        assert rule in out


# -- MP007: time.time() vs perf_counter -------------------------------------


def test_mp007_flags_time_time_module_call(tmp_path):
    path = tmp_path / "timing.py"
    path.write_text(
        "import time\n"
        "def measure():\n"
        "    start = time.time()\n"
        "    work()\n"
        "    return time.time() - start\n"
    )
    violations = lint.lint_file(str(path))
    assert [v.rule for v in violations] == ["MP007", "MP007"]
    assert violations[0].line == 3


def test_mp007_flags_from_import_and_aliases(tmp_path):
    path = tmp_path / "aliased.py"
    path.write_text(
        "from time import time\n"
        "import time as clock\n"
        "a = time()\n"
        "b = clock.time()\n"
    )
    violations = lint.lint_file(str(path))
    assert [v.rule for v in violations] == ["MP007", "MP007"]


def test_mp007_accepts_perf_counter_and_unrelated_time_attrs(tmp_path):
    path = tmp_path / "clean.py"
    path.write_text(
        "import time\n"
        "def measure():\n"
        "    start = time.perf_counter()\n"
        "    time.sleep(0.1)\n"
        "    m = time.monotonic()\n"
        "    return time.perf_counter() - start + m\n"
    )
    assert lint.lint_file(str(path)) == []


def test_mp007_not_armed_without_time_import(tmp_path):
    path = tmp_path / "other.py"
    path.write_text(
        "class time:\n"
        "    @staticmethod\n"
        "    def time():\n"
        "        return 0\n"
        "x = 1\n"
    )
    assert lint.lint_file(str(path)) == []


def test_mp007_reasoned_suppression_for_wall_clock_timestamp(tmp_path):
    path = tmp_path / "stamped.py"
    path.write_text(
        "import time\n"
        "ts = time.time()  # lint-ok: MP007 wall-clock timestamp\n"
    )
    assert lint.lint_file(str(path)) == []
