"""Validate bench.py's analytic FLOPs model against XLA's cost analysis.

The MFU the benchmark reports is ``tasks/s * train_flops_per_task / peak``;
if the hand-derived FLOPs model were wrong the headline number would be
silently garbage (round-3 verdict, weak #1). This pins the model to the
compiler's own count for the exact lowered train step — on CPU, today,
before any TPU number is quoted.

The model counts conv+linear only, so agreement tightens as width grows:
at 64 filters (conv-dominated, the paper width) it must be within 20%; at
16 filters the elementwise/BN share is structurally larger and the model
is documented as a ~35-45% undercount (still the conservative direction
for MFU).
"""

import numpy as np
import pytest

import bench
from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.core import maml, msl


def _cfg(filters, steps, max_pooling, **kw):
    return MAMLConfig(
        dataset_name="omniglot_dataset",
        image_height=28,
        image_width=28,
        image_channels=1,
        num_classes_per_set=5,
        num_samples_per_class=1,
        num_target_samples=1,
        batch_size=2,
        cnn_num_filters=filters,
        num_stages=4,
        max_pooling=max_pooling,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True,
        second_order=True,
        number_of_training_steps_per_iter=steps,
        number_of_evaluation_steps_per_iter=steps,
        use_remat=False,  # remat recompute would inflate the executed count
        task_axis_mode="vmap",
        **kw,
    )


def _xla_flops(cfg, second_order):
    import jax

    state = maml.init_state(cfg)
    rng = np.random.RandomState(0)
    b, way = cfg.batch_size, cfg.num_classes_per_set
    x_s = rng.randn(b, way, 1, 28, 28, 1).astype(np.float32)
    x_t = rng.randn(b, way, 1, 28, 28, 1).astype(np.float32)
    y_s = np.tile(np.arange(way, dtype=np.int32)[None, :, None], (b, 1, 1))
    y_t = y_s.copy()
    weights = np.asarray(
        msl.loss_weights_for(
            cfg.number_of_training_steps_per_iter, True, True, 0,
            cfg.multi_step_loss_num_epochs,
        )
    )
    step = jax.jit(maml.make_train_step(cfg, second_order=second_order))
    compiled = step.lower(state, x_s, y_s, x_t, y_t, weights, 1e-3).compile()
    return float(bench._cost_analysis_dict(compiled)["flops"])


# slow lane: each variant lowers + compiles a full second-order train step
# at conv-dominated width (~40s each on CPU), and the FLOPs model has no
# fast-lane consumers — bench quotes MFU from it only on real runs
@pytest.mark.slow
@pytest.mark.parametrize("second_order", [True, False])
def test_model_within_20pct_at_conv_dominated_width(second_order):
    cfg = _cfg(64, 5, max_pooling=True)
    xla = _xla_flops(cfg, second_order)
    model = bench.train_flops_per_task(cfg, second_order) * cfg.batch_size
    # MFU is only quoted for the second-order flagship step, where the
    # model must track the compiler's count tightly; the first-order 1.5x
    # factor is documented as "-ish" (train_flops_per_task) and measures a
    # ~30% undercount on this XLA version — still conservative (MFU could
    # only be understated), so it gets the conservative-bound check only
    if second_order:
        assert 0.8 < model / xla < 1.2, (model, xla)
    else:
        assert 0.5 < model / xla <= 1.05, (model, xla)


@pytest.mark.slow
@pytest.mark.parametrize("max_pooling", [True, False])
def test_model_is_conservative_at_small_width(max_pooling):
    """Both backbone branches: the model never OVER-counts (MFU reported
    from it can only understate utilization) and stays within 2x."""
    cfg = _cfg(16, 3, max_pooling=max_pooling)
    xla = _xla_flops(cfg, True)
    model = bench.train_flops_per_task(cfg, True) * cfg.batch_size
    assert 0.5 < model / xla <= 1.05, (model, xla)
