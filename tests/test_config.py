"""Config-system tests: reference JSON compatibility, bool coercion,
resume-key exclusion (parser_utils.py:58-106)."""

import json
import os

import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig

from conftest import REFERENCE_ROOT

REF_CONFIG = os.path.join(
    REFERENCE_ROOT,
    "experiment_config",
    "mini-imagenet_maml++-mini-imagenet_5_2_0.01_48_5_0.json",
)


def test_bool_coercion():
    cfg = MAMLConfig(second_order="True", max_pooling="false")
    assert cfg.second_order is True
    assert cfg.max_pooling is False


def test_json_load_ignores_resume_and_unknown_keys(tmp_path):
    path = tmp_path / "c.json"
    json.dump(
        {
            "batch_size": 7,
            "continue_from_epoch": 3,
            "gpu_to_use": 2,
            "some_unknown_key": 1,
        },
        open(path, "w"),
    )
    cfg = MAMLConfig.from_json_file(str(path))
    assert cfg.batch_size == 7
    assert cfg.continue_from_epoch == "latest"  # default untouched
    assert cfg.gpu_to_use == 0


def test_overrides_beat_json(tmp_path):
    path = tmp_path / "c.json"
    json.dump({"batch_size": 7}, open(path, "w"))
    cfg = MAMLConfig.from_json_file(str(path), batch_size=9)
    assert cfg.batch_size == 9


def test_inner_lr_quirk_preserved_and_fixable():
    """Reference reads task_learning_rate (0.1 default), never the JSON's
    init_inner_loop_learning_rate (SURVEY.md §5)."""
    cfg = MAMLConfig(task_learning_rate=0.1, init_inner_loop_learning_rate=0.01)
    assert cfg.inner_lr_init == 0.1
    fixed = cfg.replace(use_config_init_inner_lr=True)
    assert fixed.inner_lr_init == 0.01


def test_clip_grads_only_for_imagenet():
    assert MAMLConfig(dataset_name="mini_imagenet_full_size").clip_grads
    assert not MAMLConfig(dataset_name="omniglot_dataset").clip_grads


def test_bn_steps_sized_by_max_of_train_eval():
    cfg = MAMLConfig(
        number_of_training_steps_per_iter=5,
        number_of_evaluation_steps_per_iter=7,
    )
    assert cfg.bn_num_steps == 7


@pytest.mark.skipif(not os.path.exists(REF_CONFIG), reason="reference absent")
def test_loads_actual_reference_config():
    cfg = MAMLConfig.from_json_file(REF_CONFIG)
    assert cfg.batch_size == 2
    assert cfg.cnn_num_filters == 48
    assert cfg.num_classes_per_set == 5
    assert cfg.num_samples_per_class == 5
    assert cfg.second_order is True
    assert cfg.per_step_bn_statistics is True
    assert cfg.use_multi_step_loss_optimization is True
    assert cfg.sets_are_pre_split is True
    assert cfg.max_pooling is True
    assert cfg.total_epochs == 100


def test_classification_mean_std_from_json(tmp_path):
    """CIFAR normalization stats are real config fields consumed by the
    augment pipeline (ref data.py:86-90), not silently-dropped JSON keys."""
    import numpy as np

    from howtotrainyourmamlpytorch_tpu.data.episodes import augment_image

    path = tmp_path / "c.json"
    with open(path, "w") as f:
        json.dump(
            {
                "dataset_name": "cifar_fs",
                "classification_mean": [0.5071, 0.4866, 0.4409],
                "classification_std": [0.2673, 0.2564, 0.2762],
            },
            f,
        )
    cfg = MAMLConfig.from_json_file(str(path))
    assert cfg.classification_mean == [0.5071, 0.4866, 0.4409]
    assert cfg.classification_std == [0.2673, 0.2564, 0.2762]
    img = np.full((32, 32, 3), 0.5071, np.float32)
    out = augment_image(cfg, img, k=0, augment=False)
    # channel 0 was exactly at its mean -> normalizes to 0
    np.testing.assert_allclose(out[..., 0], 0.0, atol=1e-6)
    np.testing.assert_allclose(
        out[..., 1], (0.5071 - 0.4866) / 0.2564, rtol=1e-5
    )


def test_resolved_matmul_precision_auto_rules():
    """Pin the 'auto' resolution rules so a refactor cannot silently change
    numerics: fp32 compute needs TRUE fp32 MXU multiplies ('highest' — the
    default single-bf16-pass mode measurably stalls second-order MAML++
    learning, see RESULTS.md), bf16 compute keeps the native bf16 pass
    ('default'). Explicit values always pass through untouched."""
    assert (
        MAMLConfig(compute_dtype="float32").resolved_matmul_precision
        == "highest"
    )
    assert (
        MAMLConfig(compute_dtype="bfloat16").resolved_matmul_precision
        == "default"
    )
    # explicit settings win over the auto rule, for either compute dtype
    for precision in ("default", "high", "highest"):
        for dtype in ("float32", "bfloat16"):
            cfg = MAMLConfig(compute_dtype=dtype, matmul_precision=precision)
            assert cfg.resolved_matmul_precision == precision
    with pytest.raises(ValueError, match="matmul_precision"):
        MAMLConfig(matmul_precision="bf16_3x")


def test_compilation_cache_dir_default_and_resolution(tmp_path):
    """'auto' (default) defers to the experiment builder (resolved under the
    experiment dir); explicit paths and '' pass through to the system."""
    assert MAMLConfig().compilation_cache_dir == "auto"
    # the builder resolves 'auto' to <experiment_dir>/xla_cache
    import jax

    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        enable_compilation_cache,
    )

    prior = jax.config.jax_compilation_cache_dir
    try:
        enable_compilation_cache(str(tmp_path / "cache"))
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "cache")
        enable_compilation_cache("")
        assert jax.config.jax_compilation_cache_dir is None
    finally:
        jax.config.update("jax_compilation_cache_dir", prior)


def test_data_placement_validated():
    """data_placement is checked at config time: bad values, CIFAR (per-image
    RNG augmentation can't vectorize on device), and the missing flat-store
    backing all fail with clear errors instead of a silent wrong-numbers
    path."""
    MAMLConfig(data_placement="host")  # default path needs nothing extra
    MAMLConfig(data_placement="device", use_mmap_cache=True)
    MAMLConfig(data_placement="uint8_stream", use_mmap_cache=True)
    with pytest.raises(ValueError, match="data_placement"):
        MAMLConfig(data_placement="hbm")
    with pytest.raises(ValueError, match="CIFAR"):
        MAMLConfig(
            dataset_name="cifar_fs", data_placement="device",
            use_mmap_cache=True,
        )
    with pytest.raises(ValueError, match="CIFAR"):
        MAMLConfig(
            dataset_name="cifar100", data_placement="uint8_stream",
            use_mmap_cache=True,
        )
    with pytest.raises(ValueError, match="use_mmap_cache"):
        MAMLConfig(data_placement="device")
    with pytest.raises(ValueError, match="use_mmap_cache"):
        MAMLConfig(data_placement="uint8_stream")


def test_analysis_level_validated():
    """analysis_level is checked at config time like the other level
    knobs: 'off'/'warn'/'strict' pass, anything else fails by name."""
    for level in ("off", "warn", "strict"):
        assert MAMLConfig(analysis_level=level).analysis_level == level
    with pytest.raises(ValueError, match="analysis_level"):
        MAMLConfig(analysis_level="paranoid")


def test_hbm_budget_validated():
    """hbm_budget_gb (the SPMD audit's static per-device memory budget):
    0 disables, positive values pass, negatives fail by name."""
    assert MAMLConfig().hbm_budget_gb == 0.0
    assert MAMLConfig(hbm_budget_gb=16.0).hbm_budget_gb == 16.0
    with pytest.raises(ValueError, match="hbm_budget_gb"):
        MAMLConfig(hbm_budget_gb=-1.0)
