"""Program-contract auditor (analysis/auditor.py + contracts.py).

Three layers of coverage:

* the canonical program family audits CLEAN — donation, no-transfer,
  dtype-policy and op-census contracts hold on all four donating
  train-step jits, the fused eval multi-step and the index expander
  (the session-scoped ``audit_reports`` fixture compiles the family once);
* mutation tests — deliberately break one contract per throwaway program
  (donation dropped, a mid-step ``device_put``, an f64 upcast, an f32
  matmul under bf16, a census regression, a grouped-conv lowering) and
  assert exactly that contract fires with no cross-talk;
* the off-path — ``analysis_level='off'`` is config-only: programs built
  under 'off' and 'strict' trace to bit-identical jaxprs, and the
  dispatch path without a detector is a single attribute check.

Plus the runtime half: RetraceDetector signature hashing, retrace events,
strict-mode RetraceError, and the schema-v4 ``retrace`` telemetry record.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import make_micro_cfg, make_synthetic_batch

from howtotrainyourmamlpytorch_tpu.analysis import auditor as audit_lib
from howtotrainyourmamlpytorch_tpu.analysis import contracts as contracts_lib
from howtotrainyourmamlpytorch_tpu.analysis.auditor import (
    ProgramAuditor,
    RetraceDetector,
    RetraceError,
)
from howtotrainyourmamlpytorch_tpu.core import maml


def _contracts_hit(report):
    return sorted({v.contract for v in report.violations})


# -- the family audits clean -------------------------------------------------


def test_family_has_expected_programs(audit_reports):
    names = {r.program for r in audit_reports}
    assert names == {
        "train_step[so=1]",
        "train_multi_step[so=1,k=2]",
        "train_step_indexed[so=1]",
        "train_multi_step_indexed[so=1,k=2]",
        "eval_multi_step[k=2]",
        "index_expander",
        "serve_step[b=2]",
        "serve_step_uint8[b=2]",
        "predict_step[b=2]",
    }


def test_family_audits_clean(audit_reports):
    for r in audit_reports:
        assert r.ok, f"{r.program}: {[str(v) for v in r.violations]}"
        assert r.contracts_checked == contracts_lib.CONTRACT_NAMES


def test_family_census_nonempty(audit_reports):
    """Every compiled program yields a census (the op classes the baseline
    pins); the train steps are dot-dominated on the CPU im2col path."""
    by_name = {r.program: r for r in audit_reports}
    assert by_name["train_step[so=1]"].census.get("dot", 0) > 0
    assert by_name["index_expander"].census.get("gather", 0) > 0


# -- mutation tests: each contract fires alone -------------------------------


def test_donation_contract_fires_without_donation(micro_cfg):
    """The same train step jitted WITHOUT donate_argnums, audited against
    the declared donation contract: only 'donation' fires (the program is
    otherwise clean — no cross-talk)."""
    auditor = ProgramAuditor(micro_cfg)
    plain = jax.jit(maml.make_train_step(micro_cfg, second_order=True))
    state = audit_lib._state_avals(micro_cfg)
    batch = audit_lib._batch_avals(micro_cfg)
    weights = jax.ShapeDtypeStruct(
        (micro_cfg.number_of_training_steps_per_iter,), jnp.float32
    )
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    report = auditor.audit(
        "mutant_no_donation", plain, (state, *batch, weights, lr),
        donate=maml.TRAIN_DONATE,
    )
    assert _contracts_hit(report) == ["donation"]
    assert "double-buffered" in report.violations[0].detail


def test_transfer_contract_flags_device_put(micro_cfg):
    auditor = ProgramAuditor(micro_cfg)

    def bad(x):
        return jax.device_put(x) * 2.0

    report = auditor.audit(
        "mutant_device_put", jax.jit(bad),
        (jax.ShapeDtypeStruct((8, 8), jnp.float32),),
    )
    assert _contracts_hit(report) == ["no_transfer"]
    assert "device_put" in report.violations[0].detail


def test_transfer_contract_flags_host_callback(micro_cfg):
    auditor = ProgramAuditor(micro_cfg)

    def bad(x):
        return jax.pure_callback(
            lambda a: np.asarray(a) * 2,
            jax.ShapeDtypeStruct((8,), jnp.float32),
            x,
        )

    report = auditor.audit(
        "mutant_callback", jax.jit(bad),
        (jax.ShapeDtypeStruct((8,), jnp.float32),),
    )
    assert _contracts_hit(report) == ["no_transfer"]
    assert "pure_callback" in report.violations[0].detail


def test_dtype_contract_flags_f64(micro_cfg):
    from jax.experimental import enable_x64

    auditor = ProgramAuditor(micro_cfg)

    def bad(x):
        return (x.astype(jnp.float64) * 2.0).astype(jnp.float32)

    with enable_x64():
        report = auditor.audit(
            "mutant_f64", jax.jit(bad),
            (jax.ShapeDtypeStruct((8, 8), jnp.float32),),
        )
    assert _contracts_hit(report) == ["dtype_policy"]
    assert "float64" in report.violations[0].detail


def test_dtype_contract_flags_f32_matmul_under_bf16():
    """Under compute_dtype='bfloat16' a big f32 dot is an unintended
    upcast; scalar-sized f32 reductions (the MSL weighting dot) stay
    legal — pinned by the clean-family test, which includes bf16-legal
    f32 scalar dots."""
    cfg = make_micro_cfg(compute_dtype="bfloat16")
    auditor = ProgramAuditor(cfg)

    def bad(x, w):
        return x.astype(jnp.float32) @ w.astype(jnp.float32)

    report = auditor.audit(
        "mutant_f32_matmul", jax.jit(bad),
        (jax.ShapeDtypeStruct((32, 32), jnp.bfloat16),
         jax.ShapeDtypeStruct((32, 32), jnp.bfloat16)),
    )
    assert _contracts_hit(report) == ["dtype_policy"]
    assert "upcast" in report.violations[0].detail

    def small(x, w):
        # scalar-loss-sized f32 contraction: legal under the policy
        return jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))

    report = auditor.audit(
        "scalar_f32_dot", jax.jit(small),
        (jax.ShapeDtypeStruct((4,), jnp.bfloat16),
         jax.ShapeDtypeStruct((4,), jnp.bfloat16)),
    )
    assert report.ok


def test_bf16_train_step_audits_clean():
    """The real train step under the bf16 policy: its f32 dots are all
    scalar-loss reductions, so the dtype contract passes."""
    cfg = make_micro_cfg(compute_dtype="bfloat16")
    reports = audit_lib.audit_system_programs(
        cfg, programs=["train_step[so=1]"]
    )
    (report,) = reports
    assert report.ok, [str(v) for v in report.violations]


def test_grouped_conv_contract_fires_on_grouped_lowering(micro_cfg):
    """A vmap-over-batched-weights lax conv lowers to a
    feature_group_count=tasks grouped conv — the exact regression the
    op_census contract exists to catch on the GEMM path."""
    auditor = ProgramAuditor(micro_cfg)

    def conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    batched = jax.vmap(conv)
    report = auditor.audit(
        "mutant_grouped_conv", jax.jit(batched),
        (jax.ShapeDtypeStruct((3, 2, 8, 8, 4), jnp.float32),
         jax.ShapeDtypeStruct((3, 3, 3, 4, 4), jnp.float32)),
        expect_no_grouped_conv=True,
    )
    assert _contracts_hit(report) == ["op_census"]
    assert "grouped" in report.violations[0].detail


def test_census_regression_fires_and_improvement_does_not(micro_cfg):
    """An op-census baseline with fewer interesting ops than the current
    program flags a regression; a baseline with MORE (the current program
    improved) stays silent."""
    import dataclasses

    fingerprint = contracts_lib.config_fingerprint(
        dataclasses.asdict(micro_cfg)
    )

    def fake_baseline(census):
        return {
            "version": 1,
            "jax": jax.__version__,
            "backend": "cpu",
            "config_fingerprint": fingerprint,
            "programs": {"prog@cpu": {"census": census}},
        }

    def f(x, w):
        return x @ w

    args = (jax.ShapeDtypeStruct((16, 16), jnp.float32),
            jax.ShapeDtypeStruct((16, 16), jnp.float32))
    probe = ProgramAuditor(micro_cfg).audit("prog", jax.jit(f), args)
    current = probe.census
    smaller = {k: max(0, v - 1) for k, v in current.items()}
    bigger = {k: v + 5 for k, v in current.items()}

    regressed = ProgramAuditor(
        micro_cfg, baseline=fake_baseline(smaller),
        config_fingerprint=fingerprint,
    ).audit("prog", jax.jit(f), args)
    assert _contracts_hit(regressed) == ["op_census"]
    assert "regression" in regressed.violations[0].detail

    improved = ProgramAuditor(
        micro_cfg, baseline=fake_baseline(bigger),
        config_fingerprint=fingerprint,
    ).audit("prog", jax.jit(f), args)
    assert improved.ok


def test_census_compare_skipped_for_foreign_baseline(micro_cfg):
    """A baseline pinned under a different jax or audit config must never
    produce phantom regressions — the compare disarms."""
    baseline = {
        "version": 1, "jax": "0.0.0", "backend": "cpu",
        "config_fingerprint": "feedbeef00000000",
        "programs": {"prog@cpu": {"census": {"dot": 0, "fusion": 0}}},
    }
    auditor = ProgramAuditor(
        micro_cfg, baseline=baseline, config_fingerprint="something-else"
    )

    def f(x, w):
        return x @ w

    report = auditor.audit(
        "prog", jax.jit(f),
        (jax.ShapeDtypeStruct((16, 16), jnp.float32),
         jax.ShapeDtypeStruct((16, 16), jnp.float32)),
    )
    assert report.ok


def test_pinned_repo_baseline_loads():
    """CONTRACTS.json at the repo root parses and covers the seven canonical
    programs (the re-pin workflow keeps it in lockstep with the family)."""
    baseline = contracts_lib.load_baseline()
    assert baseline is not None, "CONTRACTS.json missing at the repo root"
    assert len(baseline["programs"]) >= 7
    for key in baseline["programs"]:
        assert "@" in key


# -- analysis_level='off' leaves programs untouched --------------------------


def test_analysis_off_programs_bit_identical():
    """analysis_level is pure configuration: the traced train-step jaxpr
    under 'off' and 'strict' is textually identical (the same discipline
    as the telemetry/health off-paths)."""
    cfg_off = make_micro_cfg(analysis_level="off")
    cfg_strict = make_micro_cfg(analysis_level="strict")
    state = audit_lib._state_avals(cfg_off)
    batch = audit_lib._batch_avals(cfg_off)
    weights = jax.ShapeDtypeStruct((2,), jnp.float32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    texts = []
    for cfg in (cfg_off, cfg_strict):
        step = jax.jit(
            maml.make_train_step(cfg, second_order=True),
            donate_argnums=maml.TRAIN_DONATE,
        )
        texts.append(str(step.trace(state, *batch, weights, lr).jaxpr))
    assert texts[0] == texts[1]


def test_analysis_off_installs_no_detector(micro_cfg):
    """The system facade with no detector keeps dispatching normally —
    the off-path is one attribute check."""
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )

    model = MAMLFewShotClassifier(micro_cfg, use_mesh=False)
    assert model.retrace_detector is None
    x_s, y_s, x_t, y_t = make_synthetic_batch(micro_cfg)
    losses = model.run_train_iter((x_s, x_t, y_s, y_t), epoch=0)
    assert np.isfinite(float(np.asarray(losses["loss"])))


# -- runtime retrace detection -----------------------------------------------


def test_retrace_detector_quiet_on_stable_signatures():
    det = RetraceDetector()
    args = (np.zeros((4, 8), np.float32), 0.01)
    for _ in range(5):
        assert det.observe("site_a", args) is False
    assert det.retrace_count == 0


def test_retrace_detector_flags_new_signature():
    events = []
    det = RetraceDetector(on_retrace=lambda **kw: events.append(kw))
    det.observe("site_a", (np.zeros((4, 8), np.float32),))
    # same shapes at another site: fine (different program)
    det.observe("site_b", (np.zeros((2, 8), np.float32),))
    assert det.retrace_count == 0
    # a NEW shape at a known site is a retrace
    assert det.observe("site_a", (np.zeros((5, 8), np.float32),)) is True
    assert det.retrace_count == 1
    assert events[0]["site"] == "site_a"
    assert events[0]["n_signatures"] == 2
    # dtype changes retrace too
    det.observe("site_a", (np.zeros((4, 8), np.int32),))
    assert det.retrace_count == 2
    # re-seeing a known signature stays quiet
    det.observe("site_a", (np.zeros((4, 8), np.float32),))
    assert det.retrace_count == 2


def test_retrace_detector_strict_raises():
    det = RetraceDetector(strict=True)
    det.observe("s", (np.zeros((4,), np.float32),))
    with pytest.raises(RetraceError, match="retraced mid-run"):
        det.observe("s", (np.zeros((8,), np.float32),))


def test_retrace_event_reaches_telemetry_schema_v4(tmp_path):
    """The on_retrace -> telemetry `retrace` record path the builder
    wires: the emitted log validates under the v4 schema and the inspect
    CLI surfaces the count."""
    from howtotrainyourmamlpytorch_tpu.telemetry import schema
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import Telemetry
    from howtotrainyourmamlpytorch_tpu.tools import telemetry_cli

    cfg = make_micro_cfg(telemetry_level="scalars")
    tel = Telemetry(cfg, str(tmp_path))
    det = RetraceDetector(
        on_retrace=lambda site, signature, n_signatures: tel.event(
            "retrace", iter=7, site=site, signature=signature,
            n_signatures=n_signatures,
        )
    )
    det.observe("train_step[so=1]", (np.zeros((4, 8), np.float32),))
    det.observe("train_step[so=1]", (np.zeros((4, 9), np.float32),))
    tel.close()
    log = os.path.join(str(tmp_path), "telemetry.jsonl")
    assert schema.validate_file(log) >= 2
    recs = [json.loads(line) for line in open(log) if line.strip()]
    retraces = [r for r in recs if r["kind"] == "retrace"]
    assert len(retraces) == 1
    assert retraces[0]["schema"] == schema.SCHEMA_VERSION
    assert retraces[0]["site"] == "train_step[so=1]"
    # inspect CLI: summary counts it, anomalies timeline renders a row
    rc = telemetry_cli.main(["summary", log])
    assert rc == 0
    rc = telemetry_cli.main(["anomalies", log])
    assert rc == 0


def test_system_dispatch_observes_retrace(micro_cfg):
    """The facade's dispatch hooks feed the detector: two train iters with
    different target-set sizes at one site flag exactly one retrace."""
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )

    events = []
    model = MAMLFewShotClassifier(micro_cfg, use_mesh=False)
    model.retrace_detector = RetraceDetector(
        on_retrace=lambda **kw: events.append(kw)
    )
    x_s, y_s, x_t, y_t = make_synthetic_batch(micro_cfg)
    model.run_train_iter((x_s, x_t, y_s, y_t), epoch=0)
    assert events == []
    # same site, fatter target set -> new abstract signature -> retrace
    x_t2 = np.concatenate([x_t, x_t], axis=2)
    y_t2 = np.concatenate([y_t, y_t], axis=2)
    model.run_train_iter((x_s, x_t2, y_s, y_t2), epoch=0)
    assert len(events) == 1
    assert events[0]["site"] == "train_step[so=1]"


# -- builder wiring ----------------------------------------------------------


class _BuilderShim:
    """The slice of ExperimentBuilder state `_install_analysis` and
    `_on_retrace` touch — exercises the real methods without a dataset."""

    def __init__(self, cfg, model, telemetry):
        self.cfg = cfg
        self.model = model
        self.telemetry = telemetry
        self.flight_recorder = None
        self.state = {"current_iter": 3}
        self.retrace_detector = None
        self.logged = []

    def _log(self, msg):
        self.logged.append(msg)

    from howtotrainyourmamlpytorch_tpu.experiment.builder import (
        ExperimentBuilder as _EB,
    )

    _install_analysis = _EB._install_analysis
    _audit_spmd = _EB._audit_spmd
    _on_retrace = _EB._on_retrace


def _fake_reports(violations):
    return [
        contracts_lib.AuditReport(
            program="train_step[so=1]",
            backend="cpu",
            contracts_checked=contracts_lib.CONTRACT_NAMES,
            violations=violations,
        )
    ]


def test_builder_warn_installs_detector_and_logs(monkeypatch, tmp_path):
    """analysis_level='warn': violations are logged, the run proceeds, and
    the retrace detector lands on the system facade."""
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import Telemetry

    cfg = make_micro_cfg(
        analysis_level="warn", telemetry_level="scalars"
    )
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    tel = Telemetry(cfg, str(tmp_path))
    shim = _BuilderShim(cfg, model, tel)
    bad = [contracts_lib.ContractViolation(
        "donation", "train_step[so=1]", "double-buffered"
    )]
    monkeypatch.setattr(audit_lib, "audit_system_programs",
                        lambda *a, **k: _fake_reports(bad))
    shim._install_analysis()
    assert shim.retrace_detector is not None
    assert model.retrace_detector is shim.retrace_detector
    assert not shim.retrace_detector.strict
    assert any("1 violation(s)" in m for m in shim.logged)
    # the wired _on_retrace emits a schema-valid v4 record
    shim.retrace_detector.observe("s", (np.zeros((2,), np.float32),))
    shim.retrace_detector.observe("s", (np.zeros((3,), np.float32),))
    tel.close()
    from howtotrainyourmamlpytorch_tpu.telemetry import schema

    log = os.path.join(str(tmp_path), "telemetry.jsonl")
    assert schema.validate_file(log) >= 1
    kinds = [json.loads(line)["kind"] for line in open(log) if line.strip()]
    assert "retrace" in kinds


def _fake_spmd_reports(violations, mesh_spec="1x8"):
    return [
        contracts_lib.SpmdAuditReport(
            program="train_step[so=1]",
            backend="cpu",
            contracts_checked=contracts_lib.SPMD_CONTRACT_NAMES,
            violations=violations,
            mesh_spec=mesh_spec,
            collectives={"all-reduce": {"ici": {"count": 2, "bytes": 64}}},
            roofline={
                "bound": "memory", "predicted_hfu": 0.2,
                "predicted_mfu": None, "flops_per_task": 1.0e6,
            },
        )
    ]


def test_builder_mesh_build_runs_spmd_audit(monkeypatch, tmp_path):
    """On a multi-device single-host build, _install_analysis adds the
    SPMD audit to the base one: its violations are logged and the
    telemetry `analysis` record (schema v5) carries the mesh and the
    flagship roofline summary."""
    from howtotrainyourmamlpytorch_tpu.analysis import spmd as spmd_lib
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import Telemetry

    cfg = make_micro_cfg(
        batch_size=8, analysis_level="warn", telemetry_level="scalars"
    )
    model = MAMLFewShotClassifier(cfg)  # 8 virtual devices -> task mesh
    assert model.mesh is not None
    tel = Telemetry(cfg, str(tmp_path))
    shim = _BuilderShim(cfg, model, tel)
    monkeypatch.setattr(audit_lib, "audit_system_programs",
                        lambda *a, **k: _fake_reports([]))
    bad = [contracts_lib.ContractViolation(
        "collective_census", "train_step[so=1]", "store gathered"
    )]
    seen = {}

    def fake_spmd_audit(cfg_, mesh=None, auditor=None, **kw):
        seen["mesh"] = mesh
        return _fake_spmd_reports(bad)

    monkeypatch.setattr(spmd_lib, "audit_spmd_programs", fake_spmd_audit)
    shim._install_analysis()
    assert seen["mesh"] is not None  # the SPMD family was audited
    assert any("1 SPMD program(s)" in m and "1 violation(s)" in m
               for m in shim.logged)
    tel.close()
    log = os.path.join(str(tmp_path), "telemetry.jsonl")
    from howtotrainyourmamlpytorch_tpu.telemetry import schema

    assert schema.validate_file(log) >= 1
    recs = [json.loads(line) for line in open(log) if line.strip()]
    analysis = [r for r in recs if r["kind"] == "analysis"]
    assert len(analysis) == 1
    assert analysis[0]["programs"] == 2  # 1 base + 1 SPMD (faked)
    assert analysis[0]["violations"] == 1
    assert analysis[0]["mesh"] == "1x8"
    assert analysis[0]["roofline"]["bound"] == "memory"

    # strict: the SPMD violation fails the build like a base one
    cfg_strict = make_micro_cfg(batch_size=8, analysis_level="strict")
    model2 = MAMLFewShotClassifier(cfg_strict)
    shim2 = _BuilderShim(cfg_strict, model2,
                         Telemetry(cfg_strict, str(tmp_path)))
    with pytest.raises(contracts_lib.AuditError, match="store gathered"):
        shim2._install_analysis()


def test_builder_strict_raises_on_violation(monkeypatch, tmp_path):
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import Telemetry

    cfg = make_micro_cfg(analysis_level="strict")
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    shim = _BuilderShim(cfg, model, Telemetry(cfg, str(tmp_path)))
    bad = [contracts_lib.ContractViolation(
        "no_transfer", "train_step[so=1]", "device_put x1"
    )]
    monkeypatch.setattr(audit_lib, "audit_system_programs",
                        lambda *a, **k: _fake_reports(bad))
    with pytest.raises(contracts_lib.AuditError, match="device_put"):
        shim._install_analysis()


def test_builder_strict_clean_installs_strict_detector(monkeypatch, tmp_path):
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import Telemetry

    cfg = make_micro_cfg(analysis_level="strict")
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    shim = _BuilderShim(cfg, model, Telemetry(cfg, str(tmp_path)))
    monkeypatch.setattr(audit_lib, "audit_system_programs",
                        lambda *a, **k: _fake_reports([]))
    shim._install_analysis()
    assert shim.retrace_detector.strict
    with pytest.raises(RetraceError):
        shim.retrace_detector.observe("s", (np.zeros((2,), np.float32),))
        shim.retrace_detector.observe("s", (np.zeros((3,), np.float32),))


# -- cli audit ---------------------------------------------------------------


@pytest.mark.slow
def test_cli_audit_end_to_end(tmp_path, micro_cfg, capsys):
    """`cli audit --config ... --json` compiles the family, reports every
    program ok, and exits 0; `--pin` writes a loadable baseline that a
    follow-up audit compares clean against."""
    import dataclasses

    from howtotrainyourmamlpytorch_tpu.tools import audit_cli

    cfg_path = tmp_path / "audit_cfg.json"
    with open(cfg_path, "w") as f:
        json.dump(dataclasses.asdict(micro_cfg), f)
    contracts_path = tmp_path / "CONTRACTS.json"
    rc = audit_cli.main([
        "--config", str(cfg_path), "--contracts", str(contracts_path),
        "--pin",
    ])
    assert rc == 0
    pinned = contracts_lib.load_baseline(str(contracts_path))
    assert pinned is not None and len(pinned["programs"]) == 7
    rc = audit_cli.main([
        "--config", str(cfg_path), "--contracts", str(contracts_path),
        "--json",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert all(p["ok"] for p in payload["programs"].values())
