"""Subprocess worker for the true multi-process distributed test.

Launched by ``tests/test_parallel.py::test_two_process_training_matches_single``
as 2 coordinated processes (CPU backend, 4 virtual devices each) and once as
a single 8-device process. Runs a few training epochs through ``cli.main`` —
the same entry the reference's launch scripts hit — so the real
``jax.distributed.initialize``, ``create_hybrid_device_mesh``,
``make_array_from_process_local_data``, bootstrap broadcast, collective
checkpointing, and primary-only metric writes all execute across genuine
process boundaries (supersedes ref few_shot_learning_system.py:73-81, whose
only scaling mechanism is single-process nn.DataParallel).
"""

from __future__ import annotations

import argparse
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--process_id", type=int, required=True)
    ap.add_argument("--num_processes", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--n_local_devices", type=int, required=True)
    ap.add_argument("--data_root", required=True)
    ap.add_argument("--exp_name", required=True)
    ap.add_argument("--cache_dir", required=True)
    ap.add_argument("--total_epochs", type=int, default=2)
    args = ap.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.n_local_devices}"
    )
    if args.num_processes > 1:
        # cli.main -> initialize_distributed() reads exactly these env vars
        os.environ["JAX_COORDINATOR_ADDRESS"] = f"localhost:{args.port}"
        os.environ["JAX_NUM_PROCESSES"] = str(args.num_processes)
        os.environ["JAX_PROCESS_ID"] = str(args.process_id)
    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.num_processes > 1:
        # cross-process collectives on the CPU backend need an explicit
        # implementation (the default 'none' client rejects multiprocess
        # computations); gloo-over-TCP ships in jaxlib and rides the same
        # coordination service jax.distributed.initialize sets up
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        # gloo cannot tolerate CONCURRENT collectives on one TCP pair: the
        # one-step-lag pipeline keeps a dispatch in flight while the next
        # is enqueued, and two overlapping all-reduces race the pair's
        # preamble ("op.preamble.length <= op.nbytes" aborts, ~1 in 3
        # runs). Inline dispatch serializes device programs, which is the
        # correct-first choice for a CPU test rig anyway.
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    from howtotrainyourmamlpytorch_tpu.cli import main as cli_main

    argv = [
        "--experiment_name", args.exp_name,
        # an "imagenet"-family name (RGB /255 + stat normalize + grad clamp)
        # that is NOT a known vendored dataset, so the bootstrap's file-count
        # contract treats it as a user dataset
        "--dataset_name", "imagenet_synthetic_presplit",
        "--dataset_path", args.data_root,
        "--sets_are_pre_split", "true",
        "--indexes_of_folders_indicating_class", "[-3, -2]",
        "--image_height", "10", "--image_width", "10", "--image_channels", "3",
        "--num_classes_per_set", "2", "--num_samples_per_class", "1",
        "--num_target_samples", "1",
        "--batch_size", "8",  # global meta-batch: 1 task per device
        "--cnn_num_filters", "4", "--num_stages", "2", "--max_pooling", "true",
        "--per_step_bn_statistics", "true",
        "--learnable_per_layer_per_step_inner_loop_learning_rate", "true",
        "--use_multi_step_loss_optimization", "true",
        "--second_order", "true",
        "--number_of_training_steps_per_iter", "2",
        "--number_of_evaluation_steps_per_iter", "2",
        "--total_epochs", str(args.total_epochs),
        "--total_iter_per_epoch", "2",
        "--num_evaluation_tasks", "8",
        "--num_dataprovider_workers", "2",
        "--cache_dir", args.cache_dir,
        "--use_mmap_cache", "true",
        "--use_remat", "false",
        "--seed", "0",
    ]
    cli_main(argv)
    print(f"WORKER_DONE process={jax.process_index()}", flush=True)


if __name__ == "__main__":
    sys.exit(main())
