"""Fault-injection registry (resilience/faults.py): grammar, deterministic
triggers, seam wiring, and the no-spec zero-impact guarantee (identical
jitted programs, bit-identical step metrics)."""

import signal

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.resilience import faults
from howtotrainyourmamlpytorch_tpu.resilience.faults import (
    FaultInjector,
    InjectedFaultError,
    parse_fault_spec,
)


@pytest.fixture(autouse=True)
def _clean_injector():
    faults.uninstall()
    yield
    faults.uninstall()


# -- grammar ------------------------------------------------------------------


def test_parse_issue_example_spec():
    fs = parse_fault_spec(
        "ckpt_save:oserror@iter=40,producer:raise@batch=10,"
        "signal:sigterm@iter=55"
    )
    assert [(f.site, f.action, f.cond_key, f.cond_value, f.repeat)
            for f in fs] == [
        ("ckpt_save", "oserror", "iter", 40, 1),
        ("producer", "raise", "call", 10, 1),  # batch normalizes to call
        ("signal", "sigterm", "iter", 55, 1),
    ]


def test_parse_repeat_suffix_and_roundtrip():
    (f,) = parse_fault_spec("ckpt_save:oserror@call=3x2")
    assert (f.cond_value, f.repeat) == (3, 2)
    assert parse_fault_spec(f.spec()) == [f]


def test_parse_empty_and_whitespace_spec_is_no_faults():
    assert parse_fault_spec("") == []
    assert parse_fault_spec("  , ,") == []
    assert faults.install("") is None
    assert faults.active_injector() is None


@pytest.mark.parametrize("bad", [
    "nonsense",
    "ckpt_save:oserror",              # no condition
    "unknown_site:oserror@call=1",
    "ckpt_save:unknown_action@call=1",
    "ckpt_save:oserror@weird=1",      # unknown condition key
    "ckpt_save:oserror@call=abc",
    "ckpt_save:oserror@call=1x0",     # repeat must be >= 1
    "signal:oserror@iter=5",          # signal site takes signal actions
    "ckpt_save:sigterm@call=1",       # handled signals only at site signal
])
def test_parse_rejects_bad_entries(bad):
    with pytest.raises(ValueError, match="fault_spec"):
        parse_fault_spec(bad)


def test_config_validates_fault_spec():
    cfg = MAMLConfig(fault_spec="ckpt_save:oserror@call=1")
    assert cfg.fault_spec == "ckpt_save:oserror@call=1"
    with pytest.raises(ValueError, match="fault_spec"):
        MAMLConfig(fault_spec="ckpt_save:oserror@")


# -- trigger determinism ------------------------------------------------------


def test_call_condition_fires_exact_window():
    inj = FaultInjector(parse_fault_spec("stats_write:oserror@call=2x2"))
    inj.fire("stats_write")  # call 1: clean
    for _ in range(2):       # calls 2 and 3: the repeat window
        with pytest.raises(InjectedFaultError):
            inj.fire("stats_write")
    inj.fire("stats_write")  # call 4: spent
    inj.fire("json_write")   # other sites never affected


def test_iter_condition_waits_for_builder_tick():
    inj = FaultInjector(parse_fault_spec("ckpt_save:oserror@iter=40"))
    inj.fire("ckpt_save")  # iter not yet reached: clean
    inj.tick(39)
    inj.fire("ckpt_save")
    inj.tick(40)
    with pytest.raises(InjectedFaultError):
        inj.fire("ckpt_save")
    inj.fire("ckpt_save")  # repeat=1: spent after one firing


def test_raise_action_is_not_an_oserror():
    inj = FaultInjector(parse_fault_spec("producer:raise@call=1"))
    with pytest.raises(RuntimeError) as ei:
        inj.fire("producer")
    assert not isinstance(ei.value, OSError)  # never absorbed by retries


def test_signal_site_delivers_on_tick():
    seen = []
    previous = signal.signal(
        signal.SIGTERM, lambda s, f: seen.append(s)
    )
    try:
        inj = FaultInjector(parse_fault_spec("signal:sigterm@iter=55"))
        inj.tick(54)
        assert seen == []
        inj.tick(55)
        assert seen == [signal.SIGTERM]
        inj.tick(56)  # repeat=1: delivered exactly once
        assert seen == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, previous)


def test_injected_oserror_names_itself():
    inj = FaultInjector(parse_fault_spec("json_write:oserror@call=1"))
    with pytest.raises(InjectedFaultError, match="injected fault"):
        inj.fire("json_write")


# -- module seam API ----------------------------------------------------------


def test_module_fire_noop_without_injector():
    faults.uninstall()
    faults.fire("ckpt_save")  # must not raise
    faults.tick(10**9)


def test_storage_seams_fire(tmp_path):
    from howtotrainyourmamlpytorch_tpu.utils.storage import (
        save_statistics,
        save_to_json,
    )

    faults.install("stats_write:oserror@call=1,json_write:oserror@call=1")
    with pytest.raises(InjectedFaultError):
        save_statistics(str(tmp_path), ["a", "b"], create=True)
    with pytest.raises(InjectedFaultError):
        save_to_json(str(tmp_path / "x.json"), {"a": 1})
    # both faults spent: the seams work again (retry semantics rely on it)
    save_statistics(str(tmp_path), ["a", "b"], create=True)
    save_to_json(str(tmp_path / "x.json"), {"a": 1})
    assert (tmp_path / "x.json").exists()


# -- zero impact without a spec ----------------------------------------------


def test_jitted_train_program_identical_with_and_without_spec(tiny_cfg):
    """The acceptance bar: fault injection lives entirely in host code, so
    the lowered train-step program is byte-identical whether or not an
    (untriggered) injector is installed."""
    import jax

    from howtotrainyourmamlpytorch_tpu.core import maml

    cfg = tiny_cfg
    state = maml.init_state(cfg)
    b, n = 2, cfg.num_classes_per_set
    s, t = cfg.num_samples_per_class, cfg.num_target_samples
    h, w, c = cfg.im_shape
    args = (
        state,
        np.zeros((b, n, s, h, w, c), np.float32),
        np.zeros((b, n, s), np.int32),
        np.zeros((b, n, t, h, w, c), np.float32),
        np.zeros((b, n, t), np.int32),
        np.ones((cfg.number_of_training_steps_per_iter,), np.float32),
        0.001,
    )

    def lowered_text():
        return jax.jit(
            maml.make_train_step(cfg, second_order=False)
        ).lower(*args).as_text()

    faults.uninstall()
    without = lowered_text()
    faults.install("ckpt_save:oserror@iter=40,signal:sigterm@iter=55")
    with_spec = lowered_text()
    assert without == with_spec


def test_step_metrics_bit_identical_with_untriggered_spec(
    tiny_cfg, synthetic_batch
):
    """Running real train steps with a never-triggering spec installed
    produces bit-identical metrics and parameters."""
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )

    def run(spec):
        faults.install(spec)
        try:
            model = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
            out = []
            for i in range(2):
                batch = synthetic_batch(tiny_cfg, seed=i)
                x_s, y_s, x_t, y_t = batch
                losses = model.run_train_iter(
                    (x_s, x_t, y_s, y_t), epoch=0
                )
                out.append(
                    {k: np.asarray(v) for k, v in losses.items()}
                )
            import jax

            params = jax.device_get(model.state.net)
            return out, params
        finally:
            faults.uninstall()

    out_a, params_a = run("")
    out_b, params_b = run(
        "ckpt_save:oserror@iter=999999,signal:sigterm@iter=999999"
    )
    for da, db in zip(out_a, out_b):
        assert sorted(da) == sorted(db)
        for k in da:
            np.testing.assert_array_equal(da[k], db[k])
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(params_a),
                    jax.tree_util.tree_leaves(params_b)):
        np.testing.assert_array_equal(a, b)
