"""MSL schedule unit tests (values from few_shot_learning_system.py:83-103
computed by hand)."""

import numpy as np

from howtotrainyourmamlpytorch_tpu.core import msl


def test_epoch_zero_uniform():
    w = msl.per_step_loss_importance(5, 15, epoch=0)
    np.testing.assert_allclose(w, np.full(5, 0.2), rtol=1e-6)


def test_epoch_one_values():
    # decay_rate = 1/5/15 = 1/75; non-final 0.2 - 1/75; final 0.2 + 4/75
    w = msl.per_step_loss_importance(5, 15, epoch=1)
    np.testing.assert_allclose(w[:4], 0.2 - 1.0 / 75, rtol=1e-5)
    np.testing.assert_allclose(w[4], 0.2 + 4.0 / 75, rtol=1e-5)


def test_fully_annealed_floor_and_cap():
    # at epoch >= 15: non-final floored at 0.03/5 = 0.006,
    # final capped at 1 - 4*0.006 = 0.976
    for epoch in (15, 40, 1000):
        w = msl.per_step_loss_importance(5, 15, epoch=epoch)
        np.testing.assert_allclose(w[:4], 0.006, rtol=1e-6)
        np.testing.assert_allclose(w[4], 0.976, rtol=1e-6)


def test_sums_to_one_while_annealing():
    for epoch in range(0, 16):
        w = msl.per_step_loss_importance(5, 15, epoch=epoch)
        np.testing.assert_allclose(w.sum(), 1.0, rtol=1e-5)


def test_gate_matches_reference_branches():
    # MSL active only when use_msl and training and epoch < anneal epochs
    # (few_shot_learning_system.py:232)
    N = 5
    active = msl.loss_weights_for(N, True, True, 3, 15)
    assert active[0] != 0.0
    for args in [(True, True, 15), (True, True, 99), (True, False, 3), (False, True, 3)]:
        use, train, ep = args
        w = msl.loss_weights_for(N, use, train, ep, 15)
        np.testing.assert_array_equal(w, msl.final_step_only(N))


def test_single_step_degenerate():
    w = msl.per_step_loss_importance(1, 15, epoch=0)
    np.testing.assert_allclose(w, [1.0])
