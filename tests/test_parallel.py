"""Multi-device sharding tests on the 8-device virtual CPU mesh.

The key invariant: sharding the task axis over the mesh must be numerically
equivalent to single-device execution — the TPU-native replacement for
``nn.DataParallel``'s scatter/gather must be a pure re-layout (SURVEY.md
§2.2). The reference could never test this (no distributed backend)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.core import maml, msl
from howtotrainyourmamlpytorch_tpu.parallel import mesh as mesh_lib


@pytest.fixture(autouse=True)
def _require_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")


def _weights(cfg):
    return jnp.asarray(
        msl.per_step_loss_importance(
            cfg.number_of_training_steps_per_iter,
            cfg.multi_step_loss_num_epochs,
            0,
        )
    )


def test_sharded_step_matches_single_device(tiny_cfg, synthetic_batch):
    """Sharding the task axis must reproduce single-device meta-gradients.
    Compared at the gradient level: post-Adam weights would amplify the
    psum's float-reordering noise on ~zero-gradient params (conv bias under
    BN) into O(lr) differences."""
    cfg = tiny_cfg.replace(batch_size=8)
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg, batch_size=8)
    w = _weights(cfg)
    grads_fn = jax.jit(maml.make_grads_fn(cfg, second_order=True))

    # single device
    loss_single, g_single = grads_fn(state, x_s, y_s, x_t, y_t, w)

    # 8-device task mesh
    mesh = mesh_lib.task_mesh(8)
    state_r = mesh_lib.replicate_state(mesh, maml.init_state(cfg))
    xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    loss_shard, g_shard = grads_fn(state_r, xs, ys, xt, yt, w)

    assert float(loss_single) == pytest.approx(float(loss_shard), rel=1e-5)
    for part in ("net", "lslr"):
        for k in g_single[part]:
            np.testing.assert_allclose(
                np.asarray(g_single[part][k]), np.asarray(g_shard[part][k]),
                atol=1e-5, rtol=1e-4, err_msg=f"{part}.{k}",
            )

    # the full train step must also run sharded and agree on metrics
    step = jax.jit(maml.make_train_step(cfg, second_order=True))
    _, m_single = step(state, x_s, y_s, x_t, y_t, w, 0.01)
    _, m_shard = step(state_r, xs, ys, xt, yt, w, 0.01)
    assert float(m_single["loss"]) == pytest.approx(
        float(m_shard["loss"]), rel=1e-5
    )
    assert float(m_single["accuracy"]) == pytest.approx(
        float(m_shard["accuracy"]), abs=1e-6
    )


def test_large_meta_batch_256_tasks(tiny_cfg, synthetic_batch):
    """The large-meta-batch capability (BASELINE.json: '>=256 tasks across
    the mesh'): one second-order MAML++ step with 256 tasks sharded over the
    8-device mesh compiles and executes (tiny shapes keep CPU runtime sane)."""
    cfg = tiny_cfg.replace(
        batch_size=256,
        image_height=8,
        image_width=8,
        cnn_num_filters=4,
        num_stages=2,
        use_remat=True,
    )
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    w = _weights(cfg)
    mesh = mesh_lib.task_mesh(8)
    state = mesh_lib.replicate_state(mesh, state)
    xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    step = jax.jit(maml.make_train_step(cfg, second_order=True))
    new_state, metrics = step(state, xs, ys, xt, yt, w, 0.001)
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_mesh_requires_divisible_batch():
    mesh = mesh_lib.task_mesh(8)
    with pytest.raises(ValueError, match="not divisible"):
        mesh_lib.shard_batch(mesh, np.zeros((6, 2)))


def test_eval_step_sharded(tiny_cfg, synthetic_batch):
    cfg = tiny_cfg.replace(batch_size=8)
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg, batch_size=8)
    ev = jax.jit(maml.make_eval_step(cfg))
    m_single, p_single = ev(state, x_s, y_s, x_t, y_t)

    mesh = mesh_lib.task_mesh(8)
    state_r = mesh_lib.replicate_state(mesh, state)
    xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    m_shard, p_shard = ev(state_r, xs, ys, xt, yt)
    np.testing.assert_allclose(
        np.asarray(p_single), np.asarray(p_shard), atol=1e-5
    )
    assert float(m_single["accuracy"]) == pytest.approx(
        float(m_shard["accuracy"]), abs=1e-6
    )


def test_submesh_sizes(tiny_cfg, synthetic_batch):
    """Mesh over a subset of devices (num_devices knob)."""
    cfg = tiny_cfg.replace(batch_size=4)
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg, batch_size=4)
    step = jax.jit(maml.make_train_step(cfg, second_order=False))
    ref_state, ref_m = step(state, x_s, y_s, x_t, y_t, _weights(cfg), 0.01)
    for n in (2, 4):
        mesh = mesh_lib.task_mesh(n)
        sr = mesh_lib.replicate_state(mesh, maml.init_state(cfg))
        xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
        _, m = step(sr, xs, ys, xt, yt, _weights(cfg), 0.01)
        assert float(m["loss"]) == pytest.approx(float(ref_m["loss"]), rel=1e-5)
