"""Multi-device sharding tests on the 8-device virtual CPU mesh.

The key invariant: sharding the task axis over the mesh must be numerically
equivalent to single-device execution — the TPU-native replacement for
``nn.DataParallel``'s scatter/gather must be a pure re-layout (SURVEY.md
§2.2). The reference could never test this (no distributed backend).

Structure (the PR 8 rework, mirroring what PR 7 did to test_donation):
ONE direct numeric-equivalence test exercises the placement helpers end
to end (``test_sharded_step_matches_single_device``) and one direct-API
test pins each helper's sharding spec; everything that used to hand-roll
"is this program actually sharded / does eval shard like train / do
submeshes work" assertions is re-expressed through the SPMD auditor
contracts (``analysis/spmd.py``) — the same machinery ``cli audit
--mesh`` and the builder's build-time audit run, so the tests and the
production gate can never drift apart."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from howtotrainyourmamlpytorch_tpu.core import maml, msl
from howtotrainyourmamlpytorch_tpu.parallel import (
    distributed,
    mesh as mesh_lib,
)


@pytest.fixture(autouse=True)
def _require_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")


def _weights(cfg):
    return jnp.asarray(
        msl.per_step_loss_importance(
            cfg.number_of_training_steps_per_iter,
            cfg.multi_step_loss_num_epochs,
            0,
        )
    )


def test_sharded_step_matches_single_device(tiny_cfg, synthetic_batch):
    """Sharding the task axis must reproduce single-device meta-gradients.
    Compared at the gradient level: post-Adam weights would amplify the
    psum's float-reordering noise on ~zero-gradient params (conv bias under
    BN) into O(lr) differences."""
    cfg = tiny_cfg.replace(batch_size=8)
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg, batch_size=8)
    w = _weights(cfg)
    grads_fn = jax.jit(maml.make_grads_fn(cfg, second_order=True))

    # single device
    loss_single, g_single = grads_fn(state, x_s, y_s, x_t, y_t, w)

    # 8-device task mesh
    mesh = mesh_lib.task_mesh(8)
    state_r = mesh_lib.replicate_state(mesh, maml.init_state(cfg))
    xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    loss_shard, g_shard = grads_fn(state_r, xs, ys, xt, yt, w)

    assert float(loss_single) == pytest.approx(float(loss_shard), rel=1e-5)
    for part in ("net", "lslr"):
        for k in g_single[part]:
            np.testing.assert_allclose(
                np.asarray(g_single[part][k]), np.asarray(g_shard[part][k]),
                atol=1e-5, rtol=1e-4, err_msg=f"{part}.{k}",
            )

    # the full train step must also run sharded and agree on metrics
    step = jax.jit(maml.make_train_step(cfg, second_order=True))
    _, m_single = step(state, x_s, y_s, x_t, y_t, w, 0.01)
    _, m_shard = step(state_r, xs, ys, xt, yt, w, 0.01)
    assert float(m_single["loss"]) == pytest.approx(
        float(m_shard["loss"]), rel=1e-5
    )
    assert float(m_single["accuracy"]) == pytest.approx(
        float(m_shard["accuracy"]), abs=1e-6
    )


def test_large_meta_batch_256_tasks(tiny_cfg, synthetic_batch):
    """The large-meta-batch capability (BASELINE.json: '>=256 tasks across
    the mesh'): one second-order MAML++ step with 256 tasks sharded over the
    8-device mesh compiles and executes (tiny shapes keep CPU runtime sane)."""
    cfg = tiny_cfg.replace(
        batch_size=256,
        image_height=8,
        image_width=8,
        cnn_num_filters=4,
        num_stages=2,
        use_remat=True,
    )
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    w = _weights(cfg)
    mesh = mesh_lib.task_mesh(8)
    state = mesh_lib.replicate_state(mesh, state)
    xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    step = jax.jit(maml.make_train_step(cfg, second_order=True))
    new_state, metrics = step(state, xs, ys, xt, yt, w, 0.001)
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_mesh_requires_divisible_batch():
    mesh = mesh_lib.task_mesh(8)
    with pytest.raises(ValueError, match="not divisible"):
        mesh_lib.shard_batch(mesh, np.zeros((6, 2)))


# -- one direct-API test per placement helper --------------------------------


def test_task_mesh_and_batch_sharding_specs():
    mesh = mesh_lib.task_mesh(8)
    assert mesh.axis_names == (mesh_lib.TASK_AXIS,)
    assert mesh.devices.shape == (8,)
    assert mesh_lib.batch_sharding(mesh).spec == P(mesh_lib.TASK_AXIS)
    assert tuple(mesh_lib.replicated(mesh).spec) == ()


def test_shard_batch_places_task_axis(tiny_cfg, synthetic_batch):
    mesh = mesh_lib.task_mesh(8)
    x_s, *_ = synthetic_batch(tiny_cfg, batch_size=8)
    (placed,) = mesh_lib.shard_batch(mesh, x_s)
    assert placed.sharding.spec == P(mesh_lib.TASK_AXIS)
    np.testing.assert_array_equal(np.asarray(placed), x_s)


def test_shard_stacked_batch_places_axis1(tiny_cfg, synthetic_batch):
    """The k-chunk variant: leading scan axis replicated, task axis (dim
    1) split over the mesh, values untouched."""
    mesh = mesh_lib.task_mesh(8)
    x_s, *_ = synthetic_batch(tiny_cfg, batch_size=8)
    stacked = np.stack([x_s, x_s])
    (placed,) = mesh_lib.shard_stacked_batch(mesh, stacked)
    assert tuple(placed.sharding.spec) == (None, mesh_lib.TASK_AXIS)
    np.testing.assert_array_equal(np.asarray(placed), stacked)


def test_replicate_state_and_array_specs(tiny_cfg):
    mesh = mesh_lib.task_mesh(8)
    state = mesh_lib.replicate_state(mesh, maml.init_state(tiny_cfg))
    for leaf in jax.tree_util.tree_leaves(state):
        assert tuple(leaf.sharding.spec) == ()
    store = mesh_lib.replicate_array(
        mesh, np.arange(64, dtype=np.uint8).reshape(8, 8)
    )
    assert tuple(store.sharding.spec) == ()
    assert store.is_fully_replicated


def test_hybrid_task_mesh_and_global_batch_sharding():
    """The pod-mesh helpers: a (hosts, tasks) grid with the host axis
    major (rows never mix simulated hosts) and a global batch spec that
    shards the leading axis over BOTH mesh axes."""
    mesh = distributed.hybrid_task_mesh(processes=2)
    assert mesh.axis_names == (distributed.DATA_AXIS, mesh_lib.TASK_AXIS)
    assert mesh.devices.shape == (2, 4)
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    assert sorted(ids.flatten().tolist()) == list(range(8))
    sharding = distributed.global_batch_sharding(mesh)
    assert tuple(sharding.spec) == (
        (distributed.DATA_AXIS, mesh_lib.TASK_AXIS),
    )
    with pytest.raises(ValueError, match="not divisible"):
        distributed.hybrid_task_mesh(processes=3)


# -- the hand-rolled sharding assertions, re-expressed as SPMD contracts -----


def test_eval_program_sharding_via_spmd_contracts(spmd_audit_reports):
    """What test_eval_step_sharded used to prove numerically — eval
    shards its batch like train and keeps the state replicated — is now
    the auditor's sharding contract on the fused eval program, plus the
    collective census pinning that eval reduces ONLY metric-sized values
    (no gradient, pixel or store bytes on the interconnect)."""
    eval_report = next(
        r for r in spmd_audit_reports if r.program == "eval_multi_step[k=2]"
    )
    assert eval_report.ok, [str(v) for v in eval_report.violations]
    assert "sharding" in eval_report.contracts_checked
    total_coll_bytes = sum(
        s["bytes"]
        for by_axis in eval_report.collectives.values()
        for s in by_axis.values()
    )
    # metric means only: far below one task's pixel payload
    task_bytes = 4 * np.prod(
        (2, 1) + (8, 8, 1)
    )
    assert 0 < total_coll_bytes < task_bytes


def test_train_program_sharding_via_spmd_contracts(spmd_audit_reports):
    """The train-step twin: batch over (data, task), state replicated in
    and out, gradient all-reduce present — the contracts `cli audit
    --mesh` gates on, asserted from the same reports."""
    for name in ("train_step[so=1]", "train_multi_step[so=1,k=2]"):
        r = next(x for x in spmd_audit_reports if x.program == name)
        assert r.ok, [str(v) for v in r.violations]
        assert r.collectives.get("all-reduce"), name


def test_submesh_audits_clean(spmd_micro_cfg):
    """What test_submesh_sizes proved numerically per mesh size — the
    step stays correct on a device subset — is now: the program family's
    flagship step audits clean under a 1x4 submesh (the num_devices
    knob's shape), with its own mesh-keyed census."""
    from howtotrainyourmamlpytorch_tpu.analysis import spmd as spmd_lib

    mesh = spmd_lib.build_audit_mesh(1, 4)
    auditor = spmd_lib.SpmdAuditor(spmd_micro_cfg, mesh)
    (report,) = spmd_lib.audit_spmd_programs(
        spmd_micro_cfg, mesh=mesh, auditor=auditor,
        programs=["train_step[so=1]"],
    )
    assert report.mesh_spec == "1x4"
    assert report.ok, [str(v) for v in report.violations]
    assert report.collectives.get("all-reduce")


# -- true multi-process execution (VERDICT r2 #3) -------------------------

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn_workers(exp_name, data_root, cache_dir,
                   num_processes, n_local_devices, total_epochs=2):
    """Spawn the coordinated worker gang without waiting (kill tests poll)."""
    import subprocess
    import sys as _sys

    port = _free_port()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(os.path.dirname(__file__), "_mp_train_worker.py")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # workers own their XLA_FLAGS/JAX_PLATFORMS; drop the conftest's
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return [
        subprocess.Popen(
            [
                _sys.executable, worker,
                "--process_id", str(pid),
                "--num_processes", str(num_processes),
                "--port", str(port),
                "--n_local_devices", str(n_local_devices),
                "--data_root", str(data_root),
                "--exp_name", str(exp_name),
                "--cache_dir", str(cache_dir),
                "--total_epochs", str(total_epochs),
            ],
            env=env,
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(num_processes)
    ]


def _launch_training(exp_name, data_root, cache_dir,
                     num_processes, n_local_devices, timeout=900,
                     total_epochs=2):
    """Launch `num_processes` coordinated _mp_train_worker.py subprocesses
    and return their outputs (raises on any non-zero exit)."""
    import subprocess

    procs = _spawn_workers(
        exp_name, data_root, cache_dir, num_processes, n_local_devices,
        total_epochs,
    )
    # drain all pipes concurrently: a worker blocked on a full stdout pipe
    # inside a collective would deadlock the whole gang
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(len(procs)) as pool:
        futs = [pool.submit(p.communicate, timeout=timeout) for p in procs]
        try:
            outs = [f.result()[0] for f in futs]
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {pid} failed rc={p.returncode}:\n{out[-4000:]}"
        )
    return outs


def _read_csv_columns(path):
    import csv

    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert rows, f"no rows in {path}"
    out = {}
    for k in rows[0]:
        try:
            out[k] = np.array([float(r[k]) for r in rows])
        except (TypeError, ValueError):
            pass  # non-numeric column
    return out


@pytest.mark.slow
def test_two_process_training_matches_single(tmp_path):
    """Two REAL processes (jax.distributed.initialize, CPU backend, 4 virtual
    devices each) train through cli.main and must produce the same per-epoch
    losses as one 8-device process on the same global task stream.

    This executes — across genuine process boundaries — the hybrid DCN x ICI
    mesh (`create_hybrid_device_mesh`), per-host batch slices assembled with
    `make_array_from_process_local_data`, the dataset-bootstrap broadcast,
    collective orbax checkpointing with a primary-only swap, primary-only
    metric writes, and the cross-host prediction allgather of the test
    ensemble. The reference has no distributed backend at all
    (few_shot_learning_system.py:73-81 is single-process nn.DataParallel).
    """
    from test_e2e_presplit import _write_presplit_rgb

    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root), n_classes=4, per_class=6, size=10)

    exp_multi = tmp_path / "exp_multi"
    exp_single = tmp_path / "exp_single"
    cache_dir = tmp_path / "cache"

    outs = _launch_training(
        exp_multi, data_root, cache_dir, num_processes=2, n_local_devices=4,
    )
    assert any("WORKER_DONE process=0" in o for o in outs)
    assert any("WORKER_DONE process=1" in o for o in outs)

    _launch_training(
        exp_single, data_root, cache_dir, num_processes=1, n_local_devices=8,
    )

    csv_multi = _read_csv_columns(
        os.path.join(exp_multi, "logs", "summary_statistics.csv")
    )
    csv_single = _read_csv_columns(
        os.path.join(exp_single, "logs", "summary_statistics.csv")
    )
    assert len(csv_multi["train_loss_mean"]) == 2  # both trained 2 epochs
    for key in ("train_loss_mean", "val_loss_mean"):
        np.testing.assert_allclose(
            csv_multi[key], csv_single[key], atol=2e-3,
            err_msg=f"{key} diverged between 2-process and single-process",
        )
    for key in ("train_accuracy_mean", "val_accuracy_mean"):
        # identical stream; allow one task flip from fp reduction order
        np.testing.assert_allclose(
            csv_multi[key], csv_single[key], atol=0.13, err_msg=key,
        )
    # only the primary process wrote metric files in the 2-process run:
    # exactly one header + one data row, not two processes' interleaved writes
    with open(os.path.join(exp_multi, "logs", "test_summary.csv")) as f:
        test_rows = [ln for ln in f.read().splitlines() if ln.strip()]
    assert len(test_rows) == 2, test_rows
    assert test_rows[0].startswith("test_accuracy")
    # both runs produced the dual checkpoints
    for exp in (exp_multi, exp_single):
        saved = os.listdir(os.path.join(exp, "saved_models"))
        assert "train_model_latest" in saved and "train_model_2" in saved


@pytest.mark.slow
def test_two_process_kill_resume(tmp_path):
    """SIGKILL a 2-process training gang after its epoch-1 checkpoint lands,
    relaunch, and require the resumed run to finish and match an
    uninterrupted single-process run's epoch stream — the multi-host
    checkpoint write/swap barriers (experiment/checkpoint.py) must survive a
    REAL unclean restart, not just a graceful exit."""
    import subprocess
    import time as _time

    from test_e2e_presplit import _write_presplit_rgb

    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root), n_classes=4, per_class=6, size=10)
    exp = tmp_path / "exp_killed"
    cache_dir = tmp_path / "cache"

    # phase A targets MORE epochs than phase B so the gang cannot finish and
    # exit cleanly before the kill lands (epochs are seconds here); the
    # resume phase then completes the 2-epoch experiment from the survivor
    # checkpoint
    procs = _spawn_workers(
        exp, data_root, cache_dir, num_processes=2, n_local_devices=4,
        total_epochs=3,
    )
    # drain stdout continuously: a worker blocked on a full pipe inside a
    # collective would deadlock the gang before the checkpoint ever lands
    import io
    import threading

    bufs = [io.StringIO() for _ in procs]

    def _drain(p, buf):
        for line in p.stdout:
            buf.write(line)

    drainers = [
        threading.Thread(target=_drain, args=(p, b), daemon=True)
        for p, b in zip(procs, bufs)
    ]
    for t in drainers:
        t.start()

    # poll until the epoch-1 checkpoint AND its metrics row are durably on
    # disk (checkpoint swap completes before pack_and_save_metrics writes
    # the CSV, so header+row present => the whole epoch-1 persistence ran),
    # then SIGKILL the gang mid-epoch-2
    ckpt_dir = os.path.join(exp, "saved_models", "train_model_1")
    csv_path = os.path.join(exp, "logs", "summary_statistics.csv")

    def _epoch1_persisted():
        if not os.path.isdir(ckpt_dir) or not os.path.exists(csv_path):
            return False
        with open(csv_path) as f:
            return len([ln for ln in f.read().splitlines() if ln.strip()]) >= 2

    deadline = _time.time() + 600
    try:
        while not _epoch1_persisted():
            for p, b in zip(procs, bufs):
                assert p.poll() is None, (
                    f"worker died before epoch-1 persisted "
                    f"(rc={p.returncode}):\n{b.getvalue()[-4000:]}"
                )
            assert _time.time() < deadline, "epoch 1 not persisted within 600s"
            _time.sleep(0.5)
    finally:
        for p in procs:
            p.kill()
    for p in procs:
        p.wait(timeout=60)
    for t in drainers:
        t.join(timeout=10)

    # resume: a fresh gang on the same experiment dir must pick up from the
    # latest checkpoint and complete the remaining epoch(s)
    outs = _launch_training(
        exp, data_root, cache_dir, num_processes=2, n_local_devices=4,
        total_epochs=2,
    )
    assert any("WORKER_DONE process=0" in o for o in outs)
    assert any("WORKER_DONE process=1" in o for o in outs)

    # the resumed stream must equal an uninterrupted single-process run
    exp_ref = tmp_path / "exp_uninterrupted"
    _launch_training(
        exp_ref, data_root, cache_dir, num_processes=1, n_local_devices=8,
        total_epochs=2,
    )
    csv_res = _read_csv_columns(
        os.path.join(exp, "logs", "summary_statistics.csv")
    )
    csv_ref = _read_csv_columns(
        os.path.join(exp_ref, "logs", "summary_statistics.csv")
    )
    # epoch-2 row: trained AFTER the kill, on the fast-forwarded task stream
    assert csv_res["epoch"][-1] == csv_ref["epoch"][-1] == 2
    np.testing.assert_allclose(
        csv_res["train_loss_mean"][-1], csv_ref["train_loss_mean"][-1],
        atol=2e-3, err_msg="post-resume epoch diverged from uninterrupted run",
    )
    saved = os.listdir(os.path.join(exp, "saved_models"))
    assert "train_model_latest" in saved and "train_model_2" in saved
