"""Multi-device sharding tests on the 8-device virtual CPU mesh.

The key invariant: sharding the task axis over the mesh must be numerically
equivalent to single-device execution — the TPU-native replacement for
``nn.DataParallel``'s scatter/gather must be a pure re-layout (SURVEY.md
§2.2). The reference could never test this (no distributed backend)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.core import maml, msl
from howtotrainyourmamlpytorch_tpu.parallel import mesh as mesh_lib


@pytest.fixture(autouse=True)
def _require_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")


def _weights(cfg):
    return jnp.asarray(
        msl.per_step_loss_importance(
            cfg.number_of_training_steps_per_iter,
            cfg.multi_step_loss_num_epochs,
            0,
        )
    )


def test_sharded_step_matches_single_device(tiny_cfg, synthetic_batch):
    """Sharding the task axis must reproduce single-device meta-gradients.
    Compared at the gradient level: post-Adam weights would amplify the
    psum's float-reordering noise on ~zero-gradient params (conv bias under
    BN) into O(lr) differences."""
    cfg = tiny_cfg.replace(batch_size=8)
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg, batch_size=8)
    w = _weights(cfg)
    grads_fn = jax.jit(maml.make_grads_fn(cfg, second_order=True))

    # single device
    loss_single, g_single = grads_fn(state, x_s, y_s, x_t, y_t, w)

    # 8-device task mesh
    mesh = mesh_lib.task_mesh(8)
    state_r = mesh_lib.replicate_state(mesh, maml.init_state(cfg))
    xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    loss_shard, g_shard = grads_fn(state_r, xs, ys, xt, yt, w)

    assert float(loss_single) == pytest.approx(float(loss_shard), rel=1e-5)
    for part in ("net", "lslr"):
        for k in g_single[part]:
            np.testing.assert_allclose(
                np.asarray(g_single[part][k]), np.asarray(g_shard[part][k]),
                atol=1e-5, rtol=1e-4, err_msg=f"{part}.{k}",
            )

    # the full train step must also run sharded and agree on metrics
    step = jax.jit(maml.make_train_step(cfg, second_order=True))
    _, m_single = step(state, x_s, y_s, x_t, y_t, w, 0.01)
    _, m_shard = step(state_r, xs, ys, xt, yt, w, 0.01)
    assert float(m_single["loss"]) == pytest.approx(
        float(m_shard["loss"]), rel=1e-5
    )
    assert float(m_single["accuracy"]) == pytest.approx(
        float(m_shard["accuracy"]), abs=1e-6
    )


def test_large_meta_batch_256_tasks(tiny_cfg, synthetic_batch):
    """The large-meta-batch capability (BASELINE.json: '>=256 tasks across
    the mesh'): one second-order MAML++ step with 256 tasks sharded over the
    8-device mesh compiles and executes (tiny shapes keep CPU runtime sane)."""
    cfg = tiny_cfg.replace(
        batch_size=256,
        image_height=8,
        image_width=8,
        cnn_num_filters=4,
        num_stages=2,
        use_remat=True,
    )
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    w = _weights(cfg)
    mesh = mesh_lib.task_mesh(8)
    state = mesh_lib.replicate_state(mesh, state)
    xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    step = jax.jit(maml.make_train_step(cfg, second_order=True))
    new_state, metrics = step(state, xs, ys, xt, yt, w, 0.001)
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_mesh_requires_divisible_batch():
    mesh = mesh_lib.task_mesh(8)
    with pytest.raises(ValueError, match="not divisible"):
        mesh_lib.shard_batch(mesh, np.zeros((6, 2)))


def test_eval_step_sharded(tiny_cfg, synthetic_batch):
    cfg = tiny_cfg.replace(batch_size=8)
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg, batch_size=8)
    ev = jax.jit(maml.make_eval_step(cfg))
    m_single, p_single = ev(state, x_s, y_s, x_t, y_t)

    mesh = mesh_lib.task_mesh(8)
    state_r = mesh_lib.replicate_state(mesh, state)
    xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
    m_shard, p_shard = ev(state_r, xs, ys, xt, yt)
    np.testing.assert_allclose(
        np.asarray(p_single), np.asarray(p_shard), atol=1e-5
    )
    assert float(m_single["accuracy"]) == pytest.approx(
        float(m_shard["accuracy"]), abs=1e-6
    )


def test_submesh_sizes(tiny_cfg, synthetic_batch):
    """Mesh over a subset of devices (num_devices knob)."""
    cfg = tiny_cfg.replace(batch_size=4)
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg, batch_size=4)
    step = jax.jit(maml.make_train_step(cfg, second_order=False))
    ref_state, ref_m = step(state, x_s, y_s, x_t, y_t, _weights(cfg), 0.01)
    for n in (2, 4):
        mesh = mesh_lib.task_mesh(n)
        sr = mesh_lib.replicate_state(mesh, maml.init_state(cfg))
        xs, ys, xt, yt = mesh_lib.shard_batch(mesh, x_s, y_s, x_t, y_t)
        _, m = step(sr, xs, ys, xt, yt, _weights(cfg), 0.01)
        assert float(m["loss"]) == pytest.approx(float(ref_m["loss"]), rel=1e-5)


# -- true multi-process execution (VERDICT r2 #3) -------------------------

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _spawn_workers(exp_name, data_root, cache_dir,
                   num_processes, n_local_devices, total_epochs=2):
    """Spawn the coordinated worker gang without waiting (kill tests poll)."""
    import subprocess
    import sys as _sys

    port = _free_port()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(os.path.dirname(__file__), "_mp_train_worker.py")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # workers own their XLA_FLAGS/JAX_PLATFORMS; drop the conftest's
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return [
        subprocess.Popen(
            [
                _sys.executable, worker,
                "--process_id", str(pid),
                "--num_processes", str(num_processes),
                "--port", str(port),
                "--n_local_devices", str(n_local_devices),
                "--data_root", str(data_root),
                "--exp_name", str(exp_name),
                "--cache_dir", str(cache_dir),
                "--total_epochs", str(total_epochs),
            ],
            env=env,
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(num_processes)
    ]


def _launch_training(exp_name, data_root, cache_dir,
                     num_processes, n_local_devices, timeout=900,
                     total_epochs=2):
    """Launch `num_processes` coordinated _mp_train_worker.py subprocesses
    and return their outputs (raises on any non-zero exit)."""
    import subprocess

    procs = _spawn_workers(
        exp_name, data_root, cache_dir, num_processes, n_local_devices,
        total_epochs,
    )
    # drain all pipes concurrently: a worker blocked on a full stdout pipe
    # inside a collective would deadlock the whole gang
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(len(procs)) as pool:
        futs = [pool.submit(p.communicate, timeout=timeout) for p in procs]
        try:
            outs = [f.result()[0] for f in futs]
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {pid} failed rc={p.returncode}:\n{out[-4000:]}"
        )
    return outs


def _read_csv_columns(path):
    import csv

    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert rows, f"no rows in {path}"
    out = {}
    for k in rows[0]:
        try:
            out[k] = np.array([float(r[k]) for r in rows])
        except (TypeError, ValueError):
            pass  # non-numeric column
    return out


@pytest.mark.slow
def test_two_process_training_matches_single(tmp_path):
    """Two REAL processes (jax.distributed.initialize, CPU backend, 4 virtual
    devices each) train through cli.main and must produce the same per-epoch
    losses as one 8-device process on the same global task stream.

    This executes — across genuine process boundaries — the hybrid DCN x ICI
    mesh (`create_hybrid_device_mesh`), per-host batch slices assembled with
    `make_array_from_process_local_data`, the dataset-bootstrap broadcast,
    collective orbax checkpointing with a primary-only swap, primary-only
    metric writes, and the cross-host prediction allgather of the test
    ensemble. The reference has no distributed backend at all
    (few_shot_learning_system.py:73-81 is single-process nn.DataParallel).
    """
    from test_e2e_presplit import _write_presplit_rgb

    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root), n_classes=4, per_class=6, size=10)

    exp_multi = tmp_path / "exp_multi"
    exp_single = tmp_path / "exp_single"
    cache_dir = tmp_path / "cache"

    outs = _launch_training(
        exp_multi, data_root, cache_dir, num_processes=2, n_local_devices=4,
    )
    assert any("WORKER_DONE process=0" in o for o in outs)
    assert any("WORKER_DONE process=1" in o for o in outs)

    _launch_training(
        exp_single, data_root, cache_dir, num_processes=1, n_local_devices=8,
    )

    csv_multi = _read_csv_columns(
        os.path.join(exp_multi, "logs", "summary_statistics.csv")
    )
    csv_single = _read_csv_columns(
        os.path.join(exp_single, "logs", "summary_statistics.csv")
    )
    assert len(csv_multi["train_loss_mean"]) == 2  # both trained 2 epochs
    for key in ("train_loss_mean", "val_loss_mean"):
        np.testing.assert_allclose(
            csv_multi[key], csv_single[key], atol=2e-3,
            err_msg=f"{key} diverged between 2-process and single-process",
        )
    for key in ("train_accuracy_mean", "val_accuracy_mean"):
        # identical stream; allow one task flip from fp reduction order
        np.testing.assert_allclose(
            csv_multi[key], csv_single[key], atol=0.13, err_msg=key,
        )
    # only the primary process wrote metric files in the 2-process run:
    # exactly one header + one data row, not two processes' interleaved writes
    with open(os.path.join(exp_multi, "logs", "test_summary.csv")) as f:
        test_rows = [ln for ln in f.read().splitlines() if ln.strip()]
    assert len(test_rows) == 2, test_rows
    assert test_rows[0].startswith("test_accuracy")
    # both runs produced the dual checkpoints
    for exp in (exp_multi, exp_single):
        saved = os.listdir(os.path.join(exp, "saved_models"))
        assert "train_model_latest" in saved and "train_model_2" in saved


@pytest.mark.slow
def test_two_process_kill_resume(tmp_path):
    """SIGKILL a 2-process training gang after its epoch-1 checkpoint lands,
    relaunch, and require the resumed run to finish and match an
    uninterrupted single-process run's epoch stream — the multi-host
    checkpoint write/swap barriers (experiment/checkpoint.py) must survive a
    REAL unclean restart, not just a graceful exit."""
    import subprocess
    import time as _time

    from test_e2e_presplit import _write_presplit_rgb

    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root), n_classes=4, per_class=6, size=10)
    exp = tmp_path / "exp_killed"
    cache_dir = tmp_path / "cache"

    # phase A targets MORE epochs than phase B so the gang cannot finish and
    # exit cleanly before the kill lands (epochs are seconds here); the
    # resume phase then completes the 2-epoch experiment from the survivor
    # checkpoint
    procs = _spawn_workers(
        exp, data_root, cache_dir, num_processes=2, n_local_devices=4,
        total_epochs=3,
    )
    # drain stdout continuously: a worker blocked on a full pipe inside a
    # collective would deadlock the gang before the checkpoint ever lands
    import io
    import threading

    bufs = [io.StringIO() for _ in procs]

    def _drain(p, buf):
        for line in p.stdout:
            buf.write(line)

    drainers = [
        threading.Thread(target=_drain, args=(p, b), daemon=True)
        for p, b in zip(procs, bufs)
    ]
    for t in drainers:
        t.start()

    # poll until the epoch-1 checkpoint AND its metrics row are durably on
    # disk (checkpoint swap completes before pack_and_save_metrics writes
    # the CSV, so header+row present => the whole epoch-1 persistence ran),
    # then SIGKILL the gang mid-epoch-2
    ckpt_dir = os.path.join(exp, "saved_models", "train_model_1")
    csv_path = os.path.join(exp, "logs", "summary_statistics.csv")

    def _epoch1_persisted():
        if not os.path.isdir(ckpt_dir) or not os.path.exists(csv_path):
            return False
        with open(csv_path) as f:
            return len([ln for ln in f.read().splitlines() if ln.strip()]) >= 2

    deadline = _time.time() + 600
    try:
        while not _epoch1_persisted():
            for p, b in zip(procs, bufs):
                assert p.poll() is None, (
                    f"worker died before epoch-1 persisted "
                    f"(rc={p.returncode}):\n{b.getvalue()[-4000:]}"
                )
            assert _time.time() < deadline, "epoch 1 not persisted within 600s"
            _time.sleep(0.5)
    finally:
        for p in procs:
            p.kill()
    for p in procs:
        p.wait(timeout=60)
    for t in drainers:
        t.join(timeout=10)

    # resume: a fresh gang on the same experiment dir must pick up from the
    # latest checkpoint and complete the remaining epoch(s)
    outs = _launch_training(
        exp, data_root, cache_dir, num_processes=2, n_local_devices=4,
        total_epochs=2,
    )
    assert any("WORKER_DONE process=0" in o for o in outs)
    assert any("WORKER_DONE process=1" in o for o in outs)

    # the resumed stream must equal an uninterrupted single-process run
    exp_ref = tmp_path / "exp_uninterrupted"
    _launch_training(
        exp_ref, data_root, cache_dir, num_processes=1, n_local_devices=8,
        total_epochs=2,
    )
    csv_res = _read_csv_columns(
        os.path.join(exp, "logs", "summary_statistics.csv")
    )
    csv_ref = _read_csv_columns(
        os.path.join(exp_ref, "logs", "summary_statistics.csv")
    )
    # epoch-2 row: trained AFTER the kill, on the fast-forwarded task stream
    assert csv_res["epoch"][-1] == csv_ref["epoch"][-1] == 2
    np.testing.assert_allclose(
        csv_res["train_loss_mean"][-1], csv_ref["train_loss_mean"][-1],
        atol=2e-3, err_msg="post-resume epoch diverged from uninterrupted run",
    )
    saved = os.listdir(os.path.join(exp, "saved_models"))
    assert "train_model_latest" in saved and "train_model_2" in saved
