"""Functional-op parity vs torch.nn.functional (the reference's compute
primitives — F.conv2d meta_...py:89, F.linear :141, F.batch_norm :246,
F.layer_norm :314, pools :605/:609). torch (CPU) is the oracle."""

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.ops import functional as F

torch = pytest.importorskip("torch")
import torch.nn.functional as TF  # noqa: E402


def _nchw(x):
    return torch.tensor(np.moveaxis(x, -1, 1).copy())


def test_conv2d_matches_torch():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 9, 9, 3).astype(np.float32)
    w = rng.randn(3, 3, 3, 5).astype(np.float32)  # HWIO
    b = rng.randn(5).astype(np.float32)
    for stride, pad in [(1, 1), (2, 1), (1, 0), (2, 0)]:
        ours = np.asarray(F.conv2d(x, w, b, stride=stride, padding=pad))
        w_t = torch.tensor(np.transpose(w, (3, 2, 0, 1)).copy())  # OIHW
        theirs = TF.conv2d(_nchw(x), w_t, torch.tensor(b), stride=stride,
                           padding=pad).numpy()
        np.testing.assert_allclose(ours, np.moveaxis(theirs, 1, -1), atol=1e-4)


def test_linear_matches_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 7).astype(np.float32)
    w = rng.randn(7, 3).astype(np.float32)  # (in, out)
    b = rng.randn(3).astype(np.float32)
    ours = np.asarray(F.linear(x, w, b))
    theirs = TF.linear(torch.tensor(x), torch.tensor(w.T.copy()),
                       torch.tensor(b)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-5)


def test_batch_norm_matches_torch_training_mode():
    """Normalization must equal F.batch_norm(training=True) — the
    reference ALWAYS normalizes with batch stats (meta_...py:246-247)."""
    rng = np.random.RandomState(2)
    x = rng.randn(6, 5, 5, 4).astype(np.float32)
    gamma = rng.rand(4).astype(np.float32) + 0.5
    beta = rng.randn(4).astype(np.float32)
    rm = np.zeros(4, np.float32)
    rv = np.ones(4, np.float32)
    ours, new_m, new_v = F.batch_norm(x, gamma, beta, rm.copy(), rv.copy())
    rm_t, rv_t = torch.tensor(rm), torch.tensor(rv)
    theirs = TF.batch_norm(
        _nchw(x), rm_t, rv_t, torch.tensor(gamma), torch.tensor(beta),
        training=True, momentum=0.1, eps=1e-5,
    ).numpy()
    np.testing.assert_allclose(ours, np.moveaxis(theirs, 1, -1), atol=1e-4)
    # running-stat update must match torch's in-place tracking
    np.testing.assert_allclose(np.asarray(new_m), rm_t.numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_v), rv_t.numpy(), atol=1e-4)


def test_layer_norm_matches_torch():
    rng = np.random.RandomState(3)
    x = rng.randn(3, 5, 5, 4).astype(np.float32)
    gamma = rng.rand(5, 5, 4).astype(np.float32) + 0.5
    beta = rng.randn(5, 5, 4).astype(np.float32)
    ours = np.asarray(F.layer_norm(x, gamma, beta))
    # torch normalizes over (c, h, w); ours over (h, w, c) — same statistics
    # (full per-sample reduction), affine transposed
    theirs = TF.layer_norm(
        _nchw(x), [4, 5, 5],
        torch.tensor(np.transpose(gamma, (2, 0, 1)).copy()),
        torch.tensor(np.transpose(beta, (2, 0, 1)).copy()), eps=1e-5,
    ).numpy()
    np.testing.assert_allclose(ours, np.moveaxis(theirs, 1, -1), atol=1e-4)


def test_max_pool_matches_torch():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 8, 8, 3).astype(np.float32)
    ours = np.asarray(F.max_pool2d(x))
    theirs = TF.max_pool2d(_nchw(x), kernel_size=2, stride=2).numpy()
    np.testing.assert_allclose(ours, np.moveaxis(theirs, 1, -1), atol=1e-6)


def test_global_avg_pool_matches_torch():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 7, 7, 3).astype(np.float32)
    ours = np.asarray(F.global_avg_pool2d(x))
    theirs = TF.avg_pool2d(_nchw(x), 7).numpy()
    np.testing.assert_allclose(ours, np.moveaxis(theirs, 1, -1), atol=1e-6)


def test_cross_entropy_matches_torch():
    rng = np.random.RandomState(6)
    logits = rng.randn(10, 5).astype(np.float32)
    labels = rng.randint(0, 5, 10)
    ours = float(F.cross_entropy(logits, labels))
    theirs = float(TF.cross_entropy(torch.tensor(logits), torch.tensor(labels)))
    assert abs(ours - theirs) < 1e-5


def test_norm_conv_relu_block_order(tiny_cfg):
    """The alternate norm-first block (MetaNormLayerConvReLU,
    meta_...py:438-542): norm params sized to block INPUT channels, forward
    runs, and a train step optimizes it."""
    import jax
    import jax.numpy as jnp
    from howtotrainyourmamlpytorch_tpu.core import maml, msl
    from howtotrainyourmamlpytorch_tpu.models import vgg

    cfg = tiny_cfg.replace(block_order="norm_conv_relu")
    params, bn_state = vgg.init(cfg, jax.random.PRNGKey(0))
    # stage 0 normalizes the input image channels, not the conv output
    assert params["conv0.norm.gamma"].shape[-1] == cfg.image_channels
    assert params["conv1.norm.gamma"].shape[-1] == cfg.cnn_num_filters
    x = np.random.RandomState(0).randn(6, *cfg.im_shape).astype(np.float32)
    logits, new_bn = vgg.apply(cfg, params, bn_state, x, 0, training=True)
    assert logits.shape == (6, cfg.num_classes_per_set)
    assert np.all(np.isfinite(np.asarray(logits)))

    state = maml.init_state(cfg)
    w = jnp.asarray(
        msl.final_step_only(cfg.number_of_training_steps_per_iter)
    )
    rng = np.random.RandomState(0)
    b, n = cfg.batch_size, cfg.num_classes_per_set
    s, t = cfg.num_samples_per_class, cfg.num_target_samples
    h, ww, c = cfg.im_shape
    x_s = rng.randn(b, n, s, h, ww, c).astype(np.float32)
    x_t = rng.randn(b, n, t, h, ww, c).astype(np.float32)
    y_s = np.tile(np.arange(n, dtype=np.int32)[None, :, None], (b, 1, s))
    y_t = np.tile(np.arange(n, dtype=np.int32)[None, :, None], (b, 1, t))
    step = jax.jit(maml.make_train_step(cfg, second_order=True))
    new_state, metrics = step(state, x_s, y_s, x_t, y_t, w, 0.001)
    assert np.isfinite(float(metrics["loss"]))


def test_leaky_relu_default_slope():
    x = np.array([-2.0, -0.5, 0.0, 3.0], np.float32)
    ours = np.asarray(F.leaky_relu(x))
    theirs = TF.leaky_relu(torch.tensor(x)).numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-7)
