"""Memmap image-cache tests: bit-exactness vs the PIL path, reuse, rebuild."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.data import preprocess
from howtotrainyourmamlpytorch_tpu.data.loader import MetaLearningDataLoader


def _write_dataset(root, n_classes, per_class, size, mode, seed=0):
    """A tiny on-disk image dataset: <root>/<class>/<img>.png."""
    rng = np.random.RandomState(seed)
    for ci in range(n_classes):
        d = os.path.join(root, f"class_{ci:02d}")
        os.makedirs(d, exist_ok=True)
        for j in range(per_class):
            if mode == "1":  # omniglot-style 1-bit
                arr = (rng.rand(size, size) > 0.5)
                img = Image.fromarray(arr).convert("1")
            else:  # RGB
                arr = rng.randint(0, 256, (size, size, 3), np.uint8)
                img = Image.fromarray(arr, "RGB")
            img.save(os.path.join(d, f"im_{j}.png"))


def _cfg(root, cache, **kw):
    base = dict(
        dataset_path=str(root),
        cache_dir=str(cache),
        indexes_of_folders_indicating_class=[-2],
        train_val_test_split=[0.6, 0.2, 0.2],
        num_classes_per_set=2,
        num_samples_per_class=2,
        num_target_samples=1,
        batch_size=2,
        num_dataprovider_workers=2,
        load_into_memory=False,
    )
    base.update(kw)
    return MAMLConfig(**base)


def _first_batches(cfg, n=2):
    loader = MetaLearningDataLoader(cfg, current_iter=0, cache_dir=cfg.cache_dir)
    out = []
    gen = loader.get_train_batches(total_batches=n)
    for batch in gen:
        out.append(batch)
    out.append(next(iter(loader.get_val_batches(total_batches=1))))
    return out


@pytest.mark.parametrize(
    "dataset_name,mode,h,c",
    [
        ("omniglot_dataset", "1", 12, 1),
        ("mini_imagenet_full_size", "RGB", 16, 3),
    ],
)
def test_mmap_cache_bit_exact_vs_pil_path(tmp_path, dataset_name, mode, h, c):
    root = tmp_path / "data"
    _write_dataset(str(root), n_classes=10, per_class=5, size=h, mode=mode)
    common = dict(
        dataset_name=dataset_name, image_height=h, image_width=h,
        image_channels=c,
    )
    cfg_pil = _cfg(root, tmp_path / "c1", **common)
    cfg_mm = _cfg(root, tmp_path / "c2", use_mmap_cache=True, **common)
    for a, b in zip(_first_batches(cfg_pil), _first_batches(cfg_mm)):
        for x, y in zip(a[:4], b[:4]):
            np.testing.assert_array_equal(x, y)


def test_cache_files_reused_and_rebuilt_on_mismatch(tmp_path):
    root = tmp_path / "data"
    _write_dataset(str(root), n_classes=10, per_class=4, size=8, mode="1")
    cfg = _cfg(
        root, tmp_path / "cache", dataset_name="omniglot_dataset",
        image_height=8, image_width=8, image_channels=1, use_mmap_cache=True,
    )
    b1 = _first_batches(cfg, n=1)
    base = preprocess._cache_base(cfg, cfg.cache_dir, "train")
    mtime = os.path.getmtime(base + ".u8")
    # second build: reused, not rewritten
    b2 = _first_batches(cfg, n=1)
    assert os.path.getmtime(base + ".u8") == mtime
    np.testing.assert_array_equal(b1[0][0], b2[0][0])
    # corrupt the meta (simulate a split change): must rebuild
    with open(base + ".json") as f:
        meta = json.load(f)
    good_counts = list(meta["counts"])
    meta["counts"][0] += 1
    with open(base + ".json", "w") as f:
        json.dump(meta, f)
    _first_batches(cfg, n=1)
    with open(base + ".json") as f:
        rebuilt = json.load(f)
    assert rebuilt["counts"] == good_counts and rebuilt["done"]


def test_truncated_meta_rebuilds_instead_of_crashing(tmp_path):
    """A meta file truncated mid-write (killed build) must read as 'no
    cache' and trigger a rebuild — not crash the run with JSONDecodeError."""
    root = tmp_path / "data"
    _write_dataset(str(root), n_classes=10, per_class=4, size=8, mode="1")
    cfg = _cfg(
        root, tmp_path / "cache", dataset_name="omniglot_dataset",
        image_height=8, image_width=8, image_channels=1, use_mmap_cache=True,
    )
    b1 = _first_batches(cfg, n=1)
    base = preprocess._cache_base(cfg, cfg.cache_dir, "train")
    with open(base + ".json") as f:
        good = f.read()
    with open(base + ".json", "w") as f:
        f.write(good[: len(good) // 2])  # truncated: invalid JSON
    b2 = _first_batches(cfg, n=1)  # must not raise
    np.testing.assert_array_equal(b1[0][0], b2[0][0])
    with open(base + ".json") as f:
        assert json.load(f)["done"]


def test_build_leaves_no_temp_files(tmp_path):
    """Builds go through pid-suffixed temps + os.replace; after a build the
    cache dir contains only the final .u8/.json pairs."""
    root = tmp_path / "data"
    _write_dataset(str(root), n_classes=10, per_class=4, size=8, mode="1")
    cfg = _cfg(
        root, tmp_path / "cache", dataset_name="omniglot_dataset",
        image_height=8, image_width=8, image_channels=1, use_mmap_cache=True,
    )
    _first_batches(cfg, n=1)
    leftovers = [
        f for f in os.listdir(cfg.cache_dir) if ".tmp." in f
    ]
    assert leftovers == []


def test_half_written_cache_not_served(tmp_path):
    """A build killed before the done flag is rebuilt from scratch."""
    root = tmp_path / "data"
    _write_dataset(str(root), n_classes=10, per_class=4, size=8, mode="1")
    cfg = _cfg(
        root, tmp_path / "cache", dataset_name="omniglot_dataset",
        image_height=8, image_width=8, image_channels=1, use_mmap_cache=True,
    )
    b1 = _first_batches(cfg, n=1)
    base = preprocess._cache_base(cfg, cfg.cache_dir, "train")
    with open(base + ".json") as f:
        meta = json.load(f)
    meta["done"] = False
    with open(base + ".json", "w") as f:
        json.dump(meta, f)
    # zero the data file to prove it is rebuilt, not trusted
    size = os.path.getsize(base + ".u8")
    with open(base + ".u8", "wb") as f:
        f.write(b"\x00" * size)
    b2 = _first_batches(cfg, n=1)
    np.testing.assert_array_equal(b1[0][0], b2[0][0])


def test_stale_temp_sweep_pid_and_age(tmp_path):
    """The pre-build sweep removes dead-pid and over-age temps, keeps a live
    builder's fresh temp (incl. the EPERM 'exists but not ours' case, which
    os.kill reports for pid 1 when unprivileged)."""
    root = tmp_path / "data"
    _write_dataset(str(root), n_classes=10, per_class=4, size=8, mode="1")
    cfg = _cfg(
        root, tmp_path / "cache", dataset_name="omniglot_dataset",
        image_height=8, image_width=8, image_channels=1, use_mmap_cache=True,
    )
    os.makedirs(cfg.cache_dir, exist_ok=True)
    base = preprocess._cache_base(cfg, cfg.cache_dir, "train")
    dead_pid = 2 ** 22 + 7  # above any real pid on this host
    # the live same-uid process must NOT be os.getpid() (that is the
    # in-process builder's own temp name, which its finally-cleanup removes)
    # nor os.getppid() (pid 1 when the runner is a container's init child,
    # colliding with live_old below) — spawn a throwaway child instead
    child = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(120)"]
    )
    try:
        live_fresh = f"{base}.u8.tmp.{child.pid}"
        live_old = f"{base}.u8.tmp.1"  # pid 1: os.kill -> EPERM when unprivileged
        dead = f"{base}.u8.tmp.{dead_pid}"
        for p in (live_fresh, live_old, dead):
            with open(p, "w") as f:
                f.write("x")
        old = preprocess._STALE_TEMP_AGE_S + 60
        os.utime(live_old, (os.path.getmtime(live_old) - old,) * 2)
        _first_batches(cfg, n=1)  # triggers the sweep, then builds
        assert os.path.exists(live_fresh), "fresh live-pid temp must survive"
        assert not os.path.exists(live_old), "over-age temp swept despite live pid"
        assert not os.path.exists(dead), "dead-pid temp swept"
        os.remove(live_fresh)
    finally:
        child.kill()
        child.wait()
