"""Fleet-wide distributed tracing tests (the cross-process trace path).

Covers the v14 observability surfaces end to end without jax: the
Cristian clock-offset estimator the health sweep runs, the trace
baggage the gateway stamps into the wire frame (and the byte-identity
guarantee when tracing is off), the host-side adoption by the
MicroBatcher, the clock-aligned multi-process Perfetto export,
the gateway's Prometheus ``/metrics`` endpoint, and the ``cli trace
--fleet`` merge. Everything runs over real loopback HTTP against
stub-backed ``FleetHost`` instances, mirroring tests/test_gateway.py;
the jax-heavy end-to-end shape is CI's ``fleet-smoke`` job.
"""

import json
import os
import subprocess
import sys
from types import SimpleNamespace

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.serving import gateway as gw
from howtotrainyourmamlpytorch_tpu.serving.batcher import (
    AdaptRequest,
    MicroBatcher,
)
from howtotrainyourmamlpytorch_tpu.serving.fleet import FleetHost
from howtotrainyourmamlpytorch_tpu.serving.metrics import (
    LogHistogram,
    parse_prometheus_text,
)
from howtotrainyourmamlpytorch_tpu import telemetry as tel
from howtotrainyourmamlpytorch_tpu.telemetry.tracing import (
    Tracer,
    fleet_critical_path,
    to_chrome_trace,
)
from howtotrainyourmamlpytorch_tpu.tools import trace_cli


# -- stubs (the test_gateway.py shapes) --------------------------------------


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(record)

    def close(self):
        pass


class _FakeResult:
    def __init__(self, tenant_id="t0", way=3, targets=2):
        self.tenant_id = tenant_id
        self.preds = np.arange(
            way * targets * 5, dtype=np.float32
        ).reshape(way * targets, 5)
        self.loss = 0.25
        self.accuracy = 0.875


class _StubPending:
    def __init__(self, result):
        self._result = result

    def get(self, timeout=None):
        return self._result


class _StubRouter:
    def __init__(self):
        self.submitted = []

    def submit(self, request):
        self.submitted.append(request)
        return _StubPending(_FakeResult(request.tenant_id or "t0"))

    def stats(self):
        return {"submitted": len(self.submitted)}


class _StubReplica:
    def __init__(self, depth=0):
        self._depth = depth

    def queue_depth(self):
        return self._depth


class _StubPool:
    def __init__(self, depth=0):
        self.replicas = [_StubReplica(depth)]

    def readiness(self):
        return {0: True}

    def rollup(self):
        return {
            "dispatches": 0, "tenants": 0,
            "adapt_ms_hist": LogHistogram().to_dict(),
            "queue_ms_hist": LogHistogram().to_dict(),
        }


def _gw_cfg(**kw):
    kw.setdefault("serving_gateway_health_interval_s", 0.05)
    return MAMLConfig(**kw)


def _adapt_request(seed=123, **kw):
    rng = np.random.RandomState(seed)
    return AdaptRequest(
        support_x=rng.randn(3, 1, 10, 10, 1).astype(np.float32),
        support_y=np.tile(np.arange(3, dtype=np.int32)[:, None], (1, 1)),
        query_x=rng.randn(3, 2, 10, 10, 1).astype(np.float32),
        query_y=None,
        **kw,
    )


def _make_tracer(process=None, span_prefix=""):
    records = []

    def emit(**fields):
        records.append(fields)

    return Tracer(
        emit=emit, process=process, span_prefix=span_prefix
    ), records


def _make_fleet(n=2, sink=None, tracer=None, **cfg_kw):
    hosts, routers, members = {}, {}, {}
    for i in range(n):
        router = _StubRouter()
        host = FleetHost(router, _StubPool(), host_id=f"host{i:02d}")
        hosts[host.host_id] = host
        routers[host.host_id] = router
        members[host.host_id] = f"127.0.0.1:{host.port}"
    gateway = gw.Gateway(
        _gw_cfg(**cfg_kw), members, sink=sink, start_health_loop=False,
        tracer=tracer,
    )
    gateway.poll_once()
    return gateway, hosts, routers


def _close_fleet(gateway, hosts):
    gateway.close()
    for h in hosts.values():
        h.close()


# -- Cristian clock-offset estimator -----------------------------------------


def test_clock_offset_error_bounded_by_half_rtt():
    """Cristian's bound, with the asymmetry adversary: the remote stamp
    lands anywhere inside the RTT window, and however lopsided the
    request/response legs are, |estimate - truth| <= RTT/2 — the bound
    the estimator reports as clock_skew_bound_ms."""
    true_offset = 12_345.678  # remote clock runs this far ahead
    for d1, d2 in ((0.4, 0.4), (0.79, 0.01), (0.05, 0.95), (2.0, 0.0)):
        est = gw.ClockOffsetEstimator()
        t0 = 1000.0
        t1 = t0 + d1 + d2
        # the remote stamps its clock AFTER the request leg (d1 in)
        remote = (t0 + d1) + true_offset
        assert est.observe(t0, t1, remote) is True
        assert est.bound_ms == pytest.approx((d1 + d2) / 2)
        assert abs(est.offset_ms - true_offset) <= est.bound_ms + 1e-9


def test_clock_offset_bound_monotone_across_sweeps():
    """Only a strictly-smaller RTT replaces the latched estimate, so the
    recorded bound never loosens across health sweeps; non-causal
    samples (t1 < t0) are rejected without counting."""
    est = gw.ClockOffsetEstimator()
    bounds = []
    adopted = []
    for rtt in (3.0, 1.0, 2.5, 0.4, 0.4, 8.0):
        took = est.observe(100.0, 100.0 + rtt, 5100.0 + rtt / 2)
        adopted.append(took)
        bounds.append(est.bound_ms)
    assert adopted == [True, True, False, True, False, False]
    assert all(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:]))
    assert est.samples == 6
    before = (est.offset_ms, est.bound_ms, est.samples)
    assert est.observe(100.0, 99.0, 5100.0) is False  # clock went back?
    assert (est.offset_ms, est.bound_ms, est.samples) == before


def test_health_sweep_emits_tightening_clock_records():
    """poll_once runs the estimator against the real /healthz perf_ms
    stamp and records event='clock' only when the min-RTT sample
    improves — the LAST record per host is the authoritative offset
    `cli trace --fleet` reads."""
    sink = _ListSink()
    gateway, hosts, _ = _make_fleet(n=2, sink=sink)
    try:
        for _ in range(4):
            gateway.poll_once()
        clocks = [
            r for r in sink.records
            if r.get("kind") == "gateway" and r.get("event") == "clock"
        ]
        assert clocks, "health sweep emitted no clock records"
        hosts_seen = {r["host"] for r in clocks}
        assert hosts_seen == set(hosts)
        for r in clocks:
            tel.validate_record(r)
            # both fields are independently rounded to 3 decimals
            assert r["clock_skew_bound_ms"] == pytest.approx(
                r["rtt_ms"] / 2, abs=1.1e-3
            )
        # per host, the recorded bound tightens monotonically
        for hid in hosts_seen:
            bs = [r["clock_skew_bound_ms"] for r in clocks
                  if r["host"] == hid]
            assert all(b2 < b1 for b1, b2 in zip(bs, bs[1:]))
        st = {h["host_id"]: h for h in gateway.stats()["hosts"]}
        for hid in hosts:
            assert st[hid]["clock_skew_bound_ms"] > 0
    finally:
        _close_fleet(gateway, hosts)


# -- wire baggage: byte-identity off, propagation on -------------------------


def _capture_forward(gateway):
    captured = []
    orig = gateway._forward

    def spy(host, body):
        captured.append(body)
        return orig(host, body)

    gateway._forward = spy
    return captured


def test_wire_frame_byte_identical_when_tracing_off():
    """Tracing off is the schema-v13 wire, bytes and all: the forwarded
    header carries exactly the client keys plus the two v13 gateway
    stamps — no trace keys — and re-encoding the decoded header
    reproduces the frame bit-for-bit (the encoder serializes only the
    keys present, so absent baggage can't perturb the bytes)."""
    gateway, hosts, _ = _make_fleet(n=1)
    captured = _capture_forward(gateway)
    try:
        req = _adapt_request(tenant_id="tenant-1", deadline_ms=500.0)
        client_keys = set(gw.decode_request(gw.encode_request(req))[1])
        status, _, _ = gateway.handle_serve(gw.encode_request(req))
        assert status == 200
        header, blob = gw._decode_frame(captured[0])
        assert set(header) == client_keys | {
            "priority", "gateway_elapsed_ms"
        }
        for key in ("trace_id", "parent_span_id", "request_id",
                    "clock_offset_ms"):
            assert key not in header
        assert gw._encode_frame(header, [blob]) == captured[0]
    finally:
        _close_fleet(gateway, hosts)


def test_trace_baggage_rides_the_wire_and_host_adopts_it():
    """Tracing on: the forward frame gains exactly the four baggage
    keys, and the host handler stamps them onto the decoded request as
    trace_ctx — parenting the host tree under THIS forward span of THIS
    gateway trace."""
    tracer, records = _make_tracer(process="gateway", span_prefix="gw-")
    gateway, hosts, routers = _make_fleet(n=1, tracer=tracer)
    captured = _capture_forward(gateway)
    try:
        req = _adapt_request(tenant_id="tenant-2", deadline_ms=500.0)
        client_keys = set(gw.decode_request(gw.encode_request(req))[1])
        status, _, _ = gateway.handle_serve(gw.encode_request(req))
        assert status == 200
        header, _ = gw._decode_frame(captured[0])
        assert set(header) == client_keys | {
            "priority", "gateway_elapsed_ms", "trace_id",
            "parent_span_id", "request_id", "clock_offset_ms",
        }
        fwd = [r for r in records if r["name"] == "forward"]
        root = [r for r in records if r["name"] == "request"]
        assert len(fwd) == 1 and len(root) == 1
        assert header["trace_id"] == fwd[0]["trace_id"]
        assert header["parent_span_id"] == fwd[0]["span_id"]
        assert fwd[0]["parent_id"] == root[0]["span_id"]
        (request,) = routers["host00"].submitted
        assert request.trace_ctx == {
            "trace_id": header["trace_id"],
            "parent_span_id": header["parent_span_id"],
            "request_id": header["request_id"],
            "clock_offset_ms": header["clock_offset_ms"],
        }
        # every admitted request mints its OWN trace, never the
        # tracer's run-scoped one
        status, _, _ = gateway.handle_serve(
            gw.encode_request(_adapt_request(seed=77, deadline_ms=500.0))
        )
        assert status == 200
        roots = [r for r in records if r["name"] == "request"]
        assert len({r["trace_id"] for r in roots}) == 2
        assert tracer.trace_id not in {r["trace_id"] for r in roots}
    finally:
        _close_fleet(gateway, hosts)


def test_micro_batcher_adopts_gateway_trace():
    """The host-side half of propagation: a request carrying trace_ctx
    gets its serving root span REPARENTED under the gateway's forward
    span — same trace id, request_id carried over, the wire-delivered
    clock_offset_ms stamped as a root attr. A request without trace_ctx
    keeps a host-local trace (the in-process serving shape)."""
    tracer, records = _make_tracer(
        process="host00", span_prefix="host00-"
    )
    engine = SimpleNamespace(
        max_tenants=4,
        cfg=SimpleNamespace(serving_max_wait_ms=0.0),
        tracer=tracer,
        _validate=lambda request: None,
        _dead=None,
        warmup_stats={"warmed": True},
        serve_group=lambda requests, queue_ms=0.0: SimpleNamespace(
            results=[_FakeResult(r.tenant_id or "t0") for r in requests],
            bucket=1,
        ),
    )
    batcher = MicroBatcher(engine, max_wait_ms=0.0)
    try:
        remote = _adapt_request(tenant_id="edge")
        remote.trace_ctx = {
            "trace_id": "feedc0de12345678",
            "parent_span_id": "gw-s000003",
            "request_id": "feedc0de12345678-g000001",
            "clock_offset_ms": -3.25,
        }
        local = _adapt_request(seed=9, tenant_id="local")
        batcher.submit(remote).get(timeout=30)
        batcher.submit(local).get(timeout=30)
    finally:
        batcher.close()
    roots = {r["attrs"]["tenant_id"]: r for r in records
             if r["name"] == "request"}
    adopted = roots["edge"]
    assert adopted["trace_id"] == "feedc0de12345678"
    assert adopted["parent_id"] == "gw-s000003"
    assert adopted["attrs"]["request_id"] == "feedc0de12345678-g000001"
    assert adopted["attrs"]["clock_offset_ms"] == -3.25
    assert adopted["span_id"].startswith("host00-")
    assert adopted["process"] == "host00"
    own = roots["local"]
    assert own["trace_id"] != "feedc0de12345678"
    assert own.get("parent_id") is None
    assert "clock_offset_ms" not in own["attrs"]
    # the queue child rides the adopted trace too
    queues = [r for r in records if r["name"] == "queue"]
    assert {q["trace_id"] for q in queues} == {
        adopted["trace_id"], own["trace_id"]
    }


def test_trace_ids_stable_across_hash_seeds():
    """Propagation is bit-stable across interpreter lifetimes: two
    fresh processes with different PYTHONHASHSEEDs decode the SAME wire
    frame through the real host handler and report identical adopted
    trace context — nothing in the path leans on hash ordering."""
    req = _adapt_request(tenant_id="tenant-5", deadline_ms=500.0)
    frame = gw.encode_request(req)
    header, blob = gw._decode_frame(frame)
    header.update(
        priority=0, gateway_elapsed_ms=0.5,
        trace_id="0123456789abcdef", parent_span_id="gw-s000042",
        request_id="0123456789abcdef-g000007", clock_offset_ms=-1.75,
    )
    fwd_hex = gw._encode_frame(header, [blob]).hex()
    script = (
        "from howtotrainyourmamlpytorch_tpu.serving.fleet import (\n"
        "    FleetHost)\n"
        "from howtotrainyourmamlpytorch_tpu.serving.gateway import (\n"
        "    decode_result)\n"
        "import json\n"
        "class Pending:\n"
        "    def __init__(self, request):\n"
        "        self.request = request\n"
        "    def get(self, timeout=None):\n"
        "        import numpy as np\n"
        "        class R:\n"
        "            tenant_id = self.request.tenant_id\n"
        "            preds = np.zeros((6, 5), dtype=np.float32)\n"
        "            loss = 0.0\n"
        "            accuracy = 1.0\n"
        "        return R()\n"
        "class Router:\n"
        "    def submit(self, request):\n"
        "        print(json.dumps(request.trace_ctx, sort_keys=True))\n"
        "        return Pending(request)\n"
        "host = FleetHost(Router(), None, host_id='host00')\n"
        "status, _, body = host.handle_serve(\n"
        "    bytes.fromhex('%s'))\n"
        "assert status == 200, (status, body)\n"
        "print(decode_result(body)['tenant_id'])\n"
        "host.close()\n"
    ) % fwd_hex
    outs = []
    for seed in ("0", "424242"):
        env = dict(os.environ, PYTHONHASHSEED=seed, JAX_PLATFORMS="cpu")
        outs.append(subprocess.run(
            [sys.executable, "-c", script], env=env, text=True,
            capture_output=True, check=True, timeout=120,
        ).stdout)
    assert outs[0] == outs[1]
    ctx = json.loads(outs[0].splitlines()[0])
    assert ctx == {
        "trace_id": "0123456789abcdef",
        "parent_span_id": "gw-s000042",
        "request_id": "0123456789abcdef-g000007",
        "clock_offset_ms": -1.75,
    }


# -- keep-alive connection pooling -------------------------------------------


def test_forwarder_reuses_pooled_connections():
    """Sequential forwards to the same host ride ONE kept-alive socket:
    after the first request primes the pool, reuse dominates, and
    /stats reports the reuse rate."""
    gateway, hosts, _ = _make_fleet(n=1)
    try:
        for i in range(6):
            status, _, _ = gateway.handle_serve(
                gw.encode_request(_adapt_request(seed=i))
            )
            assert status == 200
        assert gateway.pool_fresh >= 1
        assert gateway.pool_reused >= 4
        pool = gateway.stats()["conn_pool"]
        assert pool["reused"] == gateway.pool_reused
        assert pool["reuse_rate"] == pytest.approx(
            gateway.pool_reused
            / (gateway.pool_reused + gateway.pool_fresh),
            abs=1e-3,
        )
    finally:
        _close_fleet(gateway, hosts)


def test_stale_pooled_connection_retries_once_on_fresh_socket():
    """A broken kept-alive socket is retried ONCE on a guaranteed-fresh
    connection — invisible to the caller, counted in pool_retries, and
    never surfaced as a forward failure."""
    gateway, hosts, _ = _make_fleet(n=1)
    try:
        status, _, _ = gateway.handle_serve(
            gw.encode_request(_adapt_request(seed=0))
        )
        assert status == 200
        # sabotage the pooled socket under the gateway
        handle = gateway.ring[0]
        assert handle.pool
        for conn in handle.pool:
            if conn.sock is not None:
                conn.sock.close()
        status, _, _ = gateway.handle_serve(
            gw.encode_request(_adapt_request(seed=1))
        )
        assert status == 200
        assert gateway.pool_retries >= 1
        assert gateway.forward_failures == 0
    finally:
        _close_fleet(gateway, hosts)


# -- gateway /metrics --------------------------------------------------------


def test_gateway_metrics_prometheus_exposition():
    """The /metrics families parse as text-format 0.0.4 (including the
    histogram invariants parse_prometheus_text enforces) and agree with
    the gateway's own counters: typed sheds, per-priority admissions,
    pool reuse, and the admitted-latency LogHistogram family."""
    sink = _ListSink()
    gateway, hosts, _ = _make_fleet(
        n=1, sink=sink, serving_gateway_queue_budget=1024,
        serving_gateway_priority_tiers=3,
    )
    try:
        ok = _adapt_request(seed=0, tenant_id="t-ok")
        ok.priority = 2
        status, _, _ = gateway.handle_serve(gw.encode_request(ok))
        assert status == 200
        # pile up a queue, then ask for the impossible (the
        # test_gateway.py deadline-shed recipe)
        h = gateway.ring[0]
        hosts[h.host_id].pool.replicas[0]._depth = 500
        gateway.poll_once()
        doomed = _adapt_request(seed=1, deadline_ms=0.001)
        status, _, body = gateway.handle_serve(gw.encode_request(doomed))
        assert status == 429 and json.loads(body)["reason"] == "deadline"
        metrics = parse_prometheus_text(gateway.render_metrics())
        assert metrics["gateway_shed_total"]['reason="deadline"'] == 1.0
        assert metrics["gateway_admitted_total"]['priority="2"'] == 1.0
        assert metrics["gateway_ready_hosts"][""] == 1.0
        assert metrics["gateway_conn_pool_fresh_total"][""] >= 1.0
        assert metrics["gateway_rehomes_total"][""] == 0.0
        assert metrics["gateway_admitted_latency_ms_count"][""] == 1.0
        assert metrics["gateway_admitted_latency_ms_sum"][""] > 0.0
        assert metrics["gateway_admitted_latency_ms_bucket"][
            'le="+Inf"'] == 1.0
        # the HTTP route serves the same exposition
        served = gw.GatewayServer(gateway, port=0)
        try:
            import http.client

            conn = http.client.HTTPConnection(
                "127.0.0.1", served.port, timeout=10
            )
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("Content-Type").startswith("text/plain")
            assert parse_prometheus_text(resp.read().decode()) == metrics
            conn.close()
        finally:
            served.close()
    finally:
        _close_fleet(gateway, hosts)


# -- clock-aligned merged export ---------------------------------------------


def _span(name, cat, trace_id, span_id, start_ms, dur_ms,
          parent_id=None, process=None, tid="main", **attrs):
    rec = tel.make_record(
        "span", name=name, cat=cat, trace_id=trace_id, span_id=span_id,
        start_ms=start_ms, dur_ms=dur_ms, tid=tid, attrs=attrs,
    )
    if parent_id is not None:
        rec["parent_id"] = parent_id
    if process is not None:
        rec["process"] = process
    return rec


def _fleet_span_records(host_skew_ms=4000.0):
    """A two-process trace: gateway root + forward/wire, host spans on a
    clock running host_skew_ms AHEAD of the gateway's."""
    t = "aaaabbbbccccdddd"
    gwp, hp = "gateway", "host00"
    sk = host_skew_ms
    return [
        _span("request", "gateway", t, "gw-s1", 1000.0, 62.0,
              process=gwp, request_id="r1"),
        _span("gateway_queue", "gateway", t, "gw-s2", 1000.0, 2.0,
              parent_id="gw-s1", process=gwp),
        _span("forward", "gateway", t, "gw-s3", 1002.0, 59.0,
              parent_id="gw-s1", process=gwp),
        _span("wire", "gateway", t, "gw-s4", 1002.5, 58.0,
              parent_id="gw-s3", process=gwp),
        _span("request", "serving", t, "host00-s1", 1004.0 + sk, 55.0,
              parent_id="gw-s3", process=hp, clock_offset_ms=sk),
        _span("queue", "serving", t, "host00-s2", 1004.0 + sk, 10.0,
              parent_id="host00-s1", process=hp),
        _span("assemble", "serving", t, "host00-s3", 1014.0 + sk, 1.0,
              parent_id="host00-s1", process=hp),
        _span("dispatch", "serving", t, "host00-s4", 1015.0 + sk, 40.0,
              parent_id="host00-s1", process=hp),
        _span("sync", "serving", t, "host00-s5", 1055.0 + sk, 3.0,
              parent_id="host00-s1", process=hp),
    ]


def test_offset_shift_restores_parent_containment():
    """The merged export's acceptance geometry: with the Cristian
    offset applied, every host event lands INSIDE the gateway root's
    [ts, ts+dur] window on its own process track; without the shift the
    host track floats seconds away (the shift is load-bearing, not
    cosmetic)."""
    spans = _fleet_span_records(host_skew_ms=4000.0)
    trace = to_chrome_trace(spans, offsets_ms={"host00": 4000.0})
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    by_pid_name = {
        (e["args"]["span_id"]): e for e in xs
    }
    root = by_pid_name["gw-s1"]
    host_events = [e for e in xs if e["args"]["span_id"].startswith(
        "host00-")]
    gw_pid = root["pid"]
    host_pid = host_events[0]["pid"]
    assert gw_pid != host_pid
    for e in host_events:
        assert e["ts"] >= root["ts"]
        assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 0.2
    names = {
        m["args"]["name"] for m in metas if m["name"] == "process_name"
    }
    assert names == {"gateway", "host00"}
    # timestamps stay monotonic within every (pid, tid) track
    tracks = {}
    for e in xs:
        tracks.setdefault((e["pid"], e["tid"]), []).append(e["ts"])
    for ts_list in tracks.values():
        assert ts_list == sorted(ts_list)
    # ... and WITHOUT the shift, the host track is 4 seconds adrift
    unshifted = to_chrome_trace(spans)
    far = [e for e in unshifted["traceEvents"]
           if e["ph"] == "X" and e["args"]["span_id"] == "host00-s1"]
    assert far[0]["ts"] > root["ts"] + root["dur"]


def test_fleet_critical_path_attribution():
    """The six-stage decomposition on a known trace: wire is the socket
    window NET of the host's request span, device time lands in
    dispatch, and the complete-trace identity sum(stages) ~= e2e
    holds."""
    spans = _fleet_span_records()
    out = fleet_critical_path(spans)
    assert out["requests"] == 1 and out["complete"] == 1
    assert out["spanning_traces"] == 1
    assert out["processes"] == ["gateway", "host00"]
    st = out["stages"]
    assert st["gateway_queue_ms_mean"] == pytest.approx(2.0)
    assert st["wire_ms_mean"] == pytest.approx(58.0 - 55.0)
    assert st["host_queue_ms_mean"] == pytest.approx(10.0)
    assert st["dispatch_ms_mean"] == pytest.approx(40.0)
    assert out["coverage"] == pytest.approx(
        out["stage_sum_ms_mean"] / out["e2e_ms_mean"], abs=1e-4
    )
    assert 0.9 <= out["coverage"] <= 1.1


# -- cli trace --fleet -------------------------------------------------------


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def test_trace_cli_refuses_multiple_logs_without_fleet(tmp_path, capsys):
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _write_jsonl(a, [])
    _write_jsonl(b, [])
    assert trace_cli.main([str(a), str(b)]) == 2
    assert "--fleet" in capsys.readouterr().err


def test_trace_cli_fleet_merges_discovered_host_logs(tmp_path, capsys):
    """--fleet on the gateway log alone: the log.hostNN.jsonl siblings
    are auto-discovered (the `cli slo --fleet` rule), host spans are
    shifted by the gateway's clock records, and one merged Perfetto
    artifact lands with both process tracks."""
    spans = _fleet_span_records(host_skew_ms=4000.0)
    gw_log = tmp_path / "run.jsonl"
    host_log = tmp_path / "run.host00.jsonl"
    clock = tel.make_record(
        "gateway", event="clock", host="host00",
        clock_offset_ms=4000.0, clock_skew_bound_ms=0.2,
        rtt_ms=0.4, samples=3,
    )
    _write_jsonl(
        gw_log, [clock] + [r for r in spans if r["process"] == "gateway"]
    )
    _write_jsonl(
        host_log, [r for r in spans if r["process"] == "host00"]
    )
    assert trace_cli.main(["--fleet", "--json", str(gw_log)]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["log"] == [str(gw_log), str(host_log)]
    assert payload["clock_offsets_ms"] == {"host00": 4000.0}
    assert payload["fleet"]["complete"] == 1
    out_path = tmp_path / "run.trace.json"
    assert payload["out"] == str(out_path)
    trace = json.loads(out_path.read_text())
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len({e["pid"] for e in xs}) == 2
    root = [e for e in xs if e["args"]["span_id"] == "gw-s1"][0]
    host_root = [e for e in xs
                 if e["args"]["span_id"] == "host00-s1"][0]
    assert root["ts"] <= host_root["ts"]
    assert host_root["ts"] + host_root["dur"] <= (
        root["ts"] + root["dur"] + 0.2
    )
