"""Elastic multi-host chaos harness: kill-and-rejoin equivalence.

The pod-scale extension of ``test_resilience_e2e.py``'s proof standard,
across REAL process boundaries (``jax.distributed`` + gloo CPU
collectives, a fixed total of 6 virtual devices re-factored over 1/2/3
processes):

* **SIGKILL one worker mid-epoch**, tear the gang down, then resume the
  experiment at N-1 (=1) AND N+1 (=3) processes — final params, per-epoch
  summary CSV and the final test ensemble must match an uninterrupted
  2-process baseline. The N+1 rejoin is asserted BIT-identical: the
  episode->process assignment is the pure block partition of
  ``resilience/elastic.py`` over a checkpointed global cursor, the
  assembled global device batch (6 devices, process-major) is identical
  for every factorization, and the cross-process gloo ring reduces in a
  factorization-stable order. The N-1 (=1, single-process) rejoin is
  asserted at float32-ULP tolerance instead: a single-process run reduces
  its all-reduces with the in-memory kernel, whose summation order
  differs from the gloo ring by one ULP on near-zero gradients — a
  backend-kernel property, not an episode-stream one (the stream identity
  is what the tight tolerance demonstrates).

* **SIGTERM one (non-primary) worker**: the coordinated drain
  (``resilience/elastic.py``) must drain EVERY process at the same agreed
  iteration, write exactly one collective emergency checkpoint, and exit
  code 75 (``PREEMPT_EXIT_CODE``) on every process; resuming at 3
  processes completes bit-identically.

Both tests are slow-marked (the dedicated ``elastic-smoke`` CI job runs
them without the filter).
"""

import json
import os
import re
import shutil
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

TOTAL_DEVICES = 6  # re-factored as 1x6, 2x3, 3x2 (process x local devices)
BASE_PROCS = 2  # the baseline/chaos gang; rejoins run at 1 and 3
TOTAL_ITER_PER_EPOCH = 4
TOTAL_EPOCHS = 3
KILL_ITER = 6  # mid-epoch 2: after the epoch-1 boundary save, before epoch 2's
SIGTERM_ITER = 5  # + drain_margin_iters=2 -> agreed drain well inside the run
DRAIN_MARGIN = 2


def worker_config_kwargs(data_root, exp_name, cache_dir, total_epochs,
                         fault_spec=""):
    """The ONE config recipe every compared run trains (the subprocess
    worker imports this, like ``_resilience_worker`` imports
    ``make_cfg``). Global meta-batch of 6 tasks: divisible by every
    process count (1/2/3) and by the 6-device mesh — the elastic
    re-partition requirement."""
    return dict(
        experiment_name=str(exp_name),
        dataset_name="imagenet_synthetic_presplit",
        dataset_path=str(data_root),
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=8, image_width=8, image_channels=3,
        num_classes_per_set=2, num_samples_per_class=1,
        num_target_samples=1,
        batch_size=TOTAL_DEVICES,  # 1 task per device at every topology
        cnn_num_filters=4, num_stages=1, max_pooling=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        number_of_training_steps_per_iter=1,
        number_of_evaluation_steps_per_iter=1,
        second_order=False,
        total_epochs=total_epochs,
        total_iter_per_epoch=TOTAL_ITER_PER_EPOCH,
        num_evaluation_tasks=TOTAL_DEVICES,
        total_epochs_before_pause=100,
        num_dataprovider_workers=2,
        cache_dir=str(cache_dir),
        use_mmap_cache=True, use_remat=False, seed=0,
        telemetry_level="scalars",
        io_retry_backoff_s=0.0,
        drain_margin_iters=DRAIN_MARGIN,
        # persistent compile cache DISABLED: same jaxlib-0.4.37 CPU flake
        # as test_resilience_e2e (resumed donating steps deserialized from
        # the cache corrupt the CPU client)
        compilation_cache_dir="",
        fault_spec=fault_spec,
    )


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _spawn_gang(exp_name, data_root, cache_dir, num_processes,
                total_epochs=TOTAL_EPOCHS, fault_specs=None):
    """Spawn a coordinated worker gang (fault_specs: per-worker spec dict,
    None = fault-free) without waiting."""
    assert TOTAL_DEVICES % num_processes == 0
    n_local = TOTAL_DEVICES // num_processes
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(os.path.dirname(__file__), "_elastic_worker.py")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)  # workers own their device count
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return [
        subprocess.Popen(
            [
                sys.executable, worker,
                "--process_id", str(pid),
                "--num_processes", str(num_processes),
                "--port", str(port),
                "--n_local_devices", str(n_local),
                "--data_root", str(data_root),
                "--exp_name", str(exp_name),
                "--cache_dir", str(cache_dir),
                "--total_epochs", str(total_epochs),
                "--fault_spec",
                (fault_specs or {}).get(pid, ""),
            ],
            env=env,
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(num_processes)
    ]


def _communicate_all(procs, timeout=420):
    """Drain every worker's pipe concurrently (a worker blocked on a full
    pipe inside a collective would wedge the gang)."""
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(max(1, len(procs))) as pool:
        futs = [pool.submit(p.communicate, timeout=timeout) for p in procs]
        try:
            return [f.result()[0] for f in futs]
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise


def _is_gloo_abort(procs, outs) -> bool:
    """An upstream XLA:CPU gloo transport abort (SIGABRT + the preamble/
    peer-reset signature), not a failure of the code under test: gloo
    pairs collective ops between processes with no per-executable
    namespace, and the thunk executor can issue a program's independent
    collectives in different orders on different processes, so rare
    interleavings corrupt a TCP pair and abort the gang. The system
    facade already serializes every multihost dispatch on CPU
    (``_serialize_dispatches``) and reroutes orbax's device-psum barriers
    off the interconnect (``checkpoint.py``), which makes the
    train/val/checkpoint phases stable; the small residue (mostly the
    test-ensemble phase) is retried at the launch level below."""
    if not any(p.returncode == -signal.SIGABRT for p in procs):
        return False
    blob = "\n".join(outs)
    return "gloo" in blob.lower() or "preamble" in blob


def _run_gang(exp_name, data_root, cache_dir, num_processes,
              total_epochs=TOTAL_EPOCHS, fault_specs=None, timeout=420,
              expect_rc=0, retries=6, reset=None):
    """Launch a gang and wait. A gloo-shaped abort (see ``_is_gloo_abort``)
    is relaunched up to ``retries`` times — after ``reset()`` when given
    (the baseline wipes its experiment dir so it stays a genuinely
    uninterrupted run; resume phases relaunch as-is, which is just another
    resume). Any OTHER failure raises immediately with every worker's
    output."""
    for attempt in range(retries + 1):
        procs = _spawn_gang(
            exp_name, data_root, cache_dir, num_processes,
            total_epochs=total_epochs, fault_specs=fault_specs,
        )
        outs = _communicate_all(procs, timeout=timeout)
        if all(p.returncode == expect_rc for p in procs):
            return outs
        if attempt < retries and _is_gloo_abort(procs, outs):
            print(
                f"[elastic-e2e] gloo transport abort (upstream XLA:CPU "
                f"bug), relaunching gang (attempt {attempt + 2})",
                file=sys.stderr, flush=True,
            )
            if reset is not None:
                reset()
            continue
        # dump EVERY worker: the asserting worker is usually the collateral
        # victim (gloo peer reset / heartbeat timeout), not the root cause
        report = "\n".join(
            f"--- worker {pid}/{num_processes} rc={p.returncode} "
            f"(expected {expect_rc}) ---\n{out[-3000:]}"
            for pid, (p, out) in enumerate(zip(procs, outs))
        )
        raise AssertionError(f"gang failed:\n{report}")


# -- comparison helpers -------------------------------------------------------


DETERMINISTIC = re.compile(r"loss|accuracy|learning_rate|^epoch$")

#: float32-ULP tolerance for the single-process rejoin (see module
#: docstring): the in-memory all-reduce and the gloo ring order their sums
#: differently in the last bit on near-zero gradients
ULP_RTOL = 1e-5
ULP_ATOL = 1e-12


def _det_rows(exp_dir, filename="summary_statistics.csv"):
    import csv

    path = os.path.join(exp_dir, "logs", filename)
    with open(path) as f:
        rows = list(csv.DictReader(f))
    assert rows, f"no rows in {path}"
    return [
        {k: v for k, v in row.items() if DETERMINISTIC.search(k)}
        for row in rows
    ]


def _rows_close(rows_a, rows_b):
    """Numeric near-equality of the deterministic CSV columns (the
    ULP-tolerance twin of exact row equality)."""
    assert len(rows_a) == len(rows_b)
    for ra, rb in zip(rows_a, rows_b):
        assert set(ra) == set(rb)
        for k in ra:
            np.testing.assert_allclose(
                float(ra[k]), float(rb[k]), rtol=ULP_RTOL, atol=ULP_ATOL,
                err_msg=k,
            )


def _final_state(exp_dir, template_cfg, epoch=TOTAL_EPOCHS):
    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    return ckpt.load_checkpoint(
        os.path.join(exp_dir, "saved_models"), "train_model", epoch,
        maml.init_state(template_cfg),
    )


def _assert_equivalent(exp_dir, baseline_dir, template_cfg, bit_exact=True):
    """Final params + per-epoch stats + summary CSV + final test ensemble
    vs the uninterrupted baseline: bit-identical (``bit_exact=True``, the
    multi-process rejoins) or at float32-ULP tolerance (the single-process
    rejoin — same episode stream, different all-reduce kernel)."""
    import jax

    state_a, exp_a = _final_state(baseline_dir, template_cfg)
    state_b, exp_b = _final_state(exp_dir, template_cfg)
    for leaf_a, leaf_b in zip(
        jax.tree_util.tree_leaves(state_a._asdict()),
        jax.tree_util.tree_leaves(state_b._asdict()),
    ):
        if bit_exact:
            np.testing.assert_array_equal(
                np.asarray(leaf_a), np.asarray(leaf_b)
            )
        else:
            np.testing.assert_allclose(
                np.asarray(leaf_a), np.asarray(leaf_b),
                rtol=ULP_RTOL, atol=ULP_ATOL,
            )
    assert exp_a["current_iter"] == exp_b["current_iter"]
    det = lambda stats: {  # noqa: E731
        k: v for k, v in stats.items() if DETERMINISTIC.search(k)
    }
    if bit_exact:
        assert det(exp_a["per_epoch_statistics"]) == det(
            exp_b["per_epoch_statistics"]
        )
        assert _det_rows(exp_dir) == _det_rows(baseline_dir)
        assert _det_rows(exp_dir, "test_summary.csv") == _det_rows(
            baseline_dir, "test_summary.csv"
        )
    else:
        _rows_close(_det_rows(exp_dir), _det_rows(baseline_dir))
        _rows_close(
            _det_rows(exp_dir, "test_summary.csv"),
            _det_rows(baseline_dir, "test_summary.csv"),
        )


def _telemetry_records(exp_dir):
    path = os.path.join(exp_dir, "logs", "telemetry.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class _Env:
    def __init__(self, root):
        from test_resilience_e2e import _write_presplit_rgb

        self.root = str(root)
        self.data_root = os.path.join(
            self.root, "imagenet_synthetic_presplit"
        )
        self.cache_dir = os.path.join(self.root, "cache")
        _write_presplit_rgb(self.data_root)
        # the one uninterrupted baseline every phase is compared against:
        # the FULL 2-process run. A gloo-abort retry starts it over from a
        # clean slate so "uninterrupted" stays literally true.
        self.baseline_dir = os.path.join(self.root, "baseline")
        _run_gang(
            self.baseline_dir, self.data_root, self.cache_dir,
            num_processes=BASE_PROCS,
            reset=lambda: shutil.rmtree(self.baseline_dir,
                                        ignore_errors=True),
        )

    def exp(self, name):
        return os.path.join(self.root, name)

    def template_cfg(self):
        from howtotrainyourmamlpytorch_tpu.config import MAMLConfig

        return MAMLConfig(**worker_config_kwargs(
            self.data_root, self.exp("template"), self.cache_dir,
            TOTAL_EPOCHS,
        ))


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    return _Env(tmp_path_factory.mktemp("elastic"))


# -- SIGKILL one worker, rejoin at N-1 and N+1 processes ----------------------


@pytest.mark.slow
def test_sigkill_one_worker_then_rejoin_at_other_process_counts(env):
    """Kill worker 1 of a 2-process gang at iter 6 (mid-epoch 2; the
    epoch-1 collective checkpoint is durably on disk), tear down the
    survivor, and resume the experiment TWICE from copies of the killed
    state: at N+1=3 processes (asserted bit-identical to the uninterrupted
    2-process baseline) and at N-1=1 process (asserted at float32-ULP
    tolerance — the single-process all-reduce kernel orders sums
    differently than the gloo ring; the episode stream itself is
    identical). Params, per-epoch CSV and the test ensemble are all
    compared."""
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    exp = env.exp("killed")
    for attempt in range(7):
        shutil.rmtree(exp, ignore_errors=True)
        procs = _spawn_gang(
            exp, env.data_root, env.cache_dir, num_processes=BASE_PROCS,
            fault_specs={1: f"signal:sigkill@iter={KILL_ITER}"},
        )
        # worker 1 dies at the iter-6 boundary; the survivor wedges in the
        # next collective and is torn down by the harness (as a scheduler
        # would)
        deadline = time.time() + 420
        while procs[1].poll() is None:
            assert time.time() < deadline, "faulted worker did not die"
            time.sleep(0.2)
        time.sleep(1.0)  # let any in-flight primary-side file I/O settle
        procs[0].kill()
        outs = _communicate_all(procs, timeout=60)
        if procs[1].returncode == -signal.SIGKILL:
            break
        # a gloo transport abort (upstream XLA:CPU bug, see
        # _is_gloo_abort) beat the injected SIGKILL to it — rerun the
        # phase from scratch; anything else is a real failure
        assert attempt < 6 and _is_gloo_abort(procs, outs), (
            f"faulted worker died with rc={procs[1].returncode}, not the "
            f"injected SIGKILL:\n{outs[1][-3000:]}"
        )

    saved = os.path.join(exp, "saved_models")
    # nothing graceful happened: no emergency; `latest` is the epoch-1
    # boundary save (iter 4) — the kill landed mid-epoch 2 and the epoch-2
    # save (iter 8) was never reached
    assert not ckpt.checkpoint_exists(saved, "train_model", "emergency")
    latest = ckpt.peek_experiment_state(saved, "train_model", "latest")
    assert latest["current_iter"] == TOTAL_ITER_PER_EPOCH
    # the checkpoint carries the elastic resume keys
    assert latest["process_count"] == BASE_PROCS
    assert latest["episode_cursor"] == TOTAL_ITER_PER_EPOCH * TOTAL_DEVICES

    # resume the SAME killed state at two other topologies, from copies
    for name, n_proc, bit_exact in (
        ("rejoin_n3", 3, True),
        ("rejoin_n1", 1, False),
    ):
        dst = env.exp(name)
        shutil.copytree(exp, dst)
        _run_gang(
            dst, env.data_root, env.cache_dir, num_processes=n_proc,
        )
        _assert_equivalent(
            dst, env.baseline_dir, env.template_cfg(), bit_exact=bit_exact
        )
        records = _telemetry_records(dst)
        resumes = [
            r for r in records
            if r["kind"] == "elastic" and r["event"] == "resume"
        ]
        assert resumes, "elastic resume record missing"
        assert resumes[-1]["old_process_count"] == BASE_PROCS
        assert resumes[-1]["new_process_count"] == n_proc
        assert resumes[-1]["episode_cursor"] == (
            TOTAL_ITER_PER_EPOCH * TOTAL_DEVICES
        )
        from howtotrainyourmamlpytorch_tpu.telemetry import schema

        schema.validate_file(os.path.join(dst, "logs", "telemetry.jsonl"))


# -- SIGTERM one worker: coordinated drain of the whole gang ------------------


@pytest.mark.slow
def test_one_worker_sigterm_drains_every_process_at_same_iter(env):
    """SIGTERM ONLY the non-primary worker of a 2-process gang. The drain
    request -> primary commit -> agreed-iteration drain protocol
    (resilience/elastic.py) must stop BOTH processes at the same dispatch
    boundary, write exactly one collective emergency checkpoint, and exit
    75 everywhere; resuming at 3 processes completes bit-identically to
    the baseline."""
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt
    from howtotrainyourmamlpytorch_tpu.resilience import PREEMPT_EXIT_CODE

    exp = env.exp("drained")
    outs = _run_gang(
        exp, env.data_root, env.cache_dir, num_processes=BASE_PROCS,
        fault_specs={1: f"signal:sigterm@iter={SIGTERM_ITER}"},
        expect_rc=PREEMPT_EXIT_CODE,
        # a gloo-abort retry reruns the whole drain scenario from scratch
        reset=lambda: shutil.rmtree(exp, ignore_errors=True),
    )
    # every process drained at the SAME agreed iteration
    acks = []
    for out in outs:
        m = re.search(r"draining at agreed iter (\d+)", out)
        assert m, f"no drain ack in worker output:\n{out[-2000:]}"
        acks.append(int(m.group(1)))
    assert len(set(acks)) == 1, f"processes drained at different iters: {acks}"
    drain_iter = acks[0]
    assert SIGTERM_ITER < drain_iter < TOTAL_EPOCHS * TOTAL_ITER_PER_EPOCH

    # exactly one emergency checkpoint, written at the agreed iteration
    saved = os.path.join(exp, "saved_models")
    names = [n for n in os.listdir(saved) if n.endswith("_emergency")]
    assert names == ["train_model_emergency"]
    emerg = ckpt.peek_experiment_state(saved, "train_model", "emergency")
    assert emerg["emergency_reason"] == "preemption"
    assert emerg["current_iter"] == drain_iter
    assert emerg["process_count"] == BASE_PROCS
    assert emerg["episode_cursor"] == drain_iter * TOTAL_DEVICES

    # the primary's log documents the protocol (request came from worker 1)
    records = _telemetry_records(exp)
    elastic = [r for r in records if r["kind"] == "elastic"]
    events = [r["event"] for r in elastic]
    assert "drain_commit" in events and "drain_ack" in events
    commit = next(r for r in elastic if r["event"] == "drain_commit")
    assert commit["requested_by"] == 1
    assert commit["drain_iter"] == drain_iter

    # rejoin at N+1 processes: picks the emergency over `latest`, finishes,
    # and the emergency is pruned once superseded
    _run_gang(exp, env.data_root, env.cache_dir, num_processes=3)
    _assert_equivalent(exp, env.baseline_dir, env.template_cfg())
    assert not ckpt.checkpoint_exists(saved, "train_model", "emergency")
