"""Training-health monitor: the on-device anomaly probes (presence,
correctness, bit-identity with probes off), the host-side AnomalyDetector
rules, the flight recorder ring + incident dumps, and the end-to-end
builder run that turns a forced anomaly into an on-disk incident."""

import json
import os

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu import telemetry as tel
from howtotrainyourmamlpytorch_tpu.experiment.system import MAMLFewShotClassifier
from howtotrainyourmamlpytorch_tpu.telemetry.flight_recorder import (
    INCIDENT_MANIFEST,
    RING_FILENAME,
    FlightRecorder,
)
from howtotrainyourmamlpytorch_tpu.telemetry.health import (
    PROBE_KEYS,
    AnomalyDetector,
    HealthMonitor,
)


def _batch(cfg, seed=0):
    from conftest import make_synthetic_batch

    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg, seed=seed)
    return x_s, x_t, y_s, y_t  # the facade's (x_s, x_t, y_s, y_t) order


# -- config knobs -----------------------------------------------------------


def test_config_validates_health_knobs(tiny_cfg):
    with pytest.raises(ValueError, match="health_level"):
        tiny_cfg.replace(health_level="bogus")
    with pytest.raises(ValueError, match="health_patience"):
        tiny_cfg.replace(health_patience=0)
    with pytest.raises(ValueError, match="health_grad_norm_limit"):
        tiny_cfg.replace(health_grad_norm_limit=-1.0)
    with pytest.raises(ValueError, match="anomaly_loss_spike_factor"):
        tiny_cfg.replace(anomaly_loss_spike_factor=-1.0)
    with pytest.raises(ValueError, match="anomaly_ema_beta"):
        tiny_cfg.replace(anomaly_ema_beta=1.0)
    with pytest.raises(ValueError, match="flight_recorder_steps"):
        tiny_cfg.replace(flight_recorder_steps=-1)
    with pytest.raises(ValueError, match="max_state_dumps"):
        tiny_cfg.replace(max_state_dumps=-2)
    # 0 means "rule/recorder disabled", not an error
    tiny_cfg.replace(
        anomaly_loss_spike_factor=0.0, anomaly_grad_spike_factor=0.0,
        flight_recorder_steps=0, max_state_dumps=0,
        anomaly_cooldown_steps=0, anomaly_warmup_steps=0,
    )


# -- on-device probes -------------------------------------------------------


@pytest.mark.slow
def test_probes_ride_with_metrics(tiny_cfg):
    cfg = tiny_cfg.replace(health_level="monitor")
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    losses = model.run_train_iter(_batch(cfg), epoch=0)
    health = losses["health"]
    assert sorted(health) == sorted(PROBE_KEYS)
    vals = {k: float(np.asarray(v)) for k, v in health.items()}
    assert vals["nonfinite_grads"] == 0
    assert vals["grad_norm"] > 0 and np.isfinite(vals["grad_norm"])
    assert vals["update_norm"] > 0 and vals["param_norm"] > 0
    np.testing.assert_allclose(
        vals["loss"], float(np.asarray(losses["loss"])), rtol=1e-6
    )


@pytest.mark.slow
def test_probes_grad_norm_matches_grads_fn(tiny_cfg):
    """The probe's global grad norm equals the norm of the meta-gradients
    the step actually applied (pre-clip), computed independently via
    make_grads_fn."""
    import jax

    from howtotrainyourmamlpytorch_tpu.core import maml, msl

    cfg = tiny_cfg.replace(health_level="monitor")
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    x_s, x_t, y_s, y_t = _batch(cfg)
    state_before = model.state
    weights = msl.loss_weights_for(
        cfg.number_of_training_steps_per_iter,
        cfg.use_multi_step_loss_optimization, True, 0,
        cfg.multi_step_loss_num_epochs,
    )
    _, grads = maml.make_grads_fn(cfg, second_order=True)(
        state_before,
        *(np.reshape(a, a.shape) for a in (
            model._convert_batch((x_s, x_t, y_s, y_t))
        )),
        np.asarray(weights),
    )
    expected = np.sqrt(sum(
        float(np.sum(np.square(np.asarray(g, np.float64))))
        for g in jax.tree_util.tree_leaves(grads)
    ))
    losses = model.run_train_iter((x_s, x_t, y_s, y_t), epoch=0)
    got = float(np.asarray(losses["health"]["grad_norm"]))
    np.testing.assert_allclose(got, expected, rtol=1e-4)


@pytest.mark.slow
def test_probes_off_vs_on_bit_identical(tiny_cfg):
    """health_level='monitor' must not change a single bit of the training
    metrics or the learned parameters: the probes are pure reads of step
    outputs, never inputs to the loss/update graph."""
    cfg_on = tiny_cfg.replace(health_level="monitor")
    m_off = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
    m_on = MAMLFewShotClassifier(cfg_on, use_mesh=False)
    for step in range(2):
        batch = _batch(tiny_cfg, seed=step)
        l_off = m_off.run_train_iter(batch, epoch=0)
        l_on = m_on.run_train_iter(batch, epoch=0)
        assert "health" not in l_off
        assert "health" in l_on
        np.testing.assert_array_equal(
            np.asarray(l_off["loss"]), np.asarray(l_on["loss"])
        )
        np.testing.assert_array_equal(
            np.asarray(l_off["accuracy"]), np.asarray(l_on["accuracy"])
        )
    for key in m_off.state.net:
        np.testing.assert_array_equal(
            np.asarray(m_off.state.net[key]), np.asarray(m_on.state.net[key]),
            err_msg=key,
        )


@pytest.mark.slow
def test_probes_detect_injected_nan(tiny_cfg):
    """A NaN in the input pixels must surface as non-finite probe values —
    the exact signal the epoch-granular CSV can never carry."""
    cfg = tiny_cfg.replace(health_level="monitor")
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    x_s, x_t, y_s, y_t = _batch(cfg)
    x_bad = np.array(x_s)
    x_bad[0, 0, 0, 0, 0, 0] = np.nan
    losses = model.run_train_iter((x_bad, x_t, y_s, y_t), epoch=0)
    health = {k: float(np.asarray(v)) for k, v in losses["health"].items()}
    assert health["nonfinite_grads"] > 0
    assert not np.isfinite(health["loss"])


@pytest.mark.slow
def test_probes_chunked_dispatch_stack(tiny_cfg):
    """steps_per_dispatch>1: probes come back (k,)-stacked from the fused
    scan, one entry per iteration."""
    cfg = tiny_cfg.replace(health_level="monitor")
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    batches = [_batch(cfg, seed=s) for s in range(3)]
    losses = model.run_train_iters(batches, epoch=0)
    health = losses["health"]
    for key in PROBE_KEYS:
        assert np.asarray(health[key]).shape == (3,), key


@pytest.mark.slow
def test_eval_has_no_probes(tiny_cfg):
    cfg = tiny_cfg.replace(health_level="monitor")
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    losses, _ = model.run_validation_iter(_batch(cfg))
    assert "health" not in losses


# -- AnomalyDetector --------------------------------------------------------


def _entry(loss=1.0, grad_norm=1.0, nonfinite=0, update_norm=0.01,
           param_norm=10.0):
    return {
        "loss": loss, "grad_norm": grad_norm, "nonfinite_grads": nonfinite,
        "update_norm": update_norm, "param_norm": param_norm,
    }


def test_detector_nonfinite_rules_always_armed():
    det = AnomalyDetector(warmup_steps=100, cooldown_steps=0)
    assert det.update(0, _entry()) == []
    reasons = {a["reason"] for a in det.update(1, _entry(nonfinite=7))}
    assert reasons == {"nonfinite_grads"}
    reasons = {a["reason"] for a in det.update(2, _entry(loss=float("nan")))}
    assert reasons == {"nonfinite_loss"}


def test_detector_spike_rules_need_warmup_and_fire():
    det = AnomalyDetector(
        loss_spike_factor=3.0, grad_spike_factor=3.0,
        ema_beta=0.5, warmup_steps=3, cooldown_steps=0,
    )
    # during warmup nothing fires, even on a 100x jump
    for i in range(3):
        assert det.update(i, _entry(loss=1.0, grad_norm=1.0)) == []
    assert det.update(3, _entry(loss=100.0, grad_norm=1.0)) != []
    # the spike folded into the EMA; a return to baseline stays quiet
    assert det.update(4, _entry(loss=1.0, grad_norm=1.0)) == []
    out = det.update(5, _entry(loss=1.0, grad_norm=500.0))
    assert [a["reason"] for a in out] == ["grad_norm_spike"]
    assert out[0]["value"] == 500.0 and out[0]["threshold"] > 0


def test_detector_zero_factor_disables_spike_rule():
    det = AnomalyDetector(
        loss_spike_factor=0.0, grad_spike_factor=0.0,
        warmup_steps=0, cooldown_steps=0,
    )
    for i in range(5):
        det.update(i, _entry(loss=1.0))
    assert det.update(5, _entry(loss=1e9, grad_norm=1e9)) == []


def test_detector_cooldown_suppresses_per_reason():
    det = AnomalyDetector(warmup_steps=0, cooldown_steps=10)
    assert det.update(0, _entry(nonfinite=1)) != []
    # same reason inside the window: suppressed
    assert det.update(5, _entry(nonfinite=1)) == []
    # a DIFFERENT reason still fires inside the window
    assert [a["reason"] for a in det.update(6, _entry(
        nonfinite=1, loss=float("inf")))] == ["nonfinite_loss"]
    # window elapsed: fires again
    assert det.update(10, _entry(nonfinite=1)) != []


def test_detector_update_ratio_ceiling():
    det = AnomalyDetector(update_ratio_max=0.1, warmup_steps=0,
                          cooldown_steps=0)
    assert det.update(0, _entry(update_norm=0.5, param_norm=10.0)) == []
    out = det.update(1, _entry(update_norm=5.0, param_norm=10.0))
    assert [a["reason"] for a in out] == ["update_ratio"]


def test_detector_grad_norm_limit_is_absolute_and_warmup_free():
    """Unlike the EMA spike rule, the absolute ceiling fires on the very
    first observation — a run whose gradients are already huge at step 0
    has no sane baseline to be relative to."""
    det = AnomalyDetector(grad_spike_factor=0.0, grad_norm_limit=100.0,
                          warmup_steps=50, cooldown_steps=0)
    out = det.update(0, _entry(grad_norm=150.0))
    assert [a["reason"] for a in out] == ["grad_norm_limit"]
    assert out[0]["value"] == 150.0 and out[0]["threshold"] == 100.0
    assert det.update(1, _entry(grad_norm=50.0)) == []
    # a NaN norm is the nonfinite rules' job, not a limit breach
    out = det.update(2, _entry(grad_norm=float("nan"), nonfinite=1))
    assert [a["reason"] for a in out] == ["nonfinite_grads"]


def test_detector_catches_overflowed_grad_norm():
    """Finite gradient elements whose f32 sum-of-squares reduction
    overflows to inf: no element-level rule sees it (nonfinite_grads=0,
    loss finite) and every value-gated rule skips non-finite input, so a
    dedicated always-armed rule must fire — else a catastrophically
    exploded run trains to completion silently."""
    det = AnomalyDetector(warmup_steps=100, cooldown_steps=0)
    out = det.update(0, _entry(grad_norm=float("inf")))
    assert [a["reason"] for a in out] == ["nonfinite_grad_norm"]
    assert det.anomalous_iterations == 1
    # with non-finite ELEMENTS present, nonfinite_grads owns the report
    out = det.update(1, _entry(grad_norm=float("nan"), nonfinite=3))
    assert [a["reason"] for a in out] == ["nonfinite_grads"]
    # an entry without the probe key (foreign payload) stays quiet
    assert det.update(2, {"loss": 1.0}) == []


def test_detector_counts_anomalous_iterations_through_cooldown():
    """halt patience counts iterations where a rule condition HELD, so the
    per-reason report cooldown can never stretch the halt decision."""
    det = AnomalyDetector(warmup_steps=0, cooldown_steps=1000)
    assert det.update(0, _entry(nonfinite=1)) != []   # reported
    assert det.update(1, _entry(nonfinite=1)) == []   # suppressed...
    assert det.update(2, _entry(nonfinite=1)) == []   # ...and suppressed
    assert det.anomalous_iterations == 3              # ...but all counted
    det.update(3, _entry())
    assert det.anomalous_iterations == 3


def test_detector_nan_does_not_poison_ema():
    det = AnomalyDetector(loss_spike_factor=3.0, ema_beta=0.5,
                          warmup_steps=0, cooldown_steps=0)
    det.update(0, _entry(loss=1.0))
    det.update(1, _entry(loss=float("nan")))  # fires nonfinite_loss only
    assert det.ema("loss") == 1.0  # NaN never folded in
    # recovery to baseline is judged against the clean EMA
    assert det.update(2, _entry(loss=1.0)) == []


# -- FlightRecorder ---------------------------------------------------------


def test_recorder_ring_wraps(tmp_path):
    rec = FlightRecorder(4, str(tmp_path / "inc"), cooldown_steps=0)
    for i in range(10):
        rec.record_step({"iter": i})
    ring = rec.snapshot()
    assert [e["iter"] for e in ring] == [6, 7, 8, 9]


def test_recorder_dump_writes_ring_and_manifest(tmp_path):
    rec = FlightRecorder(8, str(tmp_path / "inc"), cooldown_steps=0)
    for i in range(3):
        rec.record_step({"iter": i, "loss": float(i)})
    rec.note_event("epoch", epoch=1, val_accuracy_mean=0.5)
    path = rec.dump("nonfinite_grads", 3, details={"anomaly": {"value": 7}})
    assert path is not None and os.path.isdir(path)
    with open(os.path.join(path, RING_FILENAME)) as f:
        entries = [json.loads(line) for line in f]
    assert len(entries) == 4
    assert entries[0]["iter"] == 0 and entries[-1]["event"] == "epoch"
    with open(os.path.join(path, INCIDENT_MANIFEST)) as f:
        manifest = json.load(f)
    assert manifest["reason"] == "nonfinite_grads"
    assert manifest["iter"] == 3 and manifest["ring_entries"] == 4
    assert manifest["state_dumped"] is False
    assert manifest["details"]["anomaly"]["value"] == 7


def test_recorder_cooldown_and_state_dump_cap(tmp_path):
    dumps = []
    rec = FlightRecorder(8, str(tmp_path / "inc"), max_state_dumps=1,
                         cooldown_steps=10)
    p1 = rec.dump("loss_spike", 0, state_dump_fn=dumps.append)
    # the state dump is written into the STAGING dir (<path>.tmp) so the
    # atomic publish rename covers it — a kill mid-dump never leaves a
    # manifest-less partial incident dir
    assert p1 is not None and dumps == [p1 + ".tmp"]
    assert not os.path.exists(p1 + ".tmp")  # staging renamed away
    # inside the cooldown window: no dump at all
    assert rec.dump("loss_spike", 5, state_dump_fn=dumps.append) is None
    # window elapsed: incident written, but the state-dump cap is spent
    p2 = rec.dump("loss_spike", 10, state_dump_fn=dumps.append)
    assert p2 is not None and dumps == [p1 + ".tmp"]
    with open(os.path.join(p2, INCIDENT_MANIFEST)) as f:
        assert json.load(f)["state_dumped"] is False


def test_recorder_force_dump_bypasses_cooldown(tmp_path):
    """A watchdog stall (or the halt escalation) right after a routine
    anomaly dump must still produce its incident: force=True bypasses the
    reason-agnostic cooldown, never the disabled gate."""
    rec = FlightRecorder(8, str(tmp_path / "inc"), cooldown_steps=200)
    assert rec.dump("loss_spike", 0) is not None
    assert rec.dump("watchdog_stall", 50) is None  # sanity: window active
    path = rec.dump("watchdog_stall", 50, force=True)
    assert path is not None and os.path.isdir(path)
    off = FlightRecorder(0, str(tmp_path / "inc2"))
    assert off.dump("watchdog_stall", 0, force=True) is None


def test_recorder_state_dump_failure_is_recorded_not_raised(tmp_path):
    def boom(path):
        raise RuntimeError("device wedged")

    rec = FlightRecorder(8, str(tmp_path / "inc"), cooldown_steps=0)
    path = rec.dump("nonfinite_loss", 1, state_dump_fn=boom)
    assert path is not None
    with open(os.path.join(path, INCIDENT_MANIFEST)) as f:
        manifest = json.load(f)
    assert manifest["state_dumped"] is False
    assert "device wedged" in manifest["state_error"]


def test_recorder_disabled_cases(tmp_path):
    assert not FlightRecorder(0, str(tmp_path)).enabled
    assert not FlightRecorder(8, str(tmp_path), is_primary=False).enabled
    rec = FlightRecorder(0, str(tmp_path / "inc"))
    rec.record_step({"iter": 0})
    assert rec.snapshot() == []
    assert rec.dump("x", 0) is None


def test_recorder_never_clobbers_same_incident_name(tmp_path):
    rec = FlightRecorder(4, str(tmp_path / "inc"), cooldown_steps=0)
    p1 = rec.dump("loss_spike", 7)
    p2 = rec.dump("loss_spike", 7)
    assert p1 != p2 and os.path.isdir(p1) and os.path.isdir(p2)


# -- HealthMonitor ----------------------------------------------------------


def test_monitor_one_dispatch_lag_and_flush(tiny_cfg):
    cfg = tiny_cfg.replace(health_level="monitor", anomaly_warmup_steps=0,
                           anomaly_cooldown_steps=0)
    mon = HealthMonitor(cfg)
    mon.observe(0, {k: np.float32(1.0) for k in PROBE_KEYS})
    assert mon.steps_seen == 0  # deferred: nothing evaluated yet
    mon.observe(1, {k: np.float32(1.0) for k in PROBE_KEYS})
    assert mon.steps_seen == 1  # the previous dispatch got evaluated
    mon.flush()
    assert mon.steps_seen == 2
    mon.flush()  # idempotent
    assert mon.steps_seen == 2


def test_monitor_splits_stacked_payloads_and_reports(tiny_cfg, tmp_path):
    cfg = tiny_cfg.replace(
        health_level="monitor", telemetry_level="scalars",
        anomaly_warmup_steps=0, anomaly_cooldown_steps=0,
    )
    t = tel.Telemetry(cfg, str(tmp_path))
    rec = FlightRecorder(16, str(tmp_path / "inc"), cooldown_steps=0)
    mon = HealthMonitor(cfg, telemetry=t, recorder=rec)
    clean = {
        "loss": np.ones(3, np.float32),
        "grad_norm": np.ones(3, np.float32),
        "nonfinite_grads": np.zeros(3, np.int32),
        "update_norm": np.full(3, 0.01, np.float32),
        "param_norm": np.full(3, 10.0, np.float32),
    }
    bad = {k: np.array(v) for k, v in clean.items()}
    bad["nonfinite_grads"] = np.array([0, 5, 0], np.int32)
    mon.observe(0, clean)
    mon.observe(3, bad)  # evaluates the clean chunk
    mon.flush()          # evaluates the bad chunk -> anomaly at iter 4
    t.close()
    assert mon.steps_seen == 6
    assert mon.anomaly_count == 1
    recs = list(tel.iter_records(
        os.path.join(str(tmp_path), tel.TELEMETRY_FILENAME)))
    anoms = [r for r in recs if r["kind"] == "anomaly"]
    incidents = [r for r in recs if r["kind"] == "incident"]
    assert len(anoms) == 1 and anoms[0]["iter"] == 4
    assert anoms[0]["reason"] == "nonfinite_grads"
    assert len(incidents) == 1 and os.path.isdir(incidents[0]["path"])
    for r in recs:
        tel.validate_record(r)
    # the ring inside the incident carries the clean lead-up steps
    with open(os.path.join(incidents[0]["path"], RING_FILENAME)) as f:
        ring = [json.loads(line) for line in f]
    assert [e["iter"] for e in ring if "iter" in e][:3] == [0, 1, 2]


def test_monitor_survives_incident_dump_io_failure(tiny_cfg, tmp_path):
    """A disk-full/permission error writing the incident directory is
    best-effort forensics: it must not unwind into the train loop and kill
    a monitor-only run (the anomaly itself is still counted/reported)."""
    cfg = tiny_cfg.replace(health_level="monitor", anomaly_warmup_steps=0,
                           anomaly_cooldown_steps=0)
    rec = FlightRecorder(8, str(tmp_path / "inc"), cooldown_steps=0)
    rec.dump = lambda *a, **k: (_ for _ in ()).throw(OSError("disk full"))
    mon = HealthMonitor(cfg, recorder=rec)
    bad = {k: np.float32(1.0) for k in PROBE_KEYS}
    bad["nonfinite_grads"] = np.int32(3)
    mon.observe(0, bad)
    mon.flush()  # must not raise
    assert mon.anomaly_count == 1


def test_monitor_handles_multihost_list_payload(tiny_cfg):
    cfg = tiny_cfg.replace(health_level="monitor", anomaly_warmup_steps=0)
    mon = HealthMonitor(cfg)
    payload = [
        {k: np.float32(1.0) for k in PROBE_KEYS},
        {k: np.float32(2.0) for k in PROBE_KEYS},
    ]
    mon.observe(0, payload)
    mon.flush()
    assert mon.steps_seen == 2


def test_monitor_halt_latches_on_patience(tiny_cfg):
    cfg = tiny_cfg.replace(
        health_level="halt", health_patience=2,
        anomaly_warmup_steps=0, anomaly_cooldown_steps=0,
    )
    mon = HealthMonitor(cfg)
    clean = {k: np.float32(1.0) for k in PROBE_KEYS}
    clean["nonfinite_grads"] = np.int32(0)
    bad = dict(clean, nonfinite_grads=np.int32(3))
    mon.observe(0, bad)
    mon.observe(1, clean)  # evaluates the first bad step: 1 < patience
    assert not mon.should_halt
    mon.observe(2, bad)    # evaluates clean
    assert not mon.should_halt
    mon.observe(3, clean)  # evaluates the second bad step: latch
    assert mon.should_halt
    assert mon.halt_anomaly["iter"] == 2
    assert mon.halt_anomaly["reason"] == "nonfinite_grads"


def test_monitor_halt_latches_even_when_cooldown_suppresses_report(tiny_cfg):
    cfg = tiny_cfg.replace(
        health_level="halt", health_patience=2,
        anomaly_warmup_steps=0, anomaly_cooldown_steps=1000,
    )
    mon = HealthMonitor(cfg)
    bad = {
        **{k: np.float32(1.0) for k in PROBE_KEYS},
        "nonfinite_grads": np.int32(1),
    }
    mon.observe(0, bad)
    mon.observe(1, bad)
    mon.flush()
    assert mon.should_halt
    # the latching iteration's report was cooldown-suppressed; the latch
    # says so instead of inventing a rule
    assert mon.halt_anomaly["reason"] == "anomaly_under_cooldown"
    assert mon.anomaly_count == 1  # only the first was reported


def test_monitor_level_monitor_never_latches_halt(tiny_cfg):
    cfg = tiny_cfg.replace(
        health_level="monitor", health_patience=1,
        anomaly_warmup_steps=0, anomaly_cooldown_steps=0,
    )
    mon = HealthMonitor(cfg)
    bad = {
        **{k: np.float32(1.0) for k in PROBE_KEYS},
        "nonfinite_grads": np.int32(1),
    }
    for i in range(3):
        mon.observe(i, bad)
    mon.flush()
    assert mon.anomaly_count == 3 and not mon.should_halt


# -- end-to-end through the builder ----------------------------------------


@pytest.mark.slow
def test_builder_health_e2e_forced_anomaly(tmp_path):
    """A tiny probes-on train with a hair-trigger spike rule: the run must
    finish normally AND leave behind (a) anomaly + incident records in a
    schema-valid telemetry log, (b) an incident directory whose ring and
    manifest parse, and (c) a state dump that orbax can restore."""
    from test_e2e_presplit import _write_presplit_rgb

    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.data.loader import MetaLearningDataLoader
    from howtotrainyourmamlpytorch_tpu.experiment.builder import ExperimentBuilder

    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root))
    cfg = MAMLConfig(
        experiment_name=str(tmp_path / "exp_health"),
        dataset_name="mini_imagenet_full_size",
        dataset_path=str(data_root),
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=10, image_width=10, image_channels=3,
        num_classes_per_set=2, num_samples_per_class=1, num_target_samples=1,
        batch_size=2, cnn_num_filters=4, num_stages=2, max_pooling=True,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True, second_order=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_epochs=2, total_iter_per_epoch=4, num_evaluation_tasks=4,
        total_epochs_before_pause=100,
        num_dataprovider_workers=2, cache_dir=str(tmp_path / "cache"),
        use_mmap_cache=True, use_remat=False, seed=0,
        steps_per_dispatch=2,
        eval_batches_per_dispatch=2,
        telemetry_level="scalars",
        health_level="monitor",
        # hair trigger: every armed step's loss "spikes" over 1e-6 x EMA
        anomaly_loss_spike_factor=1e-6,
        anomaly_warmup_steps=1,
        anomaly_cooldown_steps=0,
        flight_recorder_steps=8,
        max_state_dumps=1,
    )
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    builder = ExperimentBuilder(
        cfg, model, MetaLearningDataLoader,
        experiment_root=str(tmp_path), verbose=False,
    )
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0

    log_path = os.path.join(builder.logs_filepath, tel.TELEMETRY_FILENAME)
    assert tel.validate_file(log_path) > 0
    recs = list(tel.iter_records(log_path))
    kinds = [r["kind"] for r in recs]
    assert "anomaly" in kinds and "incident" in kinds
    # run_start carries the config snapshot telemetry_cli diff consumes
    run_start = next(r for r in recs if r["kind"] == "run_start")
    assert run_start["config"]["health_level"] == "monitor"
    # the CSV stayed clean: probe keys never leak into the summary row
    import csv

    with open(os.path.join(builder.logs_filepath,
                           "summary_statistics.csv")) as f:
        header = next(csv.reader(f))
    assert not any("health" in k or "grad_norm" in k for k in header)

    incidents = [r for r in recs if r["kind"] == "incident"]
    inc_dir = incidents[0]["path"]
    assert os.path.isdir(inc_dir)
    with open(os.path.join(inc_dir, INCIDENT_MANIFEST)) as f:
        manifest = json.load(f)
    assert manifest["state_dumped"] is True
    with open(os.path.join(inc_dir, RING_FILENAME)) as f:
        ring = [json.loads(line) for line in f]
    assert ring  # the lead-up context made it to disk
    # exactly one state dump (max_state_dumps=1) and it restores
    state_dirs = [
        r["path"] for r in incidents
        if os.path.isdir(os.path.join(r["path"], "state"))
    ]
    assert len(state_dirs) == 1
    import orbax.checkpoint as ocp

    restored = ocp.StandardCheckpointer().restore(
        os.path.join(os.path.abspath(state_dirs[0]), "state")
    )
    assert sorted(restored.keys()) == ["bn", "lslr", "net", "opt"]
    with open(os.path.join(state_dirs[0], "experiment_state.json")) as f:
        exp_state = json.load(f)
    assert "current_iter" in exp_state


@pytest.mark.slow
def test_builder_halt_e2e_diverged_run(tmp_path):
    """``health_level='halt'`` end-to-end forensics (the acceptance
    criterion): a deliberately diverged run raises TrainingDivergedError
    within health_patience iterations (plus the one-dispatch detection
    lag), leaves a RESUMABLE train_model_emergency checkpoint, a forced
    ``halt`` incident dump, and a schema-valid telemetry log that `cli
    inspect` renders the anomaly timeline from."""
    import subprocess
    import sys as _sys

    from test_e2e_presplit import _write_presplit_rgb

    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.data.loader import MetaLearningDataLoader
    from howtotrainyourmamlpytorch_tpu.experiment.builder import ExperimentBuilder
    from howtotrainyourmamlpytorch_tpu.experiment.checkpoint import (
        checkpoint_exists,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry import TrainingDivergedError

    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root))
    cfg = MAMLConfig(
        experiment_name=str(tmp_path / "exp_halt"),
        dataset_name="mini_imagenet_full_size",
        dataset_path=str(data_root),
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=10, image_width=10, image_channels=3,
        num_classes_per_set=2, num_samples_per_class=1, num_target_samples=1,
        batch_size=2, cnn_num_filters=4, num_stages=2, max_pooling=True,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True, second_order=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_epochs=2, total_iter_per_epoch=4, num_evaluation_tasks=4,
        total_epochs_before_pause=100,
        num_dataprovider_workers=2, cache_dir=str(tmp_path / "cache"),
        use_mmap_cache=True, use_remat=False, seed=0,
        steps_per_dispatch=2,
        eval_batches_per_dispatch=2,
        telemetry_level="scalars",
        health_level="halt",
        health_patience=1,
        # hair trigger: every armed step's loss "spikes" over 1e-6 x EMA
        anomaly_loss_spike_factor=1e-6,
        anomaly_warmup_steps=1,
        anomaly_cooldown_steps=0,
        flight_recorder_steps=8,
        max_state_dumps=1,
    )
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    builder = ExperimentBuilder(
        cfg, model, MetaLearningDataLoader,
        experiment_root=str(tmp_path), verbose=False,
    )
    with pytest.raises(TrainingDivergedError) as exc_info:
        builder.run_experiment()
    err = exc_info.value
    # halted within patience + the one-dispatch lag — nowhere near the
    # configured 2 epochs x 4 iters of training
    assert err.iter_at_halt is not None and err.iter_at_halt <= 4
    assert int(builder.state["current_iter"]) < 8

    # the emergency checkpoint exists and RESUMES through the normal path
    assert err.checkpoint_path is not None
    assert checkpoint_exists(
        builder.saved_models_filepath, "train_model", "emergency"
    )
    exp_state = model.load_model(builder.saved_models_filepath, "emergency")
    assert "current_iter" in exp_state

    # the forced halt dump: ring + manifest naming the emergency checkpoint
    assert err.dump_dir is not None and os.path.isdir(err.dump_dir)
    with open(os.path.join(err.dump_dir, INCIDENT_MANIFEST)) as f:
        manifest = json.load(f)
    assert manifest["reason"] == "halt"
    assert manifest["details"]["emergency_checkpoint"] == err.checkpoint_path
    assert os.path.isfile(os.path.join(err.dump_dir, RING_FILENAME))

    # telemetry log: schema-valid, carries the anomaly + halt incident and
    # the run_end marker (the teardown still flushed cleanly)
    log_path = os.path.join(builder.logs_filepath, tel.TELEMETRY_FILENAME)
    assert tel.validate_file(log_path) > 0
    recs = list(tel.iter_records(log_path))
    kinds = [r["kind"] for r in recs]
    assert "anomaly" in kinds and "incident" in kinds and "run_end" in kinds
    halt_incidents = [
        r for r in recs
        if r["kind"] == "incident" and r["reason"] == "halt"
    ]
    assert halt_incidents and halt_incidents[0]["path"] == err.dump_dir

    # `cli inspect` renders the anomaly timeline from the produced log —
    # through the jax-free dispatch path a laptop would use
    for sub in (["summary"], ["anomalies"], ["validate"]):
        out = subprocess.run(
            [_sys.executable, "-m", "howtotrainyourmamlpytorch_tpu.cli",
             "inspect", *sub, log_path],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, (sub, out.stderr[-2000:])
    out = subprocess.run(
        [_sys.executable, "-m", "howtotrainyourmamlpytorch_tpu.cli",
         "inspect", "anomalies", log_path],
        capture_output=True, text=True, timeout=120,
    )
    assert "anomaly" in out.stdout and "halt" in out.stdout
