"""Compute-only MXU channel padding (``pad_channels``) bit-exactness.

Padded input channels/rows are zeros that contribute exact zeros to every
contraction partial sum, and padded output channels are sliced off before
the bias (and therefore before any norm layer) — so under the shipping
'tile' rule the padded program must be BIT-exact with the unpadded one, not
merely allclose: forward, first-order inner gradients, and the second-order
structure the meta-gradient differentiates, in f32 and bf16, through every
conv lowering and through the full backbone (conv + batch-norm + linear
head).  The one caveat — pinned by its own test below — is that a very
large explicit multiple on a tiny layer can grow the contraction dim past
the backend's GEMM blocking threshold and reassociate the accumulation at
the ~1e-6 level; the tile rule's modest pads stay inside one block.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.models import vgg
from howtotrainyourmamlpytorch_tpu.ops import functional as F


def _rand(shape, seed=0, dtype=np.float32):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape).astype(np.float32)
    ).astype(dtype)


def test_pad_target_tile_rule():
    """The documented 'tile' quantization: next power of two, floored at the
    dtype sublane tile (8 f32 / 16 bf16), multiples of the 128-lane width
    beyond it — the flagship's 48 filters compute as 64."""
    f32, bf16 = jnp.float32, jnp.bfloat16
    assert F.pad_target(48, "tile", f32) == 64
    assert F.pad_target(48, "tile", bf16) == 64
    assert F.pad_target(3, "tile", f32) == 8
    assert F.pad_target(3, "tile", bf16) == 16
    assert F.pad_target(64, "tile", f32) == 64
    assert F.pad_target(100, "tile", f32) == 128
    assert F.pad_target(129, "tile", f32) == 256
    assert F.pad_target(128, "tile", bf16) == 128
    # explicit integer multiple and off
    assert F.pad_target(48, 32, f32) == 64
    assert F.pad_target(48, "off", f32) == 48
    with pytest.raises(ValueError, match="pad_channels"):
        F.pad_target(48, "bogus", f32)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("impl", ["lax", "im2col", "gemm"])
@pytest.mark.parametrize("mode", ["tile", 8])
def test_conv2d_padded_bit_exact(dtype, impl, mode):
    x = _rand((3, 9, 9, 5), 0, dtype)
    w = _rand((3, 3, 5, 7), 1)
    b = _rand((7,), 2)
    base = F.conv2d(x, w, b, 1, 1, impl=impl, pad_channels="off")
    padded = F.conv2d(x, w, b, 1, 1, impl=impl, pad_channels=mode)
    assert base.shape == padded.shape
    np.testing.assert_array_equal(np.asarray(base), np.asarray(padded))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_linear_padded_bit_exact(dtype):
    x = _rand((6, 48), 3, dtype)
    w = _rand((48, 5), 4)
    b = _rand((5,), 5)
    base = F.linear(x, w, b, pad_channels="off")
    padded = F.linear(x, w, b, pad_channels="tile")
    assert base.shape == padded.shape
    np.testing.assert_array_equal(np.asarray(base), np.asarray(padded))


def test_oversized_explicit_multiple_is_allclose():
    """Padding a 5-channel conv to a 32-multiple grows the contraction dim
    45 -> 288, which can cross the backend GEMM's K-blocking threshold and
    reassociate the float accumulation (observed 4.5e-6 on the threaded
    XLA:CPU backend) — equivalent to float noise, not bit-exact. The tile
    rule never pads this aggressively relative to the layer size."""
    x = _rand((3, 9, 9, 5), 0)
    w = _rand((3, 3, 5, 7), 1)
    base = F.conv2d(x, w, None, 1, 1, impl="gemm", pad_channels="off")
    padded = F.conv2d(x, w, None, 1, 1, impl="gemm", pad_channels=32)
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(padded), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("impl", ["lax", "im2col", "gemm"])
def test_conv2d_padded_gradients_bit_exact(impl):
    """First- and second-order derivatives of the padded op vs unpadded —
    the orders the bi-level step actually differentiates."""
    x = _rand((2, 8, 8, 4), 6)
    w = _rand((3, 3, 4, 6), 7)

    def first(pad):
        return jax.grad(
            lambda w_: jnp.sum(
                F.conv2d(x, w_, None, 1, 1, impl=impl, pad_channels=pad) ** 2
            )
        )(w)

    np.testing.assert_array_equal(
        np.asarray(first("off")), np.asarray(first("tile"))
    )

    def second(pad):
        def f(w_):
            g = jax.grad(
                lambda w2: jnp.sum(
                    F.conv2d(x, w2, None, 1, 1, impl=impl, pad_channels=pad)
                    ** 2
                )
            )(w_)
            return jnp.sum(jnp.tanh(g))

        return jax.grad(f)(w)

    np.testing.assert_array_equal(
        np.asarray(second("off")), np.asarray(second("tile"))
    )


@pytest.mark.parametrize("dtype_name", ["float32", "bfloat16"])
def test_backbone_padded_bit_exact_through_bn(tiny_cfg, dtype_name):
    """The full backbone — conv, slice-back, batch-norm on logical channels,
    linear head — padded vs unpadded: logits and BN running stats must be
    bit-identical (the slice-back happens BEFORE the norm sees anything)."""
    cfg_off = tiny_cfg.replace(pad_channels="off", compute_dtype=dtype_name)
    cfg_pad = tiny_cfg.replace(pad_channels="tile", compute_dtype=dtype_name)
    assert cfg_pad.resolved_pad_channels == "tile"
    params, bn = vgg.init(cfg_off, jax.random.PRNGKey(0))
    x = np.random.RandomState(1).randn(6, *cfg_off.im_shape).astype(np.float32)
    out_off, bn_off = vgg.apply(cfg_off, params, bn, x, 0, training=True)
    out_pad, bn_pad = vgg.apply(cfg_pad, params, bn, x, 0, training=True)
    np.testing.assert_array_equal(np.asarray(out_off), np.asarray(out_pad))
    for k in bn_off:
        np.testing.assert_array_equal(
            np.asarray(bn_off[k]), np.asarray(bn_pad[k]), err_msg=k
        )


@pytest.mark.slow
def test_train_step_padded_metrics_exact_grads_close(tiny_cfg, synthetic_batch):
    """Slow lane (compiles two full second-order steps); the layer-level
    bit-exactness tests above keep the padding rule pinned in the fast lane.

    One full second-order outer step with tile-rule channel padding on vs
    off: loss/accuracy bit-identical, meta-gradients equal to float noise.
    Compared at the gradient level per the repo convention (make_grads_fn):
    post-Adam weights amplify float-reordering noise on ~zero-gradient
    params (a conv bias under batch-norm) into O(lr) differences."""
    from howtotrainyourmamlpytorch_tpu.core import maml, msl

    cfg_off = tiny_cfg.replace(pad_channels="off")
    cfg_pad = tiny_cfg.replace(pad_channels="tile")
    x_s, y_s, x_t, y_t = synthetic_batch(cfg_off)
    w = jnp.asarray(
        msl.loss_weights_for(
            cfg_off.number_of_training_steps_per_iter, True, True, 0,
            cfg_off.multi_step_loss_num_epochs,
        )
    )
    s_off = maml.init_state(cfg_off)
    s_pad = maml.init_state(cfg_pad)
    step_off = jax.jit(maml.make_train_step(cfg_off, second_order=True))
    step_pad = jax.jit(maml.make_train_step(cfg_pad, second_order=True))
    _, m_off = step_off(s_off, x_s, y_s, x_t, y_t, w, 0.01)
    _, m_pad = step_pad(s_pad, x_s, y_s, x_t, y_t, w, 0.01)
    assert float(m_off["loss"]) == float(m_pad["loss"])
    assert float(m_off["accuracy"]) == float(m_pad["accuracy"])
    loss_off, g_off = jax.jit(maml.make_grads_fn(cfg_off, True))(
        s_off, x_s, y_s, x_t, y_t, w
    )
    loss_pad, g_pad = jax.jit(maml.make_grads_fn(cfg_pad, True))(
        s_pad, x_s, y_s, x_t, y_t, w
    )
    assert float(loss_off) == pytest.approx(float(loss_pad), rel=1e-6)
    for part in ("net", "lslr"):
        for k in g_off[part]:
            np.testing.assert_allclose(
                np.asarray(g_off[part][k]), np.asarray(g_pad[part][k]),
                atol=1e-5, rtol=1e-4, err_msg=f"{part}.{k}",
            )


def test_pad_channels_config_validation_and_resolution():
    cfg = MAMLConfig(dataset_name="omniglot_dataset")
    assert cfg.pad_channels == "auto"
    # tests run on the CPU backend (conftest) -> auto resolves to off
    assert cfg.resolved_pad_channels == "off"
    assert cfg.replace(pad_channels=64).resolved_pad_channels == 64
    assert cfg.replace(pad_channels="off").resolved_pad_channels == "off"
    assert cfg.replace(pad_channels="tile").resolved_pad_channels == "tile"
    # JSON configs may carry the multiple as a string
    assert MAMLConfig(pad_channels="64").pad_channels == 64
    with pytest.raises(ValueError, match="pad_channels"):
        MAMLConfig(pad_channels="sometimes")
    with pytest.raises(ValueError, match="pad_channels"):
        MAMLConfig(pad_channels=-8)
