"""Telemetry subsystem: JSONL/TensorBoard sinks + schema, the on-device
training-dynamics collection (bit-identity with telemetry off, shapes and
flush), the hang watchdog, and the end-to-end smoke run the CI
schema-validation job executes."""

import json
import os
import time

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu import telemetry as tel
from howtotrainyourmamlpytorch_tpu.core import partition
from howtotrainyourmamlpytorch_tpu.experiment.system import MAMLFewShotClassifier
from howtotrainyourmamlpytorch_tpu.telemetry import sinks as sinks_mod
from howtotrainyourmamlpytorch_tpu.telemetry.watchdog import Watchdog


def _batch(cfg, seed=0):
    from conftest import make_synthetic_batch

    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg, seed=seed)
    return x_s, x_t, y_s, y_t  # the facade's (x_s, x_t, y_s, y_t) order


# -- sinks + schema ---------------------------------------------------------


def test_jsonl_sink_schema_roundtrip(tiny_cfg, tmp_path):
    cfg = tiny_cfg.replace(telemetry_level="scalars")
    t = tel.Telemetry(cfg, str(tmp_path))
    assert t.enabled
    t.event("run_start", experiment_name="exp", telemetry_level="scalars",
            resume_iter=0)
    t.epoch_scalars(1, {"train_loss_mean": 1.25, "val_accuracy_mean": 0.5,
                        "note": "non-numeric is dropped from scalars"})
    t.event("stream", epoch=1, batches=4, assembly_ms_per_batch=1.0,
            stall_ms_per_batch=0.0, queue_depth_mean=1.5)
    t.event("checkpoint", epoch=1, path="/tmp/ckpt", also_latest=True)
    t.event("device_memory", epoch=1, store_bytes_expected=0)
    t.close()
    path = os.path.join(str(tmp_path), tel.TELEMETRY_FILENAME)
    assert tel.validate_file(path) == 6  # incl. the run_end marker
    recs = list(tel.iter_records(path))
    assert [r["kind"] for r in recs] == [
        "run_start", "epoch", "stream", "checkpoint", "device_memory",
        "run_end",
    ]
    epoch_rec = recs[1]
    assert epoch_rec["schema"] == tel.SCHEMA_VERSION
    assert epoch_rec["scalars"] == {
        "train_loss_mean": 1.25, "val_accuracy_mean": 0.5,
    }


def test_telemetry_off_is_noop(tiny_cfg, tmp_path):
    t = tel.Telemetry(tiny_cfg, str(tmp_path))  # telemetry_level='off'
    assert not t.enabled
    t.event("run_start", experiment_name="x", telemetry_level="off",
            resume_iter=0)
    t.epoch_scalars(0, {"a": 1.0})
    t.dynamics(0, 1, {})
    t.close()
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           tel.TELEMETRY_FILENAME))


def test_telemetry_disabled_on_non_primary(tiny_cfg, tmp_path):
    cfg = tiny_cfg.replace(telemetry_level="scalars")
    t = tel.Telemetry(cfg, str(tmp_path), is_primary=False)
    assert not t.enabled
    t.close()
    assert not os.path.exists(os.path.join(str(tmp_path),
                                           tel.TELEMETRY_FILENAME))


def test_validate_record_rejects_bad_records():
    good = {"schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "run_end"}
    tel.validate_record(good)
    # pre-MIN versions and non-integer versions mean corruption, not the
    # future — rejected (NEWER versions are tolerated, tested below)
    for bad_ver in (0, -1, 1.5, "2", None, True):
        with pytest.raises(ValueError, match="schema version"):
            tel.validate_record({**good, "schema": bad_ver})
    with pytest.raises(ValueError, match="unknown telemetry record kind"):
        tel.validate_record({**good, "kind": "bogus"})
    with pytest.raises(ValueError, match="missing required fields"):
        tel.validate_record(
            {"schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "epoch"}
        )
    with pytest.raises(ValueError, match="'ts'"):
        tel.validate_record(
            {"schema": tel.SCHEMA_VERSION, "kind": "run_end"}
        )
    # dynamics payload types are enforced (the acceptance surface)
    dyn = {
        "schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "dynamics",
        "iter_start": 0, "num_iters": 1, "support_losses": [1.0],
        "target_losses": [1.0], "grad_norms": {"w": [1.0]},
        "lslr": {"w": [0.1]}, "msl_weights": [1.0],
    }
    tel.validate_record(dyn)
    with pytest.raises(ValueError, match="grad_norms"):
        tel.validate_record({**dyn, "grad_norms": {}})
    with pytest.raises(ValueError, match="support_losses"):
        tel.validate_record({**dyn, "support_losses": 1.0})


def test_validate_file_names_offending_line(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"schema": tel.SCHEMA_VERSION, "ts": 1.0,
                            "kind": "run_end"}) + "\n")
        f.write(json.dumps({"schema": tel.SCHEMA_VERSION, "ts": 1.0,
                            "kind": "nope"}) + "\n")
    with pytest.raises(ValueError, match="record 2"):
        tel.validate_file(path)


def test_tensorboard_sink_degrades_without_writer(tiny_cfg, tmp_path,
                                                  monkeypatch):
    """No SummaryWriter importable -> the sink disables itself and the
    facade keeps working (JSONL only) — optional-import degradation."""

    def no_writer():
        raise ImportError("no tensorboard writer in this environment")

    monkeypatch.setattr(sinks_mod, "_import_summary_writer", no_writer)
    cfg = tiny_cfg.replace(
        telemetry_level="scalars", telemetry_tensorboard=True
    )
    t = tel.Telemetry(cfg, str(tmp_path))
    assert t.enabled
    assert t.tensorboard is not None and not t.tensorboard.enabled
    t.epoch_scalars(0, {"train_loss_mean": 1.0})
    t.close()
    assert tel.validate_file(
        os.path.join(str(tmp_path), tel.TELEMETRY_FILENAME)
    ) == 2


def test_tensorboard_sink_writes_event_files(tiny_cfg, tmp_path):
    pytest.importorskip("tensorboardX")
    cfg = tiny_cfg.replace(
        telemetry_level="scalars", telemetry_tensorboard=True
    )
    t = tel.Telemetry(cfg, str(tmp_path))
    assert t.tensorboard is not None and t.tensorboard.enabled
    t.epoch_scalars(0, {"train_loss_mean": 1.0, "val_accuracy_mean": 0.25})
    t.close()
    tb_dir = os.path.join(str(tmp_path), "tensorboard")
    assert any("tfevents" in name for name in os.listdir(tb_dir))


# -- on-device dynamics collection ------------------------------------------


def test_dynamics_off_vs_on_metrics_bit_identical(tiny_cfg):
    """telemetry_level='dynamics' must not change a single bit of the
    training metrics or the learned parameters (the collection is aux-only,
    stop_gradient'ed, and reduced outside the differentiated graph)."""
    cfg_on = tiny_cfg.replace(telemetry_level="dynamics")
    m_off = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
    m_on = MAMLFewShotClassifier(cfg_on, use_mesh=False)
    for step in range(2):
        batch = _batch(tiny_cfg, seed=step)
        l_off = m_off.run_train_iter(batch, epoch=0)
        l_on = m_on.run_train_iter(batch, epoch=0)
        assert "dynamics" not in l_off
        assert "dynamics" in l_on
        np.testing.assert_array_equal(
            np.asarray(l_off["loss"]), np.asarray(l_on["loss"])
        )
        np.testing.assert_array_equal(
            np.asarray(l_off["accuracy"]), np.asarray(l_on["accuracy"])
        )
    for key in m_off.state.net:
        np.testing.assert_array_equal(
            np.asarray(m_off.state.net[key]), np.asarray(m_on.state.net[key]),
            err_msg=key,
        )


def test_dynamics_payload_shapes(tiny_cfg):
    cfg = tiny_cfg.replace(telemetry_level="dynamics")
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    losses = model.run_train_iter(_batch(cfg), epoch=0)
    dyn = losses["dynamics"]
    n_steps = cfg.number_of_training_steps_per_iter
    adapted = sorted(
        k for k in model.state.net if partition.is_inner_adapted(cfg, k)
    )
    assert np.asarray(dyn["support_losses"]).shape == (n_steps,)
    assert np.asarray(dyn["target_losses"]).shape == (n_steps,)
    assert np.asarray(dyn["msl_weights"]).shape == (n_steps,)
    assert sorted(dyn["grad_norms"]) == adapted
    assert sorted(dyn["lslr"]) == adapted
    for name in adapted:
        assert np.asarray(dyn["grad_norms"][name]).shape == (n_steps,)
        assert np.all(np.asarray(dyn["grad_norms"][name]) >= 0)
        # the reference's (num_inner_steps + 1,) LSLR shape
        assert np.asarray(dyn["lslr"][name]).shape == (n_steps + 1,)
    # MSL weights mirror the host schedule at epoch 0
    from howtotrainyourmamlpytorch_tpu.core import msl

    np.testing.assert_allclose(
        np.asarray(dyn["msl_weights"]),
        msl.loss_weights_for(
            n_steps, cfg.use_multi_step_loss_optimization, True, 0,
            cfg.multi_step_loss_num_epochs,
        ),
    )


def test_dynamics_chunked_dispatch_stacks(tiny_cfg):
    """steps_per_dispatch>1: dynamics come back (k, ...)-stacked from the
    fused scan — one record per dispatch, zero extra device syncs."""
    cfg = tiny_cfg.replace(telemetry_level="dynamics")
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    batches = [_batch(cfg, seed=s) for s in range(3)]
    losses = model.run_train_iters(batches, epoch=0)
    dyn = losses["dynamics"]
    n_steps = cfg.number_of_training_steps_per_iter
    assert np.asarray(dyn["support_losses"]).shape == (3, n_steps)
    assert np.asarray(dyn["target_losses"]).shape == (3, n_steps)
    for name, v in dyn["grad_norms"].items():
        assert np.asarray(v).shape == (3, n_steps), name
    for name, v in dyn["lslr"].items():
        assert np.asarray(v).shape == (3, n_steps + 1), name


def test_eval_metrics_unaffected_by_dynamics_level(tiny_cfg):
    cfg = tiny_cfg.replace(telemetry_level="dynamics")
    m_off = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
    m_on = MAMLFewShotClassifier(cfg, use_mesh=False)
    batch = _batch(tiny_cfg)
    l_off, _ = m_off.run_validation_iter(batch)
    l_on, _ = m_on.run_validation_iter(batch)
    assert "dynamics" not in l_on
    np.testing.assert_array_equal(
        np.asarray(l_off["loss"]), np.asarray(l_on["loss"])
    )


def test_jsonable_sanitizes_non_finite_floats():
    """A diverging run (NaN loss) must still emit spec-strict JSON lines:
    non-finite floats become null, never bare NaN/Infinity tokens."""
    import ml_dtypes

    out = sinks_mod._jsonable({
        "a": float("nan"),
        "b": [1.0, float("inf")],
        "c": np.array([1.0, np.nan, -np.inf]),
        "d": np.float32("nan"),
        "e": np.array([[1.0, 2.0]]),
        # bfloat16 (compute_dtype='bfloat16' dynamics) is dtype kind 'V',
        # which a naive issubdtype(floating) finiteness gate would skip
        "f": np.array([1.5, np.nan], dtype=ml_dtypes.bfloat16),
        "g": np.array([1, 2], dtype=np.int32),
    })
    assert out == {
        "a": None, "b": [1.0, None], "c": [1.0, None, None], "d": None,
        "e": [[1.0, 2.0]], "f": [1.5, None], "g": [1, 2],
    }
    json.dumps(out, allow_nan=False)  # strict serialization succeeds


def _stub_builder(tmp_path, cfg):
    """A minimal stand-in exposing exactly the state
    ``pack_and_save_metrics`` reads, with the real builder methods bound —
    so the CSV header-alignment logic is tested without a dataset."""
    import time as _time
    from types import SimpleNamespace

    from howtotrainyourmamlpytorch_tpu.experiment.builder import ExperimentBuilder
    from howtotrainyourmamlpytorch_tpu.resilience import RetryPolicy
    from howtotrainyourmamlpytorch_tpu.utils.profiling import StepTimer

    stub = SimpleNamespace(
        cfg=cfg,
        retry=RetryPolicy(max_attempts=1),
        logs_filepath=str(tmp_path),
        step_timer=StepTimer(),
        state={},
        epoch=1,
        create_summary_csv=False,
        _csv_keys=None,
        is_primary=True,
        start_time=_time.time(),
        telemetry=tel.Telemetry(cfg.replace(telemetry_level="off"),
                                str(tmp_path)),
        data=SimpleNamespace(pop_stream_stats=lambda: {
            "assembly_s": 0.01, "stall_s": 0.0, "depth_sum": 2.0,
            "batches": 2,
        }),
        model=SimpleNamespace(
            device_memory_stats=lambda: {"store_bytes_expected": 0}
        ),
        _dyn_pending=[],
        health_monitor=None,
        flight_recorder=None,
        _log=lambda msg: None,
    )
    for name in ("pack_and_save_metrics", "_stream_metrics",
                 "_flush_dynamics", "_existing_csv_header", "_write_stats"):
        setattr(stub, name, getattr(ExperimentBuilder, name).__get__(stub))
    return stub


def test_resumed_csv_rows_align_to_old_header(tiny_cfg, tmp_path):
    """Resuming a run whose CSV header predates newly-grown metric columns
    must append rows in the OLD header's column order (extra metrics go to
    telemetry/JSON only) — never positionally-shifted longer rows."""
    import csv

    from howtotrainyourmamlpytorch_tpu.utils.storage import (
        load_statistics,
        save_statistics,
    )

    old_header = ["train_loss_mean", "val_accuracy_mean", "epoch",
                  "epoch_run_time"]
    save_statistics(str(tmp_path), old_header, create=True)
    save_statistics(str(tmp_path), [0.9, 0.5, 1, 12.0])

    stub = _stub_builder(tmp_path, tiny_cfg)
    stub.epoch = 2
    stub.pack_and_save_metrics(
        {"train_loss_mean": 0.8},
        {"val_accuracy_mean": 0.6},
    )
    with open(os.path.join(str(tmp_path), "summary_statistics.csv")) as f:
        rows = list(csv.reader(f))
    assert rows[0] == old_header
    assert all(len(r) == len(old_header) for r in rows[1:])
    data = load_statistics(str(tmp_path))
    assert data["epoch"] == ["1", "2"]
    assert data["val_accuracy_mean"] == ["0.5", "0.6"]
    # the stream columns this build grew were dropped from the CSV
    assert "stream_assembly_ms_per_batch" not in data


def test_fresh_csv_includes_stream_columns(tiny_cfg, tmp_path):
    stub = _stub_builder(tmp_path, tiny_cfg)
    stub.create_summary_csv = True
    stub.pack_and_save_metrics(
        {"train_loss_mean": 0.8}, {"val_accuracy_mean": 0.6}
    )
    from howtotrainyourmamlpytorch_tpu.utils.storage import load_statistics

    data = load_statistics(str(tmp_path))
    assert "stream_assembly_ms_per_batch" in data
    assert "stream_queue_depth_mean" in data
    assert data["epoch"] == ["1"]


# -- watchdog ---------------------------------------------------------------


def test_watchdog_clock_starts_at_start_not_construction():
    """Construction-to-start delay must not count toward the stall timer
    (a builder can exist long before run_experiment begins beating)."""
    records = []
    wd = Watchdog(0.3, on_stall=records.append, poll_s=0.05)
    time.sleep(0.5)  # longer than the timeout, before start()
    with wd:
        time.sleep(0.1)  # well under the timeout after start()
    assert records == []


def test_watchdog_fires_on_stall():
    records = []
    wd = Watchdog(0.2, on_stall=records.append, poll_s=0.05)
    with wd:
        wd.beat("train_dispatch")
        deadline = time.monotonic() + 5.0
        while not records and time.monotonic() < deadline:
            time.sleep(0.05)
    assert len(records) == 1, "watchdog should fire exactly once per stall"
    rec = records[0]
    assert rec["stage"] == "train_dispatch"
    assert rec["seconds_since_progress"] > 0.2
    assert rec["beat_count"] == 1
    # the stack snapshot names this (blocked) main thread
    assert any("MainThread" in k for k in rec["stacks"])
    assert any("sleep" in v or "wait" in v for v in rec["stacks"].values())


def test_watchdog_stays_quiet_on_progress():
    records = []
    wd = Watchdog(0.5, on_stall=records.append, poll_s=0.05)
    with wd:
        end = time.monotonic() + 1.2
        while time.monotonic() < end:
            wd.beat("train_dispatch")
            time.sleep(0.05)
    assert records == []


def test_watchdog_rearms_after_recovery():
    records = []
    wd = Watchdog(0.15, on_stall=records.append, poll_s=0.03)
    with wd:
        wd.beat("stall_one")
        time.sleep(0.4)  # first stall fires once
        wd.beat("stall_two")  # recovery re-arms
        time.sleep(0.4)  # second stall fires once
    assert [r["stage"] for r in records] == ["stall_one", "stall_two"]


def test_watchdog_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        Watchdog(0.0, on_stall=lambda r: None)


# -- end-to-end smoke (the CI schema-validation job) ------------------------


def test_builder_telemetry_e2e_smoke(tmp_path):
    """A tiny telemetry-enabled train through ExperimentBuilder: the JSONL
    log validates against the schema and contains per-inner-step losses,
    per-layer grad norms and LSLR values for every train dispatch."""
    from test_e2e_presplit import _write_presplit_rgb

    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
    from howtotrainyourmamlpytorch_tpu.data.loader import MetaLearningDataLoader
    from howtotrainyourmamlpytorch_tpu.experiment.builder import ExperimentBuilder

    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root))
    cfg = MAMLConfig(
        experiment_name=str(tmp_path / "exp_tel"),
        dataset_name="mini_imagenet_full_size",
        dataset_path=str(data_root),
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=10, image_width=10, image_channels=3,
        num_classes_per_set=2, num_samples_per_class=1, num_target_samples=1,
        batch_size=2, cnn_num_filters=4, num_stages=2, max_pooling=True,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True, second_order=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_epochs=2, total_iter_per_epoch=4, num_evaluation_tasks=4,
        total_epochs_before_pause=100,
        num_dataprovider_workers=2, cache_dir=str(tmp_path / "cache"),
        use_mmap_cache=True, use_remat=False, seed=0,
        steps_per_dispatch=2,  # fused dispatch: dynamics arrive (k,)-stacked
        eval_batches_per_dispatch=2,
        telemetry_level="dynamics",
        tracing_level="on",  # schema-v10 spans ride the same log
        watchdog_timeout_s=120.0,  # enabled, but must stay quiet
    )
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    builder = ExperimentBuilder(
        cfg, model, MetaLearningDataLoader,
        experiment_root=str(tmp_path), verbose=False,
    )
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0

    log_path = os.path.join(builder.logs_filepath, tel.TELEMETRY_FILENAME)
    assert tel.validate_file(log_path) > 0
    recs = list(tel.iter_records(log_path))
    kinds = [r["kind"] for r in recs]
    for expected in ("run_start", "epoch", "stream", "dispatch",
                     "checkpoint", "device_memory", "dynamics", "run_end"):
        assert expected in kinds, f"missing {expected!r} records"
    assert "watchdog_stall" not in kinds
    # every train dispatch produced one dynamics record: 2 epochs x 4 iters
    # at steps_per_dispatch=2 -> 4 dispatches
    dyn_recs = [r for r in recs if r["kind"] == "dynamics"]
    assert len(dyn_recs) == 4
    assert [r["iter_start"] for r in dyn_recs] == [0, 2, 4, 6]
    n_steps = cfg.number_of_training_steps_per_iter
    for rec in dyn_recs:
        assert rec["num_iters"] == 2
        arr = np.asarray(rec["support_losses"])
        assert arr.shape == (2, n_steps) and np.all(np.isfinite(arr))
        assert np.asarray(rec["target_losses"]).shape == (2, n_steps)
        assert rec["grad_norms"] and rec["lslr"]
        for norms in rec["grad_norms"].values():
            assert np.asarray(norms).shape == (2, n_steps)
        for lrs in rec["lslr"].values():
            assert np.asarray(lrs).shape == (2, n_steps + 1)
        assert np.asarray(rec["msl_weights"]).shape == (2, n_steps)
    # the per-epoch dispatch record carries the schema-v7 overlap fields:
    # the boundary train-summary ran under the in-flight eval tail
    # (overlap_ms measured) and the phase-transition lag blocks were
    # skipped at both edges of each boundary
    disp_recs = [r for r in recs if r["kind"] == "dispatch"]
    assert disp_recs
    for rec in disp_recs:
        assert rec.get("accum_steps") == 1
        assert isinstance(rec.get("boundary_overlaps"), int)
        assert isinstance(rec.get("overlap_ms"), (int, float))
    assert sum(r["boundary_overlaps"] for r in disp_recs) > 0
    # schema-v10 causal tracing: the run emitted span records for every
    # train dispatch / eval chunk / epoch summary / checkpoint, all under
    # one run-scoped trace id, with the epoch_summary span present (the
    # PR 11 boundary overlap as an interval on the timeline)
    span_recs = [r for r in recs if r["kind"] == "span"]
    span_names = {r["name"] for r in span_recs}
    for expected in ("train_dispatch", "eval_chunk", "epoch_summary",
                     "eval_sync", "checkpoint"):
        assert expected in span_names, f"missing {expected!r} spans"
    assert len({r["trace_id"] for r in span_recs}) == 1
    # 2 epochs x 4 iters at steps_per_dispatch=2 -> 4 train dispatches
    assert sum(1 for r in span_recs if r["name"] == "train_dispatch") == 4
    for rec in span_recs:
        assert rec["dur_ms"] >= 0 and rec["start_ms"] > 0
    # the data producer emitted its pipeline spans on the same trace
    assert "sample" in span_names and "stack" in span_names
    # per-epoch records carry the CSV row's scalars + the stream stats
    epoch_recs = [r for r in recs if r["kind"] == "epoch"]
    assert len(epoch_recs) == 2
    for rec in epoch_recs:
        assert "train_loss_mean" in rec["scalars"]
        assert "val_accuracy_mean" in rec["scalars"]
        assert "stream_assembly_ms_per_batch" in rec["scalars"]
    # the CSV grew the stream columns and stays row-consistent
    import csv

    with open(os.path.join(builder.logs_filepath,
                           "summary_statistics.csv")) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 2
    assert "stream_assembly_ms_per_batch" in rows[0]


def test_config_validates_telemetry_knobs(tiny_cfg):
    with pytest.raises(ValueError, match="telemetry_level"):
        tiny_cfg.replace(telemetry_level="bogus")
    with pytest.raises(ValueError, match="watchdog_timeout_s"):
        tiny_cfg.replace(watchdog_timeout_s=-1.0)
    with pytest.raises(ValueError, match="profile_start_step"):
        tiny_cfg.replace(profile_start_step=-2)
    assert tiny_cfg.replace(telemetry_level="scalars").telemetry_level == "scalars"
    with pytest.raises(ValueError, match="tracing_level"):
        tiny_cfg.replace(tracing_level="bogus")
    with pytest.raises(ValueError, match="tracing_level='on' requires"):
        tiny_cfg.replace(tracing_level="on", telemetry_level="off")
    assert tiny_cfg.replace(
        telemetry_level="scalars", tracing_level="on"
    ).tracing_level == "on"


# -- schema forward compatibility (v2) --------------------------------------


def test_validate_accepts_v1_records():
    """Every v1 record validates unchanged under the v2 validator — v2 is
    pure additions (see the schema version history)."""
    tel.validate_record({"schema": 1, "ts": 1.0, "kind": "run_end"})
    tel.validate_record({
        "schema": 1, "ts": 1.0, "kind": "epoch", "epoch": 0,
        "scalars": {"train_loss_mean": 1.0},
    })


def test_validate_tolerates_newer_schema_versions():
    """Records stamped with a FUTURE version get envelope-only checks:
    unknown kinds and unknown fields must never make an old reader reject
    a log it can still mostly use."""
    tel.validate_record({
        "schema": tel.SCHEMA_VERSION + 1, "ts": 1.0,
        "kind": "quantum_flux", "novel_field": [1, 2, 3],
    })
    # the envelope is still enforced on future records
    with pytest.raises(ValueError, match="'ts'"):
        tel.validate_record({
            "schema": tel.SCHEMA_VERSION + 1, "kind": "quantum_flux",
        })
    with pytest.raises(ValueError, match="'kind'"):
        tel.validate_record({"schema": tel.SCHEMA_VERSION + 1, "ts": 1.0})
    # ...while the same unknown kind at the CURRENT version is rejected
    with pytest.raises(ValueError, match="unknown telemetry record kind"):
        tel.validate_record({
            "schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "quantum_flux",
        })


def test_validate_file_accepts_future_schema_fixture():
    """The pinned mixed-version fixture: v1 records, an unknown v5 kind,
    and v99 records that dropped/renamed required fields all pass — the
    forward-compatibility contract, frozen as a file so a validator
    refactor can't silently tighten it."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_future_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 5


def test_validate_file_accepts_v2_era_fixture():
    """The pinned v2-era log (written before the v3 `retry`/`preemption`
    kinds existed) validates unchanged under the v3 validator — the
    backward half of the version contract: v3 is purely additive."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v2_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 6


def test_v3_resilience_record_kinds_validate():
    """The schema v3 additions: one record of each new kind, built through
    the sink's make_record, passes strict validation."""
    tel.validate_record(tel.make_record(
        "retry", site="ckpt_save", attempt=1, max_attempts=3,
        error="InjectedFaultError('x')", backoff_s=0.5,
    ))
    tel.validate_record(tel.make_record(
        "preemption", iter=55, signal=15,
        checkpoint="saved_models/train_model_emergency",
    ))


def test_validate_file_accepts_v3_era_fixture():
    """The pinned v3-era log (written before the v4 `retrace` kind
    existed) validates unchanged under the v4 validator — the backward
    half of the version contract: v4 is purely additive."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v3_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 5


def test_v4_retrace_record_kind_validates():
    """The schema v4 addition: a `retrace` record built through the
    sink's make_record passes strict validation, and one missing its
    required fields is rejected."""
    tel.validate_record(tel.make_record(
        "retrace", iter=12, site="train_step[so=1]",
        signature="a1b2c3d4e5f60708", n_signatures=2,
    ))
    with pytest.raises(ValueError, match="missing required fields"):
        tel.validate_record({
            "schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "retrace",
            "iter": 12,
        })


def test_validate_file_accepts_v4_era_fixture():
    """The pinned v4-era log (written before the v5 `analysis` kind
    existed) validates unchanged under the v5 validator — the backward
    half of the version contract: v5 is purely additive."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v4_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 6


def test_v5_analysis_record_kind_validates():
    """The schema v5 addition: an `analysis` record (the build-time audit
    summary incl. the SPMD mesh and roofline payload) built through the
    sink's make_record passes strict validation; one missing its required
    fields is rejected."""
    tel.validate_record(tel.make_record(
        "analysis", programs=12, violations=0, mesh="1x8",
        roofline={
            "program": "train_step[so=1]", "bound": "memory",
            "predicted_hfu": 0.24, "predicted_mfu": None,
            "flops_per_task": 2.7e6,
        },
    ))
    # single-device runs carry no mesh/roofline — still valid
    tel.validate_record(tel.make_record(
        "analysis", programs=6, violations=1, mesh=None, roofline=None,
    ))
    with pytest.raises(ValueError, match="missing required fields"):
        tel.validate_record({
            "schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "analysis",
            "programs": 6,
        })


def test_validate_file_accepts_v5_era_fixture():
    """The pinned v5-era log (written before the v6 `elastic` kind
    existed) validates unchanged under the v6 validator — the backward
    half of the version contract: v6 is purely additive."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v5_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 7


def test_v6_elastic_record_kind_validates():
    """The schema v6 addition: `elastic` records (coordinated drain
    protocol events + topology-change resume markers) built through the
    sink's make_record pass strict validation; one missing its required
    field is rejected."""
    tel.validate_record(tel.make_record(
        "elastic", event="drain_commit", iter=6, drain_iter=8,
        signal=15, requested_by=1,
    ))
    tel.validate_record(tel.make_record(
        "elastic", event="resume", old_process_count=2,
        new_process_count=3, iter=4, episode_cursor=24,
    ))
    with pytest.raises(ValueError, match="missing required fields"):
        tel.validate_record({
            "schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "elastic",
            "iter": 6,
        })


def test_validate_file_accepts_v6_era_fixture():
    """The pinned v6-era log (written before the v7 dispatch-overlap
    fields existed) validates unchanged under the v7 validator — the
    backward half of the version contract: v7 is purely additive."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v6_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 7


def test_v7_dispatch_overlap_fields_validate():
    """The schema v7 addition: `dispatch` records may carry the
    epoch-boundary overlap fields (overlap_ms / boundary_overlaps /
    accum_steps) — optional, so a v7 record without them (a run whose
    boundary never overlapped) and a pre-v7 record both stay valid."""
    tel.validate_record(tel.make_record(
        "dispatch", epoch=3, train_step_time_ms=41.0,
        overlap_ms=12.5, boundary_overlaps=2, accum_steps=4,
    ))
    tel.validate_record(tel.make_record(
        "dispatch", epoch=3, train_step_time_ms=41.0,
        overlap_ms=None, boundary_overlaps=0, accum_steps=1,
    ))
    tel.validate_record(tel.make_record(
        "dispatch", epoch=3, train_step_time_ms=41.0,
    ))


def test_validate_file_accepts_v7_era_fixture():
    """The pinned v7-era log (written before the v8 `serving` kind
    existed) validates unchanged under the v8 validator — the backward
    half of the version contract: v8 is purely additive."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v7_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 7


def test_v8_serving_record_kind_validates():
    """The schema v8 addition: `serving` records (the adapt-on-request
    engine) — per-dispatch latency records and the p50/p95 rollup both
    round-trip through make_record and validate."""
    rec = tel.make_record(
        "serving", event="dispatch", tenants=3, bucket=4, shots=1,
        queue_ms=0.8, adapt_ms=4.2,
    )
    tel.validate_record(rec)
    assert rec["schema"] == tel.SCHEMA_VERSION and rec["kind"] == "serving"
    tel.validate_record(tel.make_record(
        "serving", event="rollup", dispatches=12, tenants=31,
        adapt_ms_p50=4.1, adapt_ms_p95=9.9, tenants_per_sec=120.5,
        retraces=0,
    ))
    with pytest.raises(ValueError, match="missing required fields"):
        tel.validate_record({
            "schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "serving",
        })


def test_validate_file_accepts_v8_era_fixture():
    """The pinned v8-era log (written before the v9 serving fast-path
    fields existed) validates unchanged under the v9 validator — the
    backward half of the version contract: v9 is purely additive."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v8_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 6


def test_v9_serving_fast_path_fields_validate():
    """The schema v9 additions: serving dispatch records with the ingest
    / cache fields, the event='warmup' shape, and the extended rollup
    all round-trip through make_record and validate."""
    tel.validate_record(tel.make_record(
        "serving", event="dispatch", tenants=3, bucket=4, shots=1,
        queue_ms=0.8, adapt_ms=4.2, program="predict", ingest="uint8",
        ingest_bytes=1536, cache_hits=3,
    ))
    tel.validate_record(tel.make_record(
        "serving", event="warmup", mode="artifacts", warmup_ms=312.0,
        xla_compiles=0, programs=8, ingest="f32",
    ))
    tel.validate_record(tel.make_record(
        "serving", event="rollup", dispatches=12, tenants=31,
        adapt_ms_p50=4.1, adapt_ms_p95=9.9, tenants_per_sec=120.5,
        retraces=0, ingest="index", h2d_bytes_per_dispatch=412.0,
        cache_hit_rate=0.62,
    ))


def test_validate_file_accepts_v9_era_fixture():
    """The pinned v9-era log (the fast-path serving fields and warmup
    shape of the PREVIOUS schema) validates unchanged under v10."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v9_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 7


def test_v10_span_record_kind_validates():
    """The schema v10 span kind: make_record round-trips with the full
    field set (parent/attrs optional), and a span missing its required
    interval fields is rejected."""
    rec = tel.make_record(
        "span", name="dispatch", cat="serving",
        trace_id="ab12cd34ef567890", span_id="s000042",
        parent_id="s000041", start_ms=10321.5, dur_ms=4.25,
        tid="serving-batcher",
        attrs={"program": "adapt", "bucket": 4, "shots": 1},
    )
    assert rec["schema"] == tel.SCHEMA_VERSION and rec["kind"] == "span"
    tel.validate_record(rec)
    json.dumps(rec, allow_nan=False)
    # minimal span (no parent, no attrs) also validates
    tel.validate_record(tel.make_record(
        "span", name="train_dispatch", cat="train",
        trace_id="ab12cd34ef567890", span_id="s000001",
        start_ms=1.0, dur_ms=0.5,
    ))
    with pytest.raises(ValueError, match="missing required fields"):
        tel.validate_record({
            "schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "span",
            "name": "dispatch", "cat": "serving",
        })


def test_v10_serving_decomposition_fields_validate():
    """The v10 serving-dispatch decomposition fields (batch/dispatch/
    sync) are pure additions: the record validates with and without."""
    tel.validate_record(tel.make_record(
        "serving", event="dispatch", tenants=2, bucket=2, shots=1,
        queue_ms=0.5, adapt_ms=4.0, program="adapt", ingest="f32",
        ingest_bytes=2048, cache_hits=0,
        batch_ms=0.2, dispatch_ms=3.1, sync_ms=0.9,
    ))


# -- non-finite masking is counted, not silent (sinks.make_record) ----------


def test_make_record_counts_masked_nonfinite_values():
    rec = tel.make_record(
        "anomaly", iter=3, reason="nonfinite_loss",
        value=float("nan"), threshold=0.0,
        probes={"loss": float("inf"), "grad_norm": 1.0},
    )
    assert rec["value"] is None  # masked for spec-strict JSON...
    assert rec["nonfinite_count"] == 2  # ...but counted, per field
    assert rec["nonfinite_fields"] == {"value": 1, "probes": 1}
    json.dumps(rec, allow_nan=False)
    tel.validate_record(rec)


def test_make_record_counts_per_array_nonfinites():
    """Array payloads (the dynamics stacks) report per-field counts —
    'which stack went NaN, and how badly' is answerable from JSONL."""
    rec = tel.make_record(
        "dynamics", iter_start=0, num_iters=2,
        support_losses=np.array([1.0, np.nan, np.inf]),
        target_losses=np.array([1.0, 2.0, 3.0]),
        grad_norms={"layer0": np.array([np.nan, np.nan])},
        lslr={"layer0": [0.1]},
        msl_weights=[1.0],
    )
    assert rec["nonfinite_count"] == 4
    assert rec["nonfinite_fields"] == {
        "support_losses": 2, "grad_norms": 2,
    }
    tel.validate_record(rec)


def test_make_record_omits_counts_when_all_finite():
    rec = tel.make_record("epoch", epoch=0, scalars={"loss": 1.0})
    assert "nonfinite_count" not in rec
    assert "nonfinite_fields" not in rec


# -- schema v11: multi-replica serving (replica_id + rollover) ---------------


def test_validate_file_accepts_v10_era_fixture():
    """The pinned v10-era log (the causal-tracing span shape and the
    serving latency decomposition of the PREVIOUS schema) validates
    unchanged under v11 — pure addition, nothing tightened."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v10_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 8


def test_v11_rollover_record_validates():
    """The v11 rollover shape (serving/refresh.py): one replica's
    zero-downtime checkpoint swap, full field set through make_record."""
    rec = tel.make_record(
        "serving", event="rollover", replica_id=1, old_iter=500,
        new_iter=750, standby_warmup_s=2.125, standby_warmup_mode="artifacts",
        swap_ms=0.031, xla_compiles_at_swap=0, rollover_s=2.5,
    )
    assert rec["schema"] == tel.SCHEMA_VERSION
    tel.validate_record(rec)
    json.dumps(rec, allow_nan=False)


def test_v11_replica_id_rides_serving_records():
    """replica_id is a pure addition on every serving shape: dispatch /
    rollup records validate with it AND without it (single-engine logs
    are unchanged — the field is simply absent)."""
    tel.validate_record(tel.make_record(
        "serving", event="dispatch", tenants=2, bucket=2, shots=1,
        queue_ms=0.5, adapt_ms=4.0, program="adapt", ingest="f32",
        ingest_bytes=2048, cache_hits=0, replica_id=3,
    ))
    tel.validate_record(tel.make_record(
        "serving", event="rollup", dispatches=4, tenants=8,
        adapt_ms_p50=3.0, adapt_ms_p95=6.0, tenants_per_sec=99.0,
        retraces=0, replica_id=0,
    ))
    tel.validate_record(tel.make_record(
        "serving", event="dispatch", tenants=2, bucket=2, shots=1,
        queue_ms=0.5, adapt_ms=4.0, program="adapt", ingest="f32",
    ))


# -- schema v12: serving SLO observability (slo kind + deadline shape) -------


def test_validate_file_accepts_v11_era_fixture():
    """The pinned v11-era log (replica_id-tagged serving records and the
    rollover shape of the PREVIOUS schema) validates unchanged under
    v12 — pure addition, nothing tightened."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v11_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 7


def test_v12_slo_record_round_trips():
    """The slo kind (SLOTracker.summary through make_record): full field
    set validates, JSON round-trips, and the required-field floor
    (target_ms / requests / missed) is enforced."""
    rec = tel.make_record(
        "slo", target_ms=50.0, availability=0.99, error_budget=0.01,
        requests=120, missed=3, miss_rate=0.025,
        burn_rates={"60": 2.5, "300": 1.1, "3600": None},
        worst_burn_window_s=60.0, worst_burn_rate=2.5,
        per_replica={"replica=\"0\"": {"requests": 60, "missed": 1}},
    )
    assert rec["schema"] == tel.SCHEMA_VERSION and rec["kind"] == "slo"
    tel.validate_record(rec)
    assert json.loads(json.dumps(rec, allow_nan=False)) == rec
    with pytest.raises(ValueError, match="missing required fields"):
        tel.validate_record({
            "schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "slo",
            "target_ms": 50.0,
        })


def test_v12_deadline_record_validates():
    """The serving event='deadline' shape: one resolved deadline-carrying
    request with its slack/miss verdict and the stage attribution."""
    rec = tel.make_record(
        "serving", event="deadline", tenant_id="t-042", shots=1,
        deadline_ms=50.0, slack_ms=-12.4, missed=True, e2e_ms=62.4,
        queue_ms=55.0, route_ms=0.1, batch_ms=0.8, dispatch_ms=1.9,
        sync_ms=4.7, replica_id=1,
    )
    assert rec["schema"] == tel.SCHEMA_VERSION
    tel.validate_record(rec)
    json.dumps(rec, allow_nan=False)


def test_v12_histogram_bearing_rollup_round_trips():
    """The rollup's v12 honesty/distribution fields (window_dropped +
    the sparse LogHistogram dicts) ride make_record untouched and the
    histogram reconstructs losslessly from the JSON round-trip."""
    from howtotrainyourmamlpytorch_tpu.serving.metrics import LogHistogram

    hist = LogHistogram()
    for v in (0.5, 2.0, 2.1, 40.0, 41.0, 39.0, 1000.0):
        hist.observe(v)
    rec = tel.make_record(
        "serving", event="rollup", dispatches=7, tenants=7, retraces=0,
        adapt_ms_p50=40.0, adapt_ms_p95=1000.0, tenants_per_sec=12.0,
        window_dropped=0, adapt_ms_hist=hist.to_dict(),
        queue_ms_hist=LogHistogram().to_dict(),
    )
    tel.validate_record(rec)
    wire = json.loads(json.dumps(rec, allow_nan=False))
    back = LogHistogram.from_dict(wire["adapt_ms_hist"])
    assert back.counts == hist.counts
    assert back.count == hist.count and back.min == hist.min
    assert back.quantile(0.5) == hist.quantile(0.5)
    assert wire["window_dropped"] == 0


# -- schema v13: fleet gateway (gateway kind + deadline priority fields) -----


def test_validate_file_accepts_v12_era_fixture():
    """The pinned v12-era log (deadline/slo records and the
    histogram-bearing rollup shape of the PREVIOUS schema) validates
    unchanged under v13 — pure addition, nothing tightened."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v12_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 7


def test_v13_gateway_shed_record_round_trips():
    """The gateway kind, event='shed': one typed edge rejection
    (admission or deadline) with its host/tenant attribution validates,
    JSON round-trips, and the required-field floor (event) is
    enforced."""
    rec = tel.make_record(
        "gateway", event="shed", reason="admission", host="host00",
        tenant_id="tenant-3", priority=1, queue_depth=64, budget=32,
    )
    assert rec["schema"] == tel.SCHEMA_VERSION and rec["kind"] == "gateway"
    tel.validate_record(rec)
    assert json.loads(json.dumps(rec, allow_nan=False)) == rec
    with pytest.raises(ValueError, match="missing required fields"):
        tel.validate_record({
            "schema": tel.SCHEMA_VERSION, "ts": 1.0, "kind": "gateway",
        })


def test_v13_gateway_rehome_and_rollup_records_validate():
    """The other two gateway events: a host trip/re-home marker (which
    host, the chained root cause, how many in-flight requests it
    stranded) and the fleet rollup with its exactly-merged histogram
    payloads."""
    from howtotrainyourmamlpytorch_tpu.serving.metrics import LogHistogram

    tel.validate_record(tel.make_record(
        "gateway", event="rehome", host="host02",
        cause="ConnectionRefusedError(111, 'Connection refused')",
        in_flight=2,
    ))
    hist = LogHistogram()
    for v in (1.0, 2.0, 3.0):
        hist.observe(v)
    rec = tel.make_record(
        "gateway", event="rollup", hosts=3, ready_hosts=2,
        tripped_hosts=["host02"], admitted=120,
        shed={"admission": 4, "deadline": 1}, rehomes=1,
        tenants=120, dispatches=97, adapt_ms_p99=hist.quantile(0.99),
        adapt_ms_hist=hist.to_dict(),
        queue_ms_hist=LogHistogram().to_dict(),
    )
    tel.validate_record(rec)
    wire = json.loads(json.dumps(rec, allow_nan=False))
    back = LogHistogram.from_dict(wire["adapt_ms_hist"])
    assert back.counts == hist.counts and back.count == hist.count


def test_v13_deadline_priority_fields_ride_serving_records():
    """The v13 deadline-record additions: the gateway-stamped priority
    tier and on-the-wire elapsed milliseconds ride the serving
    event='deadline' shape as optional fields — present they validate,
    absent (every pre-v13 record) nothing is required."""
    rec = tel.make_record(
        "serving", event="deadline", tenant_id="t-7", shots=1,
        deadline_ms=50.0, slack_ms=40.0, missed=False, e2e_ms=10.0,
        queue_ms=1.0, route_ms=0.1, priority=2, gateway_ms=0.31,
        replica_id=0,
    )
    tel.validate_record(rec)
    assert rec["priority"] == 2 and rec["gateway_ms"] == 0.31
    tel.validate_record(tel.make_record(
        "serving", event="deadline", tenant_id="t-7", shots=1,
        deadline_ms=50.0, slack_ms=40.0, missed=False, e2e_ms=10.0,
    ))


# -- schema v14: fleet-wide distributed tracing (clock + span process) -------


def test_validate_file_accepts_v13_era_fixture():
    """The pinned v13-era log (gateway shed/rehome/rollup records and
    prefix-free span ids of the PREVIOUS schema) validates unchanged
    under v14 — pure addition, nothing tightened."""
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v13_schema.jsonl"
    )
    assert tel.validate_file(fixture) == 8


def test_v14_gateway_clock_record_validates():
    """The gateway kind, event='clock': one Cristian offset sample
    (offset, RTT/2 skew bound, the RTT it rode) validates and JSON
    round-trips — the record `cli trace --fleet` reads to shift host
    spans onto the gateway clock."""
    rec = tel.make_record(
        "gateway", event="clock", host="host01",
        clock_offset_ms=-3.412, clock_skew_bound_ms=0.266,
        rtt_ms=0.532, samples=4,
    )
    assert rec["schema"] == tel.SCHEMA_VERSION
    tel.validate_record(rec)
    assert json.loads(json.dumps(rec, allow_nan=False)) == rec


def test_v14_span_process_field_validates():
    """The v14 span addition: an optional top-level `process` label (the
    per-process track `cli trace --fleet` groups by) — present it
    validates, absent (every pre-v14 span) nothing is required."""
    tel.validate_record(tel.make_record(
        "span", name="request", cat="serving", trace_id="ab12cd34ef567890",
        span_id="host00-s000001", parent_id="gw-s000003",
        start_ms=10.0, dur_ms=4.2, tid="serving-batcher",
        process="host00",
        attrs={"request_id": "deadbeef-g000001", "clock_offset_ms": -3.4},
    ))
    tel.validate_record(tel.make_record(
        "span", name="request", cat="serving", trace_id="ab12cd34ef567890",
        span_id="s000001", start_ms=10.0, dur_ms=4.2, tid="main",
    ))
