"""Subprocess worker for the kill/resume crash-equivalence tests.

Launched by ``tests/test_resilience_e2e.py`` with a ``fault_spec`` that
kills the process mid-run (``signal:sigkill@iter=N`` at a dispatch
boundary, or ``ckpt_finalize:sigkill@call=N`` inside the async checkpoint
finalizer). Runs the SAME builder wiring the in-process tests use, against
the same pre-built synthetic dataset, so a resumed run's outputs can be
compared bit-for-bit with an uninterrupted in-process run.
"""

from __future__ import annotations

import argparse
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data_root", required=True)
    ap.add_argument("--cache_dir", required=True)
    ap.add_argument("--exp_root", required=True)
    ap.add_argument("--exp_name", required=True)
    ap.add_argument("--fault_spec", default="")
    ap.add_argument("--total_epochs", type=int, default=3)
    args = ap.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    # the test owns the config recipe: import it from the test module so
    # the worker can never drift from the in-process runs it is compared to
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(tests_dir))  # repo root: the package
    sys.path.insert(0, tests_dir)
    from test_resilience_e2e import make_cfg

    from howtotrainyourmamlpytorch_tpu.data.loader import MetaLearningDataLoader
    from howtotrainyourmamlpytorch_tpu.experiment.builder import ExperimentBuilder
    from howtotrainyourmamlpytorch_tpu.experiment.system import MAMLFewShotClassifier

    cfg = make_cfg(
        data_root=args.data_root,
        cache_dir=args.cache_dir,
        exp_root=args.exp_root,
        exp_name=args.exp_name,
        fault_spec=args.fault_spec,
        total_epochs=args.total_epochs,
    )
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    builder = ExperimentBuilder(
        cfg, model, MetaLearningDataLoader,
        experiment_root=args.exp_root, verbose=False,
    )
    builder.run_experiment()
    print("WORKER_DONE", flush=True)


if __name__ == "__main__":
    sys.exit(main())
