"""``meta_accum_steps`` — task-microbatched meta-gradient accumulation.

The contract (ISSUE 11): the accumulated train step scans the meta-batch
in microbatches INSIDE one compiled dispatch, accumulating per-task
meta-grads in f32, and is **bit-exact** (f32) with the monolithic step at
equal total batch — for every train-step factory — while donation stays
whole-state and the dispatch signature stays retrace-free across accum
settings. bf16 compute is ULP-bounded, not bit-exact (the MXU's bf16
passes reassociate internally).

Exactness holds for microbatches of >= 2 tasks (config batch 8, accum
{1, 2, 4} here): a width-1 batched GEMM lowers as a plain GEMM whose
blocking can reassociate *within-task* partial sums — the documented
caveat in ``core.maml._meta_loss_and_grads``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import make_micro_cfg, make_synthetic_batch

from howtotrainyourmamlpytorch_tpu.core import maml, msl

BATCH = 8  # power of two, microbatch width >= 2 for accum in {1, 2, 4}
ACCUMS = (1, 2, 4)


def _cfg(**overrides):
    return make_micro_cfg(batch_size=BATCH, **overrides)


def _weights(cfg):
    return jnp.asarray(
        msl.loss_weights_for(
            cfg.number_of_training_steps_per_iter,
            cfg.use_multi_step_loss_optimization,
            True,
            0,
            cfg.multi_step_loss_num_epochs,
        )
    )


def _index_batch(cfg, store_images=64, seed=0):
    """A synthetic resident uint8 store + one valid index batch."""
    rng = np.random.RandomState(seed)
    h, w, c = cfg.im_shape
    store = rng.randint(0, 255, (store_images, h, w, c), dtype=np.uint8)
    per = cfg.num_samples_per_class + cfg.num_target_samples
    gather = rng.randint(
        0, store_images,
        (cfg.batch_size, cfg.num_classes_per_set, per), dtype=np.int64,
    ).astype(np.int32)
    rot_k = np.zeros(
        (cfg.batch_size, cfg.num_classes_per_set), dtype=np.int32
    )
    return store, gather, rot_k


def _assert_tree_bitexact(a, b, context=""):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb, context
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.tobytes() == y.tobytes(), (
            f"{context}: max abs diff "
            f"{np.max(np.abs(x.astype(np.float64) - y.astype(np.float64)))}"
        )


def test_accum_divisibility_validated():
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig

    with pytest.raises(ValueError, match="meta_accum_steps"):
        make_micro_cfg(batch_size=4, meta_accum_steps=3)
    with pytest.raises(ValueError, match="meta_accum_steps"):
        make_micro_cfg(meta_accum_steps=0)
    with pytest.raises(ValueError, match="meta_accum_steps"):
        MAMLConfig(dataset_name="omniglot_dataset", meta_accum_steps="two")
    cfg = make_micro_cfg(batch_size=4, meta_accum_steps=4)
    assert cfg.meta_accum_steps == 4
    # accum>1 with a fused chunk too large to unroll would silently void
    # the bit-exactness contract (rolled outer scan) — refused at config
    # time; accum=1 keeps any chunk size
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        make_micro_cfg(
            batch_size=4, meta_accum_steps=2, steps_per_dispatch=10
        )
    assert make_micro_cfg(
        batch_size=4, meta_accum_steps=2, steps_per_dispatch=8
    ).steps_per_dispatch == 8
    assert make_micro_cfg(steps_per_dispatch=10).steps_per_dispatch == 10


def test_accum_trace_time_batch_mismatch_is_loud():
    """A step traced for accum=2 refuses a batch the setting cannot
    split, instead of silently computing something else."""
    cfg = _cfg(meta_accum_steps=2)
    state = maml.init_state(cfg)
    # a 3-task batch from a plain config (replace() on the accum config
    # would already fail the config-time divisibility validation)
    x_s, y_s, x_t, y_t = make_synthetic_batch(make_micro_cfg(batch_size=3))
    with pytest.raises(ValueError, match="must divide"):
        jax.jit(maml.make_train_step(cfg, second_order=True))(
            state, x_s, y_s, x_t, y_t, _weights(cfg), 0.01
        )


def test_accum_bit_exact_f32_plain_step():
    """The tier-1 fast-lane equivalence: accum in {1, 2, 4} produce
    bit-identical f32 metrics AND post-update state through the full
    second-order train step at equal total batch."""
    base = _cfg()
    x_s, y_s, x_t, y_t = make_synthetic_batch(base)
    w = _weights(base)
    results = {}
    for accum in ACCUMS:
        cfg = base.replace(meta_accum_steps=accum)
        step = jax.jit(maml.make_train_step(cfg, second_order=True))
        state = maml.init_state(cfg)  # deterministic from cfg.seed
        new_state, metrics = step(state, x_s, y_s, x_t, y_t, w, 0.01)
        results[accum] = (jax.device_get(new_state), jax.device_get(metrics))
    ref_state, ref_metrics = results[1]
    for accum in ACCUMS[1:]:
        st, m = results[accum]
        _assert_tree_bitexact(
            m["loss"], ref_metrics["loss"], f"loss accum={accum}"
        )
        _assert_tree_bitexact(
            m["accuracy"], ref_metrics["accuracy"], f"accuracy accum={accum}"
        )
        for part in ("net", "lslr", "bn"):
            _assert_tree_bitexact(
                getattr(st, part), getattr(ref_state, part),
                f"state.{part} accum={accum}",
            )


def _assert_family_bitexact(run_family):
    ref = run_family(1)
    for accum in (2, 4):
        got = run_family(accum)
        for name in ref:
            ref_state, ref_metrics = ref[name]
            st, m = got[name]
            _assert_tree_bitexact(
                m["loss"], ref_metrics["loss"], f"{name} loss accum={accum}"
            )
            for part in ("net", "lslr", "bn"):
                _assert_tree_bitexact(
                    getattr(st, part), getattr(ref_state, part),
                    f"{name} state.{part} accum={accum}",
                )


@pytest.mark.slow
def test_accum_bit_exact_f32_pixel_factories():
    """The acceptance matrix, pixel half: plain + multi (fused
    steps_per_dispatch) factories stay bit-exact (f32) across accum
    {1, 2, 4} at equal total batch."""
    base = _cfg()
    x_s, y_s, x_t, y_t = make_synthetic_batch(base)
    w = _weights(base)
    k = 2
    stacked = tuple(
        np.stack([a] * k) for a in (x_s, y_s, x_t, y_t)
    )

    def run_family(accum):
        cfg = base.replace(meta_accum_steps=accum)
        out = {}
        state = maml.init_state(cfg)
        out["plain"] = jax.jit(maml.make_train_step(cfg, True))(
            state, x_s, y_s, x_t, y_t, w, 0.01
        )
        state = maml.init_state(cfg)
        out["multi"] = jax.jit(maml.make_train_multi_step(cfg, True))(
            state, *stacked, w, 0.01
        )
        return jax.device_get(out)

    _assert_family_bitexact(run_family)


@pytest.mark.slow
def test_accum_bit_exact_f32_indexed_factories():
    """The acceptance matrix, device-resident half, at batch 12 — the
    flagship's measured per-chip HBM-ceiling batch (microbatch widths
    12/6/3, inside the verified width envelope).

    ``indexed``: bit-exact (f32) across accum {1, 2, 4} — the
    single-update accumulated-vs-monolithic contract, same bar as the
    pixel factories. ``multi_indexed`` (k chained fused updates): its
    FIRST update — the one consuming entry-parameter state, where the
    accumulation contract is well-posed — is bit-exact via its metrics;
    the full chain is tolerance-bounded: updates past the first consume
    intermediate state, whose within-task codegen XLA may reassociate at
    ~1 ulp independent of accumulation (the same effect that makes fused
    multi-step vs k sequential dispatches tolerance-equal, not bitwise —
    test_system.py::test_run_train_iters_matches_sequential), and Adam
    amplifies that on ~zero-gradient params."""
    base = make_micro_cfg(batch_size=12)
    w = _weights(base)
    k = 2
    store, gather, rot_k = _index_batch(base)
    gather_k = np.stack([gather] * k)
    rot_k_k = np.stack([rot_k] * k)

    def run_indexed(accum):
        cfg = base.replace(meta_accum_steps=accum)
        out = {}
        state = maml.init_state(cfg)
        out["indexed"] = jax.jit(
            maml.make_train_step_indexed(cfg, True, augment=False)
        )(state, store, gather, rot_k, w, 0.01)
        return jax.device_get(out)

    _assert_family_bitexact(run_indexed)

    multi = {}
    for accum in (1, 2, 4):
        cfg = base.replace(meta_accum_steps=accum)
        state = maml.init_state(cfg)
        st, m = jax.jit(
            maml.make_train_multi_step_indexed(cfg, True, augment=False)
        )(state, store, gather_k, rot_k_k, w, 0.01)
        multi[accum] = (jax.device_get(st), jax.device_get(m))
    ref_state, ref_m = multi[1]
    for accum in (2, 4):
        st, m = multi[accum]
        # update 1 (entry state): the accumulation contract, bit-exact
        _assert_tree_bitexact(
            np.asarray(m["loss"])[0], np.asarray(ref_m["loss"])[0],
            f"multi_indexed first-update loss accum={accum}",
        )
        # the chained tail: tolerance-bounded (see docstring)
        np.testing.assert_allclose(
            np.asarray(m["loss"]), np.asarray(ref_m["loss"]),
            rtol=1e-5, err_msg=f"multi_indexed losses accum={accum}",
        )
        for part in ("net", "lslr"):
            for key in getattr(ref_state, part):
                np.testing.assert_allclose(
                    np.asarray(getattr(st, part)[key]),
                    np.asarray(getattr(ref_state, part)[key]),
                    atol=2e-3,
                    err_msg=f"multi_indexed {part}.{key} accum={accum}",
                )


def test_accum_bf16_ulp_bounded():
    """bf16 compute: accumulated vs monolithic stays within a few bf16
    ULPs (the f32 master params absorb most of it — the bound here is
    loose only relative to f32's exact-equality bar)."""
    base = _cfg(compute_dtype="bfloat16")
    x_s, y_s, x_t, y_t = make_synthetic_batch(base)
    w = _weights(base)
    outs = {}
    for accum in (1, 2):
        cfg = base.replace(meta_accum_steps=accum)
        step = jax.jit(maml.make_train_step(cfg, second_order=True))
        state = maml.init_state(cfg)
        new_state, metrics = step(state, x_s, y_s, x_t, y_t, w, 0.01)
        outs[accum] = (jax.device_get(new_state), jax.device_get(metrics))
    (s1, m1), (s2, m2) = outs[1], outs[2]
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=3e-2)
    for part in ("net", "lslr"):
        for key in getattr(s1, part):
            np.testing.assert_allclose(
                np.asarray(getattr(s1, part)[key], np.float32),
                np.asarray(getattr(s2, part)[key], np.float32),
                rtol=3e-2, atol=3e-2, err_msg=f"{part}.{key}",
            )


def test_accum_step_donates_whole_state():
    """Donation survives accumulation: the accumulated step's executable
    aliases at least the whole MetaState (the TRAIN_DONATE audit passes —
    same contract the un-accumulated family pins in test_donation)."""
    from howtotrainyourmamlpytorch_tpu.analysis import auditor as audit_lib

    cfg = _cfg(meta_accum_steps=2)
    auditor = audit_lib.ProgramAuditor(cfg)
    state = audit_lib._state_avals(cfg)
    weights = jax.ShapeDtypeStruct(
        (cfg.number_of_training_steps_per_iter,), jnp.float32
    )
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    batch = audit_lib._batch_avals(cfg)
    report = auditor.audit(
        "train_step[so=1,accum=2]",
        jax.jit(maml.make_train_step(cfg, True),
                donate_argnums=maml.TRAIN_DONATE),
        (state, *batch, weights, lr),
        donate=maml.TRAIN_DONATE,
    )
    donation_violations = [
        v for v in report.violations if v.contract == "donation"
    ]
    assert donation_violations == []
    assert report.donation is not None
    assert report.donation["alias_size_bytes"] >= audit_lib.tree_byte_size(
        state
    )


def test_accum_dispatches_are_retrace_free(tmp_path):
    """Accumulation is a STATIC trace knob: repeated dispatches through
    the system facade at any accum setting keep one abstract signature
    per site (the PR 7 RetraceDetector observes zero retraces)."""
    from howtotrainyourmamlpytorch_tpu.analysis.auditor import RetraceDetector
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )

    cfg = _cfg(meta_accum_steps=2)
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    detector = RetraceDetector(strict=True)
    model.retrace_detector = detector
    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg)
    batch = (x_s, x_t, y_s, y_t)  # facade convention
    for _ in range(3):
        model.run_train_iter(batch, epoch=0)
    metrics, _ = model.run_validation_iter(batch)
    assert np.isfinite(float(np.asarray(metrics["loss"])))
    assert detector.retrace_count == 0


@pytest.mark.slow
def test_accum_serializes_microbatches_and_never_grows_temps():
    """The memory half of the contract, stated at the strength the
    backend guarantees it: the accumulated program carries the
    microbatch serialization chain (one input-gating optimization
    barrier per microbatch, plus the final reduction barrier — so a
    memory-aware scheduler CAN run one microbatch's activations at a
    time instead of the monolithic live set), and the static temp
    allocation never grows vs the monolithic step. The realized peak is
    the scheduler's call per backend: XLA:CPU keeps backwards coalesced
    (temps shrink only slightly here), the TPU memory-aware scheduler is
    what the HBM decoupling targets — on-device numbers belong to the
    BENCH trajectory, not this CPU test."""
    base = make_micro_cfg(
        batch_size=8, image_height=28, image_width=28, cnn_num_filters=16,
        num_stages=3, num_target_samples=8, use_remat=False,
    )
    temps = {}
    barriers = {}
    for accum in (1, 4):
        cfg = base.replace(meta_accum_steps=accum)
        step = jax.jit(
            maml.make_train_step(cfg, True),
            donate_argnums=maml.TRAIN_DONATE,
        )
        state = jax.eval_shape(lambda cfg=cfg: maml.init_state(cfg))
        x_s, y_s, x_t, y_t = make_synthetic_batch(base)
        args = [
            jax.ShapeDtypeStruct(a.shape, a.dtype)
            for a in (x_s, y_s, x_t, y_t)
        ]
        w = jax.ShapeDtypeStruct(
            (cfg.number_of_training_steps_per_iter,), jnp.float32
        )
        lr = jax.ShapeDtypeStruct((), jnp.float32)
        traced = step.trace(state, *args, w, lr)
        # the serialization chain is a trace-level structure (XLA:CPU
        # folds barriers out of the optimized HLO text): the accumulated
        # program adds the input gate inside the scanned microbatch body
        # (the jaxpr shows the scan body once — unrolling happens at
        # lowering) on top of the shared pre-reduction barrier
        barriers[accum] = str(traced.jaxpr).count("optimization_barrier")
        compiled = traced.lower().compile()
        temps[accum] = int(compiled.memory_analysis().temp_size_in_bytes)
    assert barriers[1] == 1, barriers
    assert barriers[4] == 2, barriers
    # and accumulation never INCREASES the static allocation
    assert temps[4] <= temps[1], temps


def test_accum_grads_accumulate_in_f32_under_bf16():
    """The accumulation dtype contract: per-task meta-grads (and their
    reduction) are f32 even under bf16 compute — the jaxpr's stacked
    grad leaves carry float32."""
    cfg = _cfg(compute_dtype="bfloat16", meta_accum_steps=2)
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg)
    loss, grads = jax.jit(maml.make_grads_fn(cfg, True))(
        state, x_s, y_s, x_t, y_t, _weights(cfg)
    )
    for part in ("net", "lslr"):
        for key, leaf in grads[part].items():
            assert leaf.dtype == jnp.float32, f"{part}.{key}"
