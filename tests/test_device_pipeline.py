"""Device-resident episode pipeline tests (data_placement tiers).

Bit-exactness proofs: the on-device gather/decode/rot90 path
(``ops.device_pipeline``) and the uint8-stream host gather must produce
arrays IDENTICAL to the host float path — for Omniglot (unrescaled float
cast + rot-k) and Mini-ImageNet (/255 + ImageNet-stat normalize, incl.
reverse_channels) — and a full jitted train step over them must produce
identical loss/accuracy. Plus the loader tiers end-to-end through the
system facade, and the producer-thread leak fix.
"""

import os
import time

import jax
import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.core import maml, msl
from howtotrainyourmamlpytorch_tpu.data.episodes import (
    sample_episode,
    sample_episode_indices,
)
from howtotrainyourmamlpytorch_tpu.data.loader import (
    IndexBatch,
    MetaLearningDataLoader,
)
from howtotrainyourmamlpytorch_tpu.data.preprocess import FlatStore
from howtotrainyourmamlpytorch_tpu.ops import device_pipeline


def _flat_store(n_classes=6, per_class=9, h=8, w=8, c=1, seed=0):
    rng = np.random.RandomState(seed)
    data = rng.randint(0, 256, (n_classes * per_class, h, w, c), dtype=np.uint8)
    return FlatStore(
        data=data,
        offsets={str(i): i * per_class for i in range(n_classes)},
        sizes={str(i): per_class for i in range(n_classes)},
    )


def _cfg(**kw):
    base = dict(
        dataset_name="omniglot_dataset",
        image_height=8,
        image_width=8,
        image_channels=1,
        num_classes_per_set=4,
        num_samples_per_class=1,
        num_target_samples=2,
        use_mmap_cache=True,
        data_placement="device",
    )
    base.update(kw)
    return MAMLConfig(**base)


def _expand_one(cfg, store, seed, augment):
    """Host episode + its on-device indexed expansion, for comparison."""
    views = store.views()
    keys = np.array(list(views.keys()))
    host = sample_episode(cfg, views, keys, seed=seed, augment=augment)
    ie = sample_episode_indices(cfg, store.offsets, store.sizes, keys, seed=seed)
    expand = jax.jit(device_pipeline.make_index_expander(cfg, augment=augment))
    x_s, y_s, x_t, y_t = expand(store.data, ie.gather[None], ie.rot_k[None])
    return host, (np.asarray(x_s[0]), np.asarray(y_s[0]),
                  np.asarray(x_t[0]), np.asarray(y_t[0]))


@pytest.mark.parametrize("augment", [False, True])
def test_indexed_path_bit_exact_omniglot(augment):
    """Omniglot: unrescaled float cast + per-class rot-k, bit-for-bit."""
    cfg = _cfg()
    store = _flat_store()
    host, (x_s, y_s, x_t, y_t) = _expand_one(cfg, store, seed=7, augment=augment)
    np.testing.assert_array_equal(x_s, host.x_support)
    np.testing.assert_array_equal(x_t, host.x_target)
    np.testing.assert_array_equal(y_s, host.y_support)
    np.testing.assert_array_equal(y_t, host.y_target)


@pytest.mark.parametrize("reverse_channels", [False, True])
def test_indexed_path_bit_exact_mini_imagenet(reverse_channels):
    """Mini-ImageNet: /255 + ImageNet-stat normalize (+ BGR flip),
    bit-for-bit — the decode LUT makes the device values the host values by
    construction (XLA fast-math would otherwise drift ULPs)."""
    cfg = _cfg(
        dataset_name="mini_imagenet",
        image_channels=3,
        num_samples_per_class=2,
        reverse_channels=reverse_channels,
    )
    store = _flat_store(c=3, seed=1)
    host, (x_s, _, x_t, _) = _expand_one(cfg, store, seed=3, augment=False)
    np.testing.assert_array_equal(x_s, host.x_support)
    np.testing.assert_array_equal(x_t, host.x_target)


def test_uint8_stream_decode_bit_exact():
    """uint8 host gather + on-device decode == host float path, bit-for-bit
    (rot90 on integer pixels commutes with the elementwise decode)."""
    cfg = _cfg(dataset_name="mini_imagenet", image_channels=3,
               data_placement="uint8_stream")
    store = _flat_store(c=3, seed=5)
    views = store.views()
    keys = np.array(list(views.keys()))
    host = sample_episode(cfg, views, keys, seed=9, augment=False)
    ie = sample_episode_indices(cfg, store.offsets, store.sizes, keys, seed=9)
    x_u8 = store.data[ie.gather]
    decode = jax.jit(device_pipeline.make_decoder(cfg))
    x = np.asarray(decode(x_u8))
    spc = cfg.num_samples_per_class
    np.testing.assert_array_equal(x[:, :spc], host.x_support)
    np.testing.assert_array_equal(x[:, spc:], host.x_target)


def test_index_rng_parity_with_pixel_path():
    """The four-draw RNG discipline: the rows the index sampler selects are
    exactly the images the pixel sampler decodes (pre-decode gather)."""
    cfg = _cfg()
    store = _flat_store()
    views = store.views()
    keys = np.array(list(views.keys()))
    host = sample_episode(cfg, views, keys, seed=11, augment=False)
    ie = sample_episode_indices(cfg, store.offsets, store.sizes, keys, seed=11)
    gathered = store.data[ie.gather].astype(np.float32)  # omniglot decode
    spc = cfg.num_samples_per_class
    np.testing.assert_array_equal(gathered[:, :spc], host.x_support)
    np.testing.assert_array_equal(gathered[:, spc:], host.x_target)


def test_train_step_identical_across_batch_forms():
    """A full jitted train step fed (a) host pixels and (b) store+indices
    produces identical loss/accuracy — the whole-program equivalence the
    placement tiers rely on."""
    cfg = _cfg(
        num_samples_per_class=2,
        cnn_num_filters=3,
        num_stages=1,
        number_of_training_steps_per_iter=2,
        use_remat=False,
    )
    store = _flat_store(h=8, w=8)
    views = store.views()
    keys = np.array(list(views.keys()))
    eps, ies = [], []
    for seed in (3, 4):
        eps.append(sample_episode(cfg, views, keys, seed=seed, augment=True))
        ies.append(
            sample_episode_indices(cfg, store.offsets, store.sizes, keys, seed=seed)
        )
    x_s = np.stack([e.x_support for e in eps])
    x_t = np.stack([e.x_target for e in eps])
    y_s = np.stack([e.y_support for e in eps])
    y_t = np.stack([e.y_target for e in eps])
    gather = np.stack([ie.gather for ie in ies])
    rot_k = np.stack([ie.rot_k for ie in ies])
    weights = np.asarray(msl.final_step_only(
        cfg.number_of_training_steps_per_iter))

    state = maml.init_state(cfg)
    pixel_step = jax.jit(maml.make_train_step(cfg, second_order=True))
    state_p, metrics_p = pixel_step(state, x_s, y_s, x_t, y_t, weights, 1e-3)

    state2 = maml.init_state(cfg)
    idx_step = jax.jit(
        maml.make_train_step_indexed(cfg, second_order=True, augment=True)
    )
    state_i, metrics_i = idx_step(state2, store.data, gather, rot_k, weights, 1e-3)

    np.testing.assert_allclose(
        np.asarray(metrics_p["loss"]), np.asarray(metrics_i["loss"]),
        rtol=0, atol=0,
    )
    np.testing.assert_allclose(
        np.asarray(metrics_p["accuracy"]), np.asarray(metrics_i["accuracy"]),
        rtol=0, atol=0,
    )
    for k in state_p.net:
        np.testing.assert_array_equal(
            np.asarray(state_p.net[k]), np.asarray(state_i.net[k])
        )


def test_non_square_rot90_rejected():
    cfg = _cfg(image_height=8, image_width=6)
    with pytest.raises(ValueError, match="square"):
        device_pipeline.make_index_expander(cfg, augment=True)
    # rotation not traced in -> no constraint
    device_pipeline.make_index_expander(cfg, augment=False)


# -- loader tiers on a real (synthetic) on-disk dataset ---------------------


def _write_presplit(root, mode, n_classes=4, per_class=5, size=12, seed=0):
    rng = np.random.RandomState(seed)
    for set_name in ("train", "val", "test"):
        for ci in range(n_classes):
            d = os.path.join(root, set_name, f"c{ci:02d}")
            os.makedirs(d, exist_ok=True)
            base = rng.randint(0, 200)
            shape = (size, size) if mode == "L" else (size, size, 3)
            for j in range(per_class):
                arr = np.clip(
                    base + rng.randint(-30, 30, shape), 0, 255
                ).astype(np.uint8)
                Image.fromarray(arr, mode).save(os.path.join(d, f"im{j}.png"))


def _tier_cfg(root, cache, placement, dataset_name, channels):
    return MAMLConfig(
        dataset_name=dataset_name,
        dataset_path=root,
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=12, image_width=12, image_channels=channels,
        num_classes_per_set=2, num_samples_per_class=1, num_target_samples=2,
        batch_size=2, cnn_num_filters=4, num_stages=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_mmap_cache=True, use_remat=False, seed=0,
        num_dataprovider_workers=2, cache_dir=cache,
        data_placement=placement,
    )


@pytest.mark.parametrize(
    "dataset_name,mode,channels",
    [("omniglot_synth", "L", 1), ("mini_imagenet_synth", "RGB", 3)],
)
def test_loader_tiers_bit_exact(tmp_path, dataset_name, mode, channels):
    """Equivalence at the loader level: for a fixed seed, the uint8 tier's
    device-decoded batches and the device tier's expanded index batches are
    bit-identical to the host tier's float batches."""
    root = str(tmp_path / dataset_name)
    _write_presplit(root, mode)
    batches = {}
    for placement in ("host", "uint8_stream", "device"):
        cache = str(tmp_path / f"cache_{placement}")
        cfg = _tier_cfg(root, cache, placement, dataset_name, channels)
        loader = MetaLearningDataLoader(
            cfg, cache_dir=cache, shard_id=0, num_shards=1
        )
        batches[placement] = (
            cfg,
            loader,
            list(loader.get_train_batches(total_batches=2, augment_images=True)),
        )

    cfg_h, _, host = batches["host"]
    _, _, u8 = batches["uint8_stream"]
    cfg_d, loader_d, dev = batches["device"]
    decode = jax.jit(device_pipeline.make_decoder(cfg_h))
    augment = "omniglot" in dataset_name
    expand = jax.jit(
        device_pipeline.make_index_expander(cfg_d, augment=augment)
    )
    store = loader_d.dataset.flat_stores["train"].data
    for hb, ub, db in zip(host, u8, dev):
        assert isinstance(db, IndexBatch) and db.set_name == "train"
        assert ub[0].dtype == np.uint8
        # uint8 tier: device decode reproduces the host floats
        np.testing.assert_array_equal(np.asarray(decode(ub[0])), hb[0])
        np.testing.assert_array_equal(np.asarray(decode(ub[1])), hb[1])
        np.testing.assert_array_equal(ub[2], hb[2])  # labels
        np.testing.assert_array_equal(ub[4], hb[4])  # seeds
        # device tier: index expansion reproduces the host floats
        x_s, y_s, x_t, y_t = expand(store, db.gather, db.rot_k)
        np.testing.assert_array_equal(np.asarray(x_s), hb[0])
        np.testing.assert_array_equal(np.asarray(x_t), hb[1])
        np.testing.assert_array_equal(np.asarray(y_s), hb[2])
        np.testing.assert_array_equal(np.asarray(y_t), hb[3])
        np.testing.assert_array_equal(db.seeds, hb[4])


@pytest.mark.slow
def test_system_tiers_identical_through_full_steps(tmp_path):
    """Acceptance equivalence: for a fixed seed, the 'device' and
    'uint8_stream' placements reproduce the host path's per-step train
    loss/accuracy (and fused-dispatch + validation metrics) through the full
    system facade."""
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )

    root = str(tmp_path / "omniglot_synth")
    _write_presplit(root, "L")
    results = {}
    for placement in ("host", "uint8_stream", "device"):
        cache = str(tmp_path / f"cache_{placement}")
        cfg = _tier_cfg(root, cache, placement, "omniglot_synth", 1)
        model = MAMLFewShotClassifier(cfg, use_mesh=False)
        loader = MetaLearningDataLoader(
            cfg, cache_dir=cache, shard_id=0, num_shards=1
        )
        if placement == "device":
            model.register_flat_stores(
                {n: fs.data for n, fs in loader.dataset.flat_stores.items()}
            )
        vals = []
        for b in loader.get_train_batches(total_batches=2, augment_images=True):
            m = model.run_train_iter(b, epoch=0)
            vals += [float(np.asarray(m["loss"])),
                     float(np.asarray(m["accuracy"]))]
        chunk = list(loader.get_train_batches(total_batches=2,
                                              augment_images=True))
        mm = model.run_train_iters(chunk, epoch=0)
        vals += np.asarray(mm["loss"]).ravel().tolist()
        vb = list(loader.get_val_batches(total_batches=2))
        vm, preds = model.run_validation_iters(vb, return_preds=True)
        vals += np.asarray(vm["loss"]).ravel().tolist()
        results[placement] = (np.asarray(vals), np.asarray(preds))

    np.testing.assert_array_equal(
        results["host"][0], results["uint8_stream"][0]
    )
    np.testing.assert_array_equal(results["host"][0], results["device"][0])
    np.testing.assert_array_equal(
        results["host"][1], results["uint8_stream"][1]
    )
    np.testing.assert_array_equal(results["host"][1], results["device"][1])


@pytest.mark.slow
def test_device_tier_on_mesh_matches_single_device(tmp_path):
    """data_placement='device' on a multi-device mesh: the store replicates,
    the index batches shard over the task axis, and metrics equal the
    unsharded run (the sharded gather reads the same replicated rows)."""
    import jax as _jax

    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )

    if len(_jax.devices()) < 2:
        pytest.skip("needs the 8-virtual-device CPU backend")
    root = str(tmp_path / "omniglot_synth")
    _write_presplit(root, "L")
    out = {}
    for use_mesh in (False, True):
        cache = str(tmp_path / f"cache_{use_mesh}")
        cfg = _tier_cfg(root, cache, "device", "omniglot_synth", 1)
        model = MAMLFewShotClassifier(cfg, use_mesh=use_mesh)
        if use_mesh:
            assert model.mesh is not None
        loader = MetaLearningDataLoader(
            cfg, cache_dir=cache, shard_id=0, num_shards=1
        )
        model.register_flat_stores(
            {n: fs.data for n, fs in loader.dataset.flat_stores.items()}
        )
        vals = []
        for b in loader.get_train_batches(total_batches=2, augment_images=True):
            m = model.run_train_iter(b, epoch=0)
            vals.append(float(np.asarray(m["loss"])))
        vb = list(loader.get_val_batches(total_batches=1))
        vm, _ = model.run_validation_iter(vb[0])
        vals.append(float(np.asarray(vm["loss"])))
        out[use_mesh] = np.asarray(vals)
    np.testing.assert_allclose(out[False], out[True], rtol=1e-6)


def test_device_tier_index_batches_are_tiny(tmp_path):
    """The H2D contract: an IndexBatch is a few KB where the float batch is
    MBs (the whole point of the tier)."""
    root = str(tmp_path / "omniglot_synth")
    _write_presplit(root, "L")
    cache = str(tmp_path / "cache")
    cfg = _tier_cfg(root, cache, "device", "omniglot_synth", 1)
    loader = MetaLearningDataLoader(cfg, cache_dir=cache, shard_id=0, num_shards=1)
    (b,) = list(loader.get_train_batches(total_batches=1))
    index_bytes = b.gather.nbytes + b.rot_k.nbytes
    cfg_h = cfg.replace(data_placement="host")
    loader_h = MetaLearningDataLoader(
        cfg_h, cache_dir=str(tmp_path / "cache_h"), shard_id=0, num_shards=1
    )
    (hb,) = list(loader_h.get_train_batches(total_batches=1))
    pixel_bytes = sum(int(a.nbytes) for a in hb[:4])
    assert index_bytes * 50 < pixel_bytes  # 12x12x1 floats vs int32 indices


def test_producer_thread_exits_when_consumer_abandons(tmp_path):
    """Satellite: a producer blocked in put() against a full queue must
    observe stop and exit when the consumer abandons the generator (the old
    blocking put leaked the thread forever)."""
    root = str(tmp_path / "omniglot_synth")
    _write_presplit(root, "L")
    cache = str(tmp_path / "cache")
    cfg = _tier_cfg(root, cache, "host", "omniglot_synth", 1).replace(
        prefetch_batches=1
    )
    loader = MetaLearningDataLoader(cfg, cache_dir=cache, shard_id=0, num_shards=1)
    gen = loader.get_train_batches(total_batches=100)
    next(gen)  # start the stream; producer races ahead and fills the queue
    thread = loader._last_producer_thread
    assert thread is not None and thread.is_alive()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:  # wait for it to park in put()
        time.sleep(0.05)
        if loader.pop_stream_stats()["batches"] >= 1:
            break
    gen.close()  # consumer abandons -> finally: stop.set()
    thread.join(10.0)
    assert not thread.is_alive(), "producer thread leaked after consumer close"


def test_stream_stats_accumulate_and_reset(tmp_path):
    root = str(tmp_path / "omniglot_synth")
    _write_presplit(root, "L")
    cache = str(tmp_path / "cache")
    cfg = _tier_cfg(root, cache, "host", "omniglot_synth", 1)
    loader = MetaLearningDataLoader(cfg, cache_dir=cache, shard_id=0, num_shards=1)
    list(loader.get_train_batches(total_batches=3))
    stats = loader.pop_stream_stats()
    assert stats["batches"] == 3
    assert stats["assembly_s"] > 0.0
    assert stats["stall_s"] >= 0.0
    assert loader.pop_stream_stats()["batches"] == 0  # reset
