"""Driver-artifact smoke tests.

``bench.py`` and ``__graft_entry__.entry()`` are the two things the round
driver executes; round 2 shipped a bench that died with NameError on every
backend because nothing in the suite ran them.  These tests close that hole:
the bench must always print one parsable JSON line (on any backend), and
``entry()`` must return a jittable (fn, args) pair.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_prints_parsable_json_line():
    """Slow lane: the 'bench exits 0 with a schema-valid line' duty runs on
    every push via the dedicated CI bench-smoke job; this twin adds the
    detailed per-measurement assertions (epoch boundary, input pipeline,
    telemetry/health overhead, donation, HLO cost) on the full bench."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        BENCH_WARMUP_STEPS="1",
        BENCH_TIMED_STEPS="2",
        BENCH_BATCH_SIZE="2",
        BENCH_CNN_NUM_FILTERS="8",
        BENCH_IMAGE_HEIGHT="16",
        BENCH_IMAGE_WIDTH="16",
        BENCH_NUMBER_OF_TRAINING_STEPS_PER_ITER="2",
        # keep the epoch-boundary eval compile cheap in CI (first-order,
        # 2 inner steps); the measurement itself is still exercised
        BENCH_NUMBER_OF_EVALUATION_STEPS_PER_ITER="2",
        BENCH_NO_BASELINE_WRITE="1",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, f"bench.py failed:\n{out.stderr[-3000:]}"
    line = out.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "meta_tasks_per_sec_per_chip"
    assert rec["value"] > 0
    assert rec["unit"] == "tasks/s/chip"
    # stored baselines are TPU-recorded; this CPU run has no comparable
    # baseline -> null, never a bogus 1.0 that reads as "no change"
    assert rec["vs_baseline"] is None
    assert rec["backend"] == "cpu"
    # the epoch-boundary tail (fused val + checkpoint) is measured and
    # self-describing
    eb = rec["epoch_boundary"]
    assert eb["seconds"] > 0
    assert eb["val_seconds"] > 0 and eb["ckpt_seconds"] > 0
    assert eb["ckpt_seconds"] >= eb["ckpt_blocking_seconds"]
    assert eb["val_batches"] >= 1 and eb["eval_batches_per_dispatch"] >= 1
    # the three-tier input-pipeline measurement: uint8 streaming moves ~4x
    # fewer H2D bytes (pixels exactly 4x; int32 labels unchanged), the
    # index-only device tier well under 1 MB/step
    ip = rec["input_pipeline"]
    host_b = ip["host"]["h2d_bytes_per_step"]
    u8_b = ip["uint8_stream"]["h2d_bytes_per_step"]
    dev_b = ip["device"]["h2d_bytes_per_step"]
    assert host_b >= 3.9 * u8_b
    assert dev_b < 1_000_000 and dev_b < u8_b
    for tier in ("host", "uint8_stream", "device"):
        assert ip[tier]["assembly_ms_per_step"] >= 0
        assert ip[tier]["producer_stall_ms_per_step"] >= 0
    # on-device dynamics collection cost is measured and self-describing
    to = rec["telemetry_overhead"]
    assert to["off_ms_per_step"] > 0 and to["dynamics_ms_per_step"] > 0
    assert to["timed_steps"] >= 1
    # on-device health-probe cost (health_level='monitor' vs off) is
    # reported the same way
    ho = rec["health_overhead"]
    assert ho["off_ms_per_step"] > 0 and ho["monitor_ms_per_step"] > 0
    assert ho["timed_steps"] >= 1
    assert "overhead_pct" in ho
    # host-side span emission must be noise next to a device step: both
    # arms time the SAME compiled executable, so <5% is a real bound on
    # the tracing layer, not on measurement drift
    tro = rec["tracing_overhead"]
    assert tro["off_ms_per_step"] > 0 and tro["spans_ms_per_step"] > 0
    assert tro["timed_steps"] >= 1
    assert tro["overhead_pct"] is not None and tro["overhead_pct"] < 5.0
    # adapt-on-request serving: latency percentiles + throughput under
    # the strict zero-retrace gate (ROADMAP item 1)
    sv = rec["serving"]
    assert sv["adaptation_latency_ms_p50"] > 0
    assert sv["adaptation_latency_ms_p95"] >= sv["adaptation_latency_ms_p50"]
    assert sv["tenants_per_sec"] > 0
    assert sv["retraces"] == 0
    assert sv["dispatches"] >= 1 and sv["tenants"] >= sv["dispatches"]
    assert sv["bucket_ladder"] == [1, 2]  # the reduced-mode ladder
    assert rec["n_chips"] >= 1
    assert rec["dtype"] in ("float32", "bfloat16")
    # the step lowering is self-describing: conv impl + channel padding
    # (CPU auto: im2col, padding off)
    assert rec["conv_impl"] == "im2col"
    assert rec["pad_channels"] == "off"
    # the PR-16 compute-diet knobs are self-describing too (CPU auto:
    # fused one-pass BN stats, reshape pool, hoisted layer-1 patches)
    assert rec["bn_stats_impl"] == "fused"
    assert rec["pool_impl"] == "reshape"
    assert rec["im2col_hoist"] is True
    # donation/aliasing stats of the compiled step: the state is donated
    # and the executable aliases a non-trivial byte count in place
    don = rec["donation"]
    assert don["donate_argnums"] == [0]
    assert don.get("alias_size_bytes", 0) > 0
    # per-category HLO cost breakdown: totals plus an op census that names
    # the contraction ops the lowering produced
    hc = rec["hlo_cost"]
    assert hc["flops"] > 0 and hc["bytes_accessed"] > 0
    assert "hlo_op_counts" in hc and "fusion" in hc["hlo_op_counts"]
    # CPU has no published MXU peak -> mfu is null, never a bogus number
    assert rec["mfu"] is None
    # the static roofline model of the timed executable: nominal CPU
    # peaks (clearly marked), a bound verdict, ranked contributors, and
    # flops/task agreeing with XLA's own count — the cross-check the
    # SPMD audit's roofline contract pins (acceptance: within 5%)
    roof = rec["roofline"]
    assert roof["nominal_peaks"] is True
    assert roof["bound"] in ("compute", "memory")
    assert roof["predicted_hfu"] is not None
    assert roof["top_contributors"]
    assert roof["flops_per_task"] == pytest.approx(
        rec["xla_flops_per_task"], rel=0.05
    )
    # non-TPU backends run the reduced workload and say so
    assert rec["reduced"] is True
    # the line is self-describing: the exact shapes that produced the number
    assert rec["workload"] == {
        "image": [16, 16, 3],
        "filters": 8,
        "stages": 4,
        "way": 5,
        "shot": 5,
        "targets": 15,
        "inner_steps": 2,
        "second_order": True,
    }


def test_cpu_fallback_workload_is_pinned():
    """The reduced-mode defaults are the driver's round-over-round CPU
    series; they must never drift (VERDICT r4: r03->r04 changed a fallback
    default mid-series and broke comparability)."""
    sys.path.insert(0, REPO)
    import bench as bench_mod

    assert bench_mod._CPU_FALLBACK_DEFAULTS == {
        "BENCH_WARMUP_STEPS": "1",
        "BENCH_TIMED_STEPS": "3",
        "BENCH_BATCH_SIZE": "2",
        "BENCH_CNN_NUM_FILTERS": "16",
        "BENCH_IMAGE_HEIGHT": "28",
        "BENCH_IMAGE_WIDTH": "28",
        "BENCH_NUMBER_OF_TRAINING_STEPS_PER_ITER": "3",
        "BENCH_USE_REMAT": "false",
    }


def test_workload_knobs_include_diet_env():
    """A diet-knob A/B run (BENCH_BN_STATS_IMPL etc.) is a sweep, not a
    default-knob run: it must never refresh the longitudinal baseline."""
    sys.path.insert(0, REPO)
    import bench as bench_mod

    for k in ("BENCH_BN_STATS_IMPL", "BENCH_IM2COL_HOIST",
              "BENCH_POOL_IMPL"):
        assert k in bench_mod._WORKLOAD_KNOBS


def test_bench_flops_model_is_sane():
    """The analytic FLOPs model should agree with a hand count on a small
    known config (one conv stage + head, max-pooling path)."""
    import bench as bench_mod

    sys.path.insert(0, REPO)
    from __graft_entry__ import _flagship_cfg

    cfg = _flagship_cfg(
        image_height=8,
        image_width=8,
        image_channels=3,
        num_stages=1,
        cnn_num_filters=4,
        num_classes_per_set=5,
    )
    # conv: 2 * H*W * k*k * cin * cout = 2*8*8*9*3*4; head on 4*4*4 feat
    expected = 2.0 * 8 * 8 * 9 * 3 * 4 + 2.0 * (4 * 4 * 4) * 5
    got = bench_mod.forward_flops_per_image(cfg)
    assert got == expected
    # train FLOPs must scale linearly in inner steps
    one = bench_mod.train_flops_per_task(
        _flagship_cfg(number_of_training_steps_per_iter=1)
    )
    five = bench_mod.train_flops_per_task(
        _flagship_cfg(number_of_training_steps_per_iter=5)
    )
    assert abs(five / one - 5.0) < 1e-9


def test_peak_flops_lookup():
    import bench as bench_mod

    assert bench_mod._peak_flops("TPU v5e", "bfloat16") == 197e12
    assert bench_mod._peak_flops("TPU v4", "float32") == 92e12
    assert bench_mod._peak_flops("cpu", "float32") is None


def test_graft_entry_fn_jits_and_runs():
    """entry() must return (fn, args) that jit-compiles and produces
    logits of shape (n*s, n) — the driver compile-checks exactly this."""
    import jax

    sys.path.insert(0, REPO)
    from __graft_entry__ import _flagship_cfg, entry

    fn, args = entry()
    out = jax.jit(fn)(*args)
    cfg = _flagship_cfg()
    n, s = cfg.num_classes_per_set, cfg.num_samples_per_class
    assert out.shape == (n * s, n)
    assert np.all(np.isfinite(np.asarray(out)))


@pytest.mark.slow
def test_bench_sweep_runs_and_ranks():
    """bench_sweep.py end-to-end on CPU with a clamped grid: the subprocess
    plumbing, per-point env assembly, error tolerance, and ranked table must
    be proven before the sweep gatekeeps real TPU time (round-3 verdict,
    weak #3)."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        BENCH_WARMUP_STEPS="1",
        BENCH_BATCH_SIZE="2",
        BENCH_CNN_NUM_FILTERS="8",
        BENCH_IMAGE_HEIGHT="16",
        BENCH_IMAGE_WIDTH="16",
        BENCH_NUMBER_OF_TRAINING_STEPS_PER_ITER="2",
        BENCH_NO_BASELINE_WRITE="1",
        BENCH_SWEEP_GRID="smoke",  # 2 points instead of 6
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [
            sys.executable,
            os.path.join("script_generation_tools", "bench_sweep.py"),
            "--steps", "2", "--timeout", "420",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=1200,
    )
    assert out.returncode == 0, f"sweep failed:\n{out.stderr[-3000:]}"
    assert "tasks/s/chip" in out.stdout  # table header printed
    assert "best (" in out.stdout  # at least one point succeeded + ranked
