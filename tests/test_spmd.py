"""SPMD performance-contract auditor (analysis/spmd.py + roofline.py).

Same three-layer structure as test_analysis.py:

* the canonical program family audits CLEAN under a real 2x4 hybrid
  (data, task) mesh — sharding, collective-census, HBM-budget and
  roofline contracts hold on all seven programs (the session-scoped
  ``spmd_audit_reports`` fixture compiles the family once);
* mutation tests — deliberately break ONE contract per throwaway program
  (batch sharding dropped, a replicated-store gather forced into the
  step, the HBM budget shrunk below the static peak, the device-peak
  table perturbed) and assert exactly that contract fires, no cross-talk;
* the pure helpers — replica-group parsing (iota + explicit forms),
  per-axis classification, shape-byte math, census compare semantics
  (growth fails, shrinkage silent), baseline merge, and the roofline
  flops cross-check against XLA's own cost analysis (the same figure
  bench.py records as ``xla_flops_per_task``).
"""

import json

import jax
import jax.numpy as jnp
import pytest
from conftest import make_micro_cfg
from jax.sharding import NamedSharding, PartitionSpec as P

from howtotrainyourmamlpytorch_tpu.analysis import contracts as contracts_lib
from howtotrainyourmamlpytorch_tpu.analysis import roofline as roofline_lib
from howtotrainyourmamlpytorch_tpu.analysis import spmd as spmd_lib
from howtotrainyourmamlpytorch_tpu.core import maml


@pytest.fixture(autouse=True)
def _require_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")


def _contracts_hit(report):
    return sorted({v.contract for v in report.violations})


def _sds(shape, dtype, mesh, tag):
    return spmd_lib._sharded(
        jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype)), mesh, tag
    )


# -- the family audits clean under the mesh ----------------------------------


def test_spmd_family_has_expected_programs(spmd_audit_reports):
    names = {r.program for r in spmd_audit_reports}
    assert names == {
        "train_step[so=1]",
        "train_multi_step[so=1,k=2]",
        "train_step_indexed[so=1]",
        "train_multi_step_indexed[so=1,k=2]",
        "eval_multi_step[k=2]",
        "index_expander",
        "serve_step[b=8]",
        "serve_step_uint8[b=8]",
        "predict_step[b=8]",
    }
    assert all(r.mesh_spec == "2x4" for r in spmd_audit_reports)


def test_spmd_family_audits_clean(spmd_audit_reports):
    for r in spmd_audit_reports:
        assert r.ok, f"{r.program}: {[str(v) for v in r.violations]}"
        assert r.contracts_checked == contracts_lib.SPMD_CONTRACT_NAMES


def test_train_steps_reduce_gradients_eval_reduces_metrics(
    spmd_audit_reports,
):
    """The expected collective profile: every train step all-reduces its
    meta-gradients (classified across the full 2x4 mesh — the global
    reduce spans both axes), eval all-reduces only its metric means, and
    the index expander — pure per-shard gather/decode against the
    replicated store — needs NO collectives at all (the residency
    claim, now machine-checked)."""
    by_name = {r.program: r for r in spmd_audit_reports}
    for name, r in by_name.items():
        if name.startswith("train"):
            ar = r.collectives.get("all-reduce", {})
            assert ar, f"{name}: no gradient all-reduce found"
            assert set(ar) <= {"both", "ici", "dcn"}
            assert sum(a["bytes"] for a in ar.values()) > 0
    assert by_name["index_expander"].collectives == {}
    eval_colls = by_name["eval_multi_step[k=2]"].collectives
    assert set(eval_colls) <= {"all-reduce"}


def test_spmd_reports_carry_hbm_and_roofline(spmd_audit_reports):
    for r in spmd_audit_reports:
        assert r.hbm is not None and r.hbm["peak_bytes"] > 0
        assert r.roofline is not None
        assert r.roofline["bound"] in ("compute", "memory")
        assert r.roofline["predicted_hfu"] is not None
        assert r.roofline["flops_per_task"] > 0
        assert r.roofline["top_contributors"], r.program


# -- mutation tests: each contract fires alone -------------------------------


def _mesh_and_auditor(**kw):
    cfg = make_micro_cfg(batch_size=8)
    mesh = spmd_lib.build_audit_mesh(2, 4)
    return cfg, mesh, spmd_lib.SpmdAuditor(cfg, mesh, **kw)


def test_sharding_contract_fires_when_batch_sharding_dropped():
    """A batch arg audited with its (data, task) sharding dropped — the
    `global_batch_sharding` placement gone, everything else intact — must
    trip the sharding contract and nothing else (the collective census
    SHRINKS in this mutation, which is never a violation)."""
    cfg, mesh, auditor = _mesh_and_auditor()

    def step(scale, x):
        return (x * scale).sum()

    scale = _sds((), jnp.float32, mesh, spmd_lib.REPLICATED)
    x_replicated = _sds((8, 4), jnp.float32, mesh, spmd_lib.REPLICATED)
    report = auditor.audit(
        "mutant_unsharded_batch", jax.jit(step), (scale, x_replicated),
        (spmd_lib.REPLICATED, spmd_lib.BATCH0),
    )
    assert _contracts_hit(report) == ["sharding"]
    assert "not sharded over (data, task)" in report.violations[0].detail

    # the same program with the contract placement audits clean
    x_sharded = _sds((8, 4), jnp.float32, mesh, spmd_lib.BATCH0)
    clean = auditor.audit(
        "sharded_batch", jax.jit(step), (scale, x_sharded),
        (spmd_lib.REPLICATED, spmd_lib.BATCH0),
    )
    assert clean.ok, [str(v) for v in clean.violations]


def test_collective_census_fires_on_forced_store_gather():
    """A replicated uint8 store forced through a task-sharded constraint
    and gathered inside the step — the exact 'accidental all-gather of
    the resident store' the SPMD auditor exists to catch — trips the
    collective census (uint8 data on the interconnect) and only it."""
    cfg, mesh, auditor = _mesh_and_auditor()

    def bad(store, idx):
        sharded = jax.lax.with_sharding_constraint(
            store,
            NamedSharding(mesh, P(("hosts", "tasks"))),
        )
        return sharded[idx]

    store = _sds((64, 8, 8, 1), jnp.uint8, mesh, spmd_lib.REPLICATED)
    idx = _sds((8, 4), jnp.int32, mesh, spmd_lib.BATCH0)
    report = auditor.audit(
        "mutant_store_gather", jax.jit(bad), (store, idx),
        (spmd_lib.REPLICATED, spmd_lib.BATCH0),
        expect_replicated_outputs=False,
        store_bytes=64 * 8 * 8,
    )
    assert _contracts_hit(report) == ["collective_census"]
    assert "uint8" in report.violations[0].detail

    # the clean gather — store replicated all the way, per-shard indexing
    def good(store, idx):
        return store[idx]

    clean = auditor.audit(
        "store_gather_clean", jax.jit(good), (store, idx),
        (spmd_lib.REPLICATED, spmd_lib.BATCH0),
        expect_replicated_outputs=False,
        store_bytes=64 * 8 * 8,
    )
    assert clean.ok, [str(v) for v in clean.violations]
    assert clean.collectives == {}


def test_hbm_budget_contract_fires_below_static_peak():
    """Shrinking hbm_budget_gb below the program's static per-device peak
    trips the HBM budget contract alone; a budget above it stays clean —
    and the budget default (0) disables the check entirely."""
    cfg, mesh, _ = _mesh_and_auditor()

    def step(scale, x):
        return (x * scale).sum()

    scale = _sds((), jnp.float32, mesh, spmd_lib.REPLICATED)
    x = _sds((8, 64), jnp.float32, mesh, spmd_lib.BATCH0)
    args = (scale, x)
    tags = (spmd_lib.REPLICATED, spmd_lib.BATCH0)

    tight = spmd_lib.SpmdAuditor(cfg, mesh, hbm_budget_gb=1e-9)
    report = tight.audit("mutant_oom", jax.jit(step), args, tags)
    assert _contracts_hit(report) == ["hbm_budget"]
    assert "exceeds hbm_budget_gb" in report.violations[0].detail

    roomy = spmd_lib.SpmdAuditor(cfg, mesh, hbm_budget_gb=16.0)
    assert roomy.audit("fits", jax.jit(step), args, tags).ok
    disabled = spmd_lib.SpmdAuditor(cfg, mesh, hbm_budget_gb=0.0)
    assert disabled.audit("off", jax.jit(step), args, tags).ok


def test_roofline_contract_fires_on_perturbed_peak_table():
    """A device-peak table with a zeroed/broken entry for the current
    backend must fail the roofline cross-check — and ONLY it: the same
    program under the stock table audits clean."""
    cfg, mesh, _ = _mesh_and_auditor()

    def step(scale, x):
        return (x * scale).sum()

    scale = _sds((), jnp.float32, mesh, spmd_lib.REPLICATED)
    x = _sds((8, 16), jnp.float32, mesh, spmd_lib.BATCH0)
    args = (scale, x)
    tags = (spmd_lib.REPLICATED, spmd_lib.BATCH0)

    bad_peaks = [{
        "kind": "cpu", "flops": {"float32": 0.0},
        "hbm_bytes_per_s": 0.0, "nominal": True,
    }]
    perturbed = spmd_lib.SpmdAuditor(cfg, mesh, peaks=bad_peaks)
    report = perturbed.audit("mutant_peaks", jax.jit(step), args, tags)
    assert _contracts_hit(report) == ["roofline"]
    assert "device-peak table" in report.violations[0].detail

    stock = spmd_lib.SpmdAuditor(cfg, mesh)
    assert stock.audit("stock_peaks", jax.jit(step), args, tags).ok


def test_collective_census_regression_fires_and_shrink_does_not(
    spmd_micro_cfg, spmd_audit_reports,
):
    """Mesh-keyed baseline semantics: a pinned census with FEWER
    collective bytes/counts than the program flags a regression; a pinned
    census with MORE (the program improved) stays silent."""
    import dataclasses

    fingerprint = contracts_lib.config_fingerprint(
        dataclasses.asdict(spmd_micro_cfg)
    )
    train = next(
        r for r in spmd_audit_reports if r.program == "train_step[so=1]"
    )

    def baseline_with(collectives):
        return {
            "version": 1,
            "jax": jax.__version__,
            "backend": "cpu",
            "config_fingerprint": fingerprint,
            "programs": {
                contracts_lib.spmd_census_key(
                    "train_step[so=1]", "cpu", "2x4"
                ): {"census": {}, "collectives": collectives},
            },
        }

    shrunk = {
        op: {
            axis: {"count": max(0, s["count"] - 1),
                   "bytes": max(0, s["bytes"] - 1)}
            for axis, s in by_axis.items()
        }
        for op, by_axis in train.collectives.items()
    }
    grown = {
        op: {
            axis: {"count": s["count"] + 5, "bytes": s["bytes"] + 4096}
            for axis, s in by_axis.items()
        }
        for op, by_axis in train.collectives.items()
    }

    mesh = spmd_lib.build_audit_mesh(2, 4)
    regressed = spmd_lib.SpmdAuditor(
        spmd_micro_cfg, mesh, baseline=baseline_with(shrunk),
        config_fingerprint=fingerprint,
    )
    reports = spmd_lib.audit_spmd_programs(
        spmd_micro_cfg, mesh=mesh, auditor=regressed,
        programs=["train_step[so=1]"],
    )
    assert _contracts_hit(reports[0]) == ["collective_census"]
    assert "regression" in reports[0].violations[0].detail

    improved = spmd_lib.SpmdAuditor(
        spmd_micro_cfg, mesh, baseline=baseline_with(grown),
        config_fingerprint=fingerprint,
    )
    reports = spmd_lib.audit_spmd_programs(
        spmd_micro_cfg, mesh=mesh, auditor=improved,
        programs=["train_step[so=1]"],
    )
    assert reports[0].ok, [str(v) for v in reports[0].violations]


# -- pure helpers ------------------------------------------------------------


def test_parse_mesh_spec():
    assert spmd_lib.parse_mesh_spec("1x8") == (1, 8)
    assert spmd_lib.parse_mesh_spec("2X4") == (2, 4)
    for bad in ("8", "0x8", "2x0", "ax8", "2x4x2", ""):
        with pytest.raises(ValueError, match="mesh spec"):
            spmd_lib.parse_mesh_spec(bad)


def test_hlo_shape_bytes():
    assert contracts_lib.hlo_shape_bytes("f32[8,4]") == 128
    assert contracts_lib.hlo_shape_bytes("f32[8,4]{1,0}") == 128
    assert contracts_lib.hlo_shape_bytes("bf16[10]") == 20
    assert contracts_lib.hlo_shape_bytes("u8[64,8,8,1]") == 4096
    assert contracts_lib.hlo_shape_bytes("f32[]") == 4
    assert contracts_lib.hlo_shape_bytes("(f32[2]{0}, u8[4]{0})") == 12
    assert contracts_lib.hlo_shape_bytes("pred[16]") == 16


def test_parse_replica_groups_iota_and_explicit():
    # [2,4]<=[8]: ids 0..7 in 2 groups of 4 (per-row / ICI)
    assert contracts_lib.parse_replica_groups(
        ", replica_groups=[2,4]<=[8], use_global_device_ids=true"
    ) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    # [4,2]<=[2,4]T(1,0): transpose -> per-column (DCN) groups
    assert contracts_lib.parse_replica_groups(
        ", replica_groups=[4,2]<=[2,4]T(1,0)"
    ) == [[0, 4], [1, 5], [2, 6], [3, 7]]
    # one global group
    assert contracts_lib.parse_replica_groups(
        ", replica_groups=[1,8]<=[8]"
    ) == [[0, 1, 2, 3, 4, 5, 6, 7]]
    # explicit form
    assert contracts_lib.parse_replica_groups(
        ", replica_groups={{0,1},{2,3}}, to_apply=%add"
    ) == [[0, 1], [2, 3]]
    assert contracts_lib.parse_replica_groups(", to_apply=%add") is None


def test_classify_replica_groups():
    classify = contracts_lib.classify_replica_groups
    # 2x4 mesh: rows = data (DCN), columns within a row = task (ICI)
    assert classify([[0, 1, 2, 3], [4, 5, 6, 7]], 2, 4) == "ici"
    assert classify([[0, 4], [1, 5], [2, 6], [3, 7]], 2, 4) == "dcn"
    assert classify([[0, 1, 2, 3, 4, 5, 6, 7]], 2, 4) == "both"
    assert classify(None, 2, 4) == "unknown"
    # degenerate meshes: singleton groups still classify by the only axis
    assert classify([[0], [1]], 1, 8) == "ici"
    assert classify([[0], [1]], 8, 1) == "dcn"


def test_compare_collective_census_semantics():
    pinned = {"all-reduce": {"ici": {"count": 2, "bytes": 100}}}
    same = contracts_lib.compare_collective_census(
        {"all-reduce": {"ici": {"count": 2, "bytes": 100}}}, pinned
    )
    assert same == []
    shrink = contracts_lib.compare_collective_census(
        {"all-reduce": {"ici": {"count": 1, "bytes": 50}}}, pinned
    )
    assert shrink == []
    grow = contracts_lib.compare_collective_census(
        {"all-reduce": {"ici": {"count": 3, "bytes": 100}}}, pinned
    )
    assert grow and "count: 2 -> 3" in grow[0]
    new_axis = contracts_lib.compare_collective_census(
        {"all-gather": {"dcn": {"count": 1, "bytes": 8}}}, pinned
    )
    assert len(new_axis) == 2  # count 0->1 and bytes 0->8


def test_save_baseline_merges_mesh_and_plain_entries(tmp_path):
    """`cli audit --pin` and `cli audit --mesh RxC --pin` compose: pinning
    mesh entries preserves the plain program entries (same jax/backend/
    fingerprint) instead of clobbering them — and vice versa."""
    path = str(tmp_path / "CONTRACTS.json")

    def rep(program, collectives=None):
        r = contracts_lib.SpmdAuditReport(
            program=program, backend="cpu",
            contracts_checked=contracts_lib.SPMD_CONTRACT_NAMES,
            census={"dot": 3},
        ) if collectives is not None else contracts_lib.AuditReport(
            program=program, backend="cpu",
            contracts_checked=contracts_lib.CONTRACT_NAMES,
            census={"dot": 3},
        )
        if collectives is not None:
            r.collectives = collectives
        return r

    contracts_lib.save_baseline(
        path, jax_version=jax.__version__, backend="cpu",
        config_fingerprint="f00d", reports=[rep("train_step[so=1]")],
    )
    colls = {"all-reduce": {"ici": {"count": 1, "bytes": 64}}}
    data = contracts_lib.save_baseline(
        path, jax_version=jax.__version__, backend="cpu",
        config_fingerprint="f00d",
        reports=[rep("train_step[so=1]", colls)],
        mesh_spec="1x8",
    )
    assert set(data["programs"]) == {
        "train_step[so=1]@cpu", "train_step[so=1]@cpu@1x8",
    }
    assert data["programs"]["train_step[so=1]@cpu@1x8"][
        "collectives"
    ] == colls
    # a FOREIGN prior baseline (different fingerprint) is replaced whole
    data = contracts_lib.save_baseline(
        path, jax_version=jax.__version__, backend="cpu",
        config_fingerprint="0ther", reports=[rep("train_step[so=1]")],
    )
    assert set(data["programs"]) == {"train_step[so=1]@cpu"}


# -- roofline ----------------------------------------------------------------


def test_roofline_flops_per_task_matches_cost_analysis(micro_cfg):
    """The cross-check the acceptance criterion pins: the roofline's
    flops/task must agree with XLA's own cost analysis of the same
    executable — the figure bench.py records as ``xla_flops_per_task`` —
    within 5% (they derive from the same surface, so in practice
    exactly)."""
    from howtotrainyourmamlpytorch_tpu.analysis import auditor as audit_lib

    state = audit_lib._state_avals(micro_cfg)
    batch = audit_lib._batch_avals(micro_cfg)
    weights = jax.ShapeDtypeStruct((2,), jnp.float32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    step = jax.jit(
        maml.make_train_step(micro_cfg, second_order=True),
        donate_argnums=maml.TRAIN_DONATE,
    )
    compiled = step.trace(state, *batch, weights, lr).lower().compile()
    ca = contracts_lib.cost_analysis_dict(compiled)
    xla_flops_per_task = float(ca["flops"]) / micro_cfg.batch_size
    report = roofline_lib.roofline_report(
        compiled,
        device_kind=jax.devices()[0].device_kind,
        dtype=micro_cfg.compute_dtype,
        tasks=micro_cfg.batch_size,
    )
    assert report["flops_per_task"] == pytest.approx(
        xla_flops_per_task, rel=0.05
    )
    # the agreement IS the contract: verify_roofline passes with the
    # recorded figure and fails against a figure 20% off
    assert roofline_lib.verify_roofline(
        report, "train_step", reference_flops_per_task=xla_flops_per_task
    ) == []
    bad = roofline_lib.verify_roofline(
        report, "train_step",
        reference_flops_per_task=xla_flops_per_task * 1.2,
    )
    assert bad and bad[0].contract == "roofline"
    assert "disagrees" in bad[0].detail


def test_roofline_decomposition_ranks_real_work(micro_cfg):
    """The decomposition covers most of the cost-analysis flops (dot +
    elementwise recovery), ranks contributors by predicted time with
    shares summing to ~1, and excludes free aliasing ops."""
    def f(x, w):
        y = jnp.tanh(x @ w)
        return (y * y).sum()

    compiled = (
        jax.jit(f)
        .trace(
            jax.ShapeDtypeStruct((64, 64), jnp.float32),
            jax.ShapeDtypeStruct((64, 64), jnp.float32),
        )
        .lower()
        .compile()
    )
    report = roofline_lib.roofline_report(
        compiled, device_kind="cpu", dtype="float32", tasks=1,
    )
    assert report["nominal_peaks"] is True
    assert 0.5 < report["flops_coverage"] < 2.0
    tops = report["top_contributors"]
    assert tops == sorted(tops, key=lambda c: c["seconds"], reverse=True)
    assert all(c["op"] not in roofline_lib._FREE_OPS for c in tops)
    assert sum(c["time_share"] for c in tops) <= 1.001
    # the dot carries essentially all recovered flops
    census = roofline_lib.op_cost_census(compiled.as_text())
    assert census["dot"]["flops"] >= 2 * 64 * 64 * 64


def test_peak_flops_nominal_entries_are_not_quotable():
    """bench.py's quoted MFU denominator: real TPU entries resolve, the
    nominal CPU entry and unknown hardware return None."""
    assert roofline_lib.peak_flops("TPU v5 lite", "bfloat16") == 197e12
    assert roofline_lib.peak_flops("TPU v5p", "float32") == 153e12
    assert roofline_lib.peak_flops("cpu", "float32") is None
    assert roofline_lib.peak_flops("TPU v99", "bfloat16") is None
    # the roofline itself still finds the nominal entry
    assert roofline_lib.find_peak_entry("cpu")["nominal"] is True


# -- cli audit --mesh --------------------------------------------------------


@pytest.mark.slow
def test_cli_audit_mesh_end_to_end(tmp_path, spmd_micro_cfg, capsys):
    """`cli audit --mesh 2x4 --pin` writes mesh-keyed entries; the
    follow-up `--mesh 2x4 --json` compares clean against them, reports
    collectives + hbm + roofline per program, and exits 0."""
    import dataclasses

    from howtotrainyourmamlpytorch_tpu.tools import audit_cli

    cfg_path = tmp_path / "audit_cfg.json"
    with open(cfg_path, "w") as f:
        json.dump(dataclasses.asdict(spmd_micro_cfg), f)
    contracts_path = tmp_path / "CONTRACTS.json"
    rc = audit_cli.main([
        "--config", str(cfg_path), "--contracts", str(contracts_path),
        "--mesh", "2x4", "--pin",
    ])
    assert rc == 0
    pinned = contracts_lib.load_baseline(str(contracts_path))
    assert pinned is not None and len(pinned["programs"]) == 7
    assert all(key.endswith("@2x4") for key in pinned["programs"])
    capsys.readouterr()
    rc = audit_cli.main([
        "--config", str(cfg_path), "--contracts", str(contracts_path),
        "--mesh", "2x4", "--json", "--hbm-budget-gb", "8",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    payload = json.loads(out[out.index("{"):])
    assert payload["mesh"] == "2x4"
    for name, prog in payload["programs"].items():
        assert prog["ok"], (name, prog["violations"])
        assert prog["hbm"]["peak_bytes"] > 0
        assert prog["roofline"]["bound"] in ("compute", "memory")
    train = payload["programs"]["train_step[so=1]"]
    assert train["collectives"]["all-reduce"]
    # an impossible budget makes the same audit fail with exit code 1
    rc = audit_cli.main([
        "--config", str(cfg_path), "--contracts", str(contracts_path),
        "--mesh", "2x4", "--hbm-budget-gb", "1e-9",
    ])
    assert rc == 1


def test_pinned_repo_baseline_has_mesh_entries():
    """CONTRACTS.json at the repo root carries the 1x8 mesh-keyed SPMD
    entries next to the nine single-device ones (the `cli audit --mesh
    1x8` CI gate compares against them)."""
    baseline = contracts_lib.load_baseline()
    assert baseline is not None, "CONTRACTS.json missing at the repo root"
    mesh_keys = [k for k in baseline["programs"] if k.endswith("@1x8")]
    plain_keys = [k for k in baseline["programs"] if "@" not in k.replace(
        "@cpu", "", 1
    )]
    assert len(mesh_keys) == 9
    assert len(plain_keys) == 9
    train_key = contracts_lib.spmd_census_key(
        "train_step[so=1]", "cpu", "1x8"
    )
    assert "collectives" in baseline["programs"][train_key]
