"""Reference-checkpoint import parity: converted weights must produce the
SAME logits as the reference PyTorch model (the strongest cross-framework
equivalence check — exercises layout transposes, the NCHW/NHWC flatten
permutation into the linear head, and per-step BN parameter mapping).

The reference implementation is imported read-only from /root/reference at
test time (skipped when unavailable); nothing is copied."""

import os
import sys
import types

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.models import vgg
from howtotrainyourmamlpytorch_tpu.tools.import_torch_checkpoint import (
    convert_network_state,
)

from conftest import REFERENCE_ROOT, needs_torch

needs_reference = pytest.mark.skipif(
    not os.path.isfile(
        os.path.join(REFERENCE_ROOT, "meta_neural_network_architectures.py")
    ),
    reason="reference implementation not available",
)


def _ref_args(cfg: MAMLConfig):
    return types.SimpleNamespace(
        norm_layer=cfg.norm_layer,
        cnn_num_filters=cfg.cnn_num_filters,
        num_stages=cfg.num_stages,
        conv_padding=cfg.conv_padding,
        per_step_bn_statistics=cfg.per_step_bn_statistics,
        number_of_training_steps_per_iter=cfg.number_of_training_steps_per_iter,
        learnable_bn_gamma=cfg.learnable_bn_gamma,
        learnable_bn_beta=cfg.learnable_bn_beta,
        enable_inner_loop_optimizable_bn_params=(
            cfg.enable_inner_loop_optimizable_bn_params
        ),
        learnable_batch_norm_momentum=False,
        max_pooling=cfg.max_pooling,
        device="cpu",
        meta_learning_rate=cfg.meta_learning_rate,
    )


def _build_reference_net(cfg: MAMLConfig):
    sys.path.insert(0, REFERENCE_ROOT)
    try:
        from meta_neural_network_architectures import VGGReLUNormNetwork
    finally:
        sys.path.pop(0)
    h, w, c = cfg.im_shape
    return VGGReLUNormNetwork(
        im_shape=(2, c, h, w),
        num_output_classes=cfg.num_classes_per_set,
        args=_ref_args(cfg),
        device="cpu",
        meta_classifier=True,
    )


def _cfg(**kw):
    base = dict(
        dataset_name="omniglot_dataset",
        image_height=14, image_width=14, image_channels=1,
        num_classes_per_set=5, cnn_num_filters=8, num_stages=2,
        conv_padding=True, per_step_bn_statistics=True,
        number_of_training_steps_per_iter=3,
        number_of_evaluation_steps_per_iter=3,
        max_pooling=True,
    )
    base.update(kw)
    return MAMLConfig(**base)


@needs_reference
@needs_torch
@pytest.mark.parametrize(
    "kw",
    [
        dict(max_pooling=True, per_step_bn_statistics=True),
        dict(max_pooling=False, per_step_bn_statistics=True),
        dict(max_pooling=True, per_step_bn_statistics=False),
    ],
    ids=["maxpool+perstep", "strided+perstep", "maxpool+plain-bn"],
)
def test_converted_weights_reproduce_reference_logits(kw):
    import torch

    cfg = _cfg(**kw)
    torch.manual_seed(0)
    net = _build_reference_net(cfg)
    state_dict = {
        k: v.detach().numpy() for k, v in net.state_dict().items()
    }
    import jax.numpy as jnp

    params, bn_state, _ = convert_network_state(cfg, state_dict)
    params = {k: jnp.asarray(v) for k, v in params.items()}
    bn_state = {k: jnp.asarray(v) for k, v in bn_state.items()}

    # same random input through both frameworks, every BN step index
    rng = np.random.RandomState(1)
    h, w, c = cfg.im_shape
    x_nchw = rng.randn(6, c, h, w).astype(np.float32)
    x_nhwc = np.transpose(x_nchw, (0, 2, 3, 1))
    for step in range(cfg.number_of_training_steps_per_iter):
        with torch.no_grad():
            ref_logits = net.forward(
                torch.from_numpy(x_nchw), num_step=step, training=True,
            ).numpy()
        ours, _ = vgg.apply(cfg, params, bn_state, x_nhwc, step, training=True)
        np.testing.assert_allclose(
            np.asarray(ours), ref_logits, atol=2e-4, rtol=1e-3,
            err_msg=f"step {step}",
        )


@needs_torch
def test_export_import_roundtrip_exact():
    """export -> import must reproduce every leaf bit-exactly (the layout
    permutations are mutual inverses)."""
    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.tools.export_torch_checkpoint import (
        convert_to_reference_state,
    )

    cfg = _cfg(
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
    )
    state = maml.init_state(cfg, seed=3)
    # make leaves distinguishable from init constants
    net = {
        k: np.asarray(v) + 0.01 * i
        for i, (k, v) in enumerate(sorted(state.net.items()))
    }
    bn = {k: np.asarray(v) + 0.5 for k, v in state.bn.items()}
    lslr = {
        k: np.asarray(v) * (i + 1)
        for i, (k, v) in enumerate(sorted(state.lslr.items()))
    }
    ref_sd = convert_to_reference_state(cfg, net, bn, lslr)
    net2, bn2, lslr2 = convert_network_state(cfg, ref_sd)
    assert set(net2) == set(net) and set(bn2) == set(bn) and set(lslr2) == set(lslr)
    for k in net:
        np.testing.assert_array_equal(net2[k], net[k], err_msg=k)
    for k in bn:
        np.testing.assert_array_equal(bn2[k], bn[k], err_msg=k)
    for k in lslr:
        np.testing.assert_array_equal(lslr2[k], lslr[k], err_msg=k)


@needs_reference
@needs_torch
def test_exported_weights_load_into_reference_model():
    """An exported state_dict loads into the actual reference model via
    load_state_dict and reproduces OUR logits — the export-direction parity."""
    import torch

    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.tools.export_torch_checkpoint import (
        convert_to_reference_state,
    )

    cfg = _cfg(per_step_bn_statistics=True, max_pooling=True)
    state = maml.init_state(cfg, seed=7)
    ref_sd = convert_to_reference_state(
        cfg, state.net, state.bn, state.lslr
    )
    net = _build_reference_net(cfg)
    classifier_sd = {
        k[len("classifier."):]: torch.from_numpy(v)
        for k, v in ref_sd.items()
        if k.startswith("classifier.")
    }
    net.load_state_dict(classifier_sd)

    rng = np.random.RandomState(5)
    h, w, c = cfg.im_shape
    x_nchw = rng.randn(6, c, h, w).astype(np.float32)
    x_nhwc = np.transpose(x_nchw, (0, 2, 3, 1))
    ours, _ = vgg.apply(cfg, state.net, state.bn, x_nhwc, 0, training=True)
    with torch.no_grad():
        ref_logits = net.forward(
            torch.from_numpy(x_nchw), num_step=0, training=True
        ).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref_logits, atol=2e-4, rtol=1e-3)

    # the synthesized Adam payload must load into an optimizer with the
    # reference system's trainable-parameter arity (classifier + LSLR)
    from howtotrainyourmamlpytorch_tpu.tools.export_torch_checkpoint import (
        _fresh_adam_state_dict,
    )

    trainable = [p for p in net.parameters() if p.requires_grad]
    lslr_dummies = [
        torch.nn.Parameter(torch.zeros(1)) for _ in state.lslr
    ] if cfg.learnable_per_layer_per_step_inner_loop_learning_rate else []
    ref_adam = torch.optim.Adam(trainable + lslr_dummies, lr=1e-3)
    ref_adam.load_state_dict(_fresh_adam_state_dict(cfg, state))


@needs_reference
@needs_torch
def test_full_system_checkpoint_roundtrip(tmp_path):
    """A reference-style checkpoint payload (system state_dict incl. LSLR +
    experiment scalars) imports into a loadable MetaState."""
    import torch

    from howtotrainyourmamlpytorch_tpu.tools.import_torch_checkpoint import (
        import_torch_checkpoint,
    )

    cfg = _cfg()
    torch.manual_seed(0)
    net = _build_reference_net(cfg)
    payload_net = {
        f"classifier.{k}": v for k, v in net.state_dict().items()
    }
    # LSLR entries exactly as the reference system writes them
    # (inner_loop_optimizers.py:86-91: inner-param names with '.' -> '-',
    # one (steps+1,) vector each; note the reference's 'linear.weights')
    for ref_name in (
        "layer_dict-conv0-conv-weight", "layer_dict-conv0-conv-bias",
        "layer_dict-conv1-conv-weight", "layer_dict-conv1-conv-bias",
        "layer_dict-linear-weights", "layer_dict-linear-bias",
    ):
        payload_net[
            f"inner_loop_optimizer.names_learning_rates_dict.{ref_name}"
        ] = torch.full((cfg.number_of_training_steps_per_iter + 1,), 0.4)
    payload = {
        "network": payload_net,
        "optimizer": {"ignored": True},
        "current_iter": 1500,
        "best_val_acc": 0.77,
    }
    path = tmp_path / "train_model_latest_ref"
    torch.save(payload, str(path))

    state, experiment_state = import_torch_checkpoint(cfg, str(path))
    assert experiment_state["current_iter"] == 1500
    assert experiment_state["best_val_acc"] == 0.77
    assert set(state.lslr) == {
        "conv0.conv.weight", "conv0.conv.bias", "conv1.conv.weight",
        "conv1.conv.bias", "linear.weight", "linear.bias",
    }
    np.testing.assert_allclose(np.asarray(state.lslr["conv0.conv.weight"]), 0.4)

    # eval steps > train steps: per-step BN arrays pad to bn_num_steps by
    # repeating the final step's row (the reference sizes them by train steps)
    cfg5 = cfg.replace(number_of_evaluation_steps_per_iter=5)
    state5, _ = import_torch_checkpoint(cfg5, str(path))
    g = np.asarray(state5.net["conv0.norm.gamma"])
    assert g.shape[0] == 5
    np.testing.assert_array_equal(g[3], g[2])
    np.testing.assert_array_equal(g[4], g[2])
    m = np.asarray(state5.bn["conv0.norm.mean"])
    assert m.shape[0] == 5
