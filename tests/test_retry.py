"""Retry/backoff policy (resilience/retry.py) and the retrying checkpoint
I/O seams, incl. the corrupt-checkpoint diagnosis (CheckpointCorruptError)."""

import json
import os
import shutil

import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.resilience import (
    RetriesExhaustedError,
    RetryPolicy,
    faults,
)


@pytest.fixture(autouse=True)
def _clean_injector():
    faults.uninstall()
    yield
    faults.uninstall()


# -- the policy ---------------------------------------------------------------


def test_backoff_sequence_is_deterministic_and_capped():
    p = RetryPolicy(max_attempts=6, backoff_s=0.5, factor=2.0,
                    max_backoff_s=3.0, sleep=lambda s: None)
    assert [p.backoff_for(a) for a in range(1, 6)] == [
        0.5, 1.0, 2.0, 3.0, 3.0  # capped, no jitter
    ]


def test_retries_oserror_until_success_and_observes_each_attempt():
    slept, observed = [], []
    p = RetryPolicy(max_attempts=4, backoff_s=0.5, factor=2.0,
                    sleep=slept.append, observer=lambda **kw: observed.append(kw))
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise OSError(f"transient {calls[0]}")
        return "ok"

    assert p.call(flaky, site="ckpt_save") == "ok"
    assert calls[0] == 3
    assert slept == [0.5, 1.0]  # deterministic exponential sequence
    assert [(o["site"], o["attempt"], o["max_attempts"]) for o in observed] \
        == [("ckpt_save", 1, 4), ("ckpt_save", 2, 4)]
    assert all(o["backoff_s"] > 0 for o in observed)


def test_exhausted_budget_raises_with_cause_and_final_observation():
    observed = []
    p = RetryPolicy(max_attempts=2, backoff_s=0.0,
                    observer=lambda **kw: observed.append(kw))

    def always():
        raise OSError("still down")

    with pytest.raises(RetriesExhaustedError) as ei:
        p.call(always, site="stats_write")
    assert ei.value.site == "stats_write"
    assert ei.value.attempts == 2
    assert isinstance(ei.value.__cause__, OSError)
    # the exhausted final attempt is observed too (the log tells the
    # whole story): attempts 1 and 2, the last with zero backoff
    assert [o["attempt"] for o in observed] == [1, 2]
    assert observed[-1]["backoff_s"] == 0.0


def test_non_oserror_is_never_retried():
    calls = [0]
    p = RetryPolicy(max_attempts=5, backoff_s=0.0)

    def bug():
        calls[0] += 1
        raise RuntimeError("logic bug")

    with pytest.raises(RuntimeError, match="logic bug"):
        p.call(bug, site="ckpt_save")
    assert calls[0] == 1


def test_from_config_and_validation():
    cfg = MAMLConfig(io_retry_attempts=5, io_retry_backoff_s=0.25,
                     io_retry_backoff_factor=3.0)
    p = RetryPolicy.from_config(cfg, sleep=lambda s: None)
    assert (p.max_attempts, p.backoff_s, p.factor) == (5, 0.25, 3.0)
    with pytest.raises(ValueError, match="io_retry_attempts"):
        MAMLConfig(io_retry_attempts=0)
    with pytest.raises(ValueError, match="io_retry_backoff_factor"):
        MAMLConfig(io_retry_backoff_factor=0.5)
    with pytest.raises(ValueError, match="max_attempts"):
        RetryPolicy(max_attempts=0)


def test_observer_failure_never_masks_the_seam():
    def broken_observer(**kw):
        raise ValueError("observer bug")

    p = RetryPolicy(max_attempts=2, backoff_s=0.0, observer=broken_observer)
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 2:
            raise OSError("transient")
        return "ok"

    assert p.call(flaky, site="x") == "ok"


# -- checkpoint seam integration ---------------------------------------------


def test_checkpoint_save_recovers_below_retry_budget(tiny_cfg, tmp_path):
    """Injected OSErrors on the first two save attempts, 3-attempt budget:
    the retried save succeeds and the checkpoint round-trips."""
    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    state = maml.init_state(tiny_cfg, seed=1)
    faults.install("ckpt_save:oserror@call=1x2")
    p = RetryPolicy(max_attempts=3, backoff_s=0.0)
    path = p.call(
        lambda: ckpt.save_checkpoint_async(
            str(tmp_path), "train_model", 1, state, {"current_iter": 4}
        ),
        site="ckpt_save",
    )
    ckpt.wait_for_pending()
    assert os.path.isdir(path)
    restored, exp = ckpt.load_checkpoint(
        str(tmp_path), "train_model", 1, maml.init_state(tiny_cfg)
    )
    assert exp == {"current_iter": 4}


def test_checkpoint_restore_fault_is_retryable(tiny_cfg, tmp_path):
    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    state = maml.init_state(tiny_cfg, seed=1)
    ckpt.save_checkpoint_async(
        str(tmp_path), "train_model", 1, state, {"current_iter": 4}
    )
    ckpt.wait_for_pending()
    faults.install("ckpt_restore:oserror@call=1")
    p = RetryPolicy(max_attempts=2, backoff_s=0.0)
    restored, exp = p.call(
        lambda: ckpt.load_checkpoint(
            str(tmp_path), "train_model", 1, maml.init_state(tiny_cfg)
        ),
        site="ckpt_restore",
    )
    assert exp["current_iter"] == 4


# -- corrupt checkpoints (satellite) -----------------------------------------


def _save_epochs(cfg, tmp_path, idxs):
    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    state = maml.init_state(cfg, seed=1)
    for idx in idxs:
        ckpt.save_checkpoint_async(
            str(tmp_path), "train_model", idx, state, {"current_iter": 1}
        )
    ckpt.wait_for_pending()
    return state


def test_corrupt_checkpoint_raises_named_error_with_fallbacks(
    tiny_cfg, tmp_path
):
    """A partially-written checkpoint directory must raise
    CheckpointCorruptError naming the path and the surviving siblings —
    not an opaque orbax traceback."""
    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    _save_epochs(tiny_cfg, tmp_path, [2, 3, "latest"])
    # simulate the partial write a crash leaves: the array payload is gone
    shutil.rmtree(str(tmp_path / "train_model_2" / "state"))
    with pytest.raises(ckpt.CheckpointCorruptError) as ei:
        ckpt.load_checkpoint(
            str(tmp_path), "train_model", 2, maml.init_state(tiny_cfg)
        )
    msg = str(ei.value)
    assert str(tmp_path / "train_model_2") in msg
    assert "3" in ei.value.fallbacks and "latest" in ei.value.fallbacks
    assert "2" not in ei.value.fallbacks
    # the named fallback still loads
    ckpt.load_checkpoint(
        str(tmp_path), "train_model", 3, maml.init_state(tiny_cfg)
    )


def test_truncated_experiment_state_is_reported_corrupt(tiny_cfg, tmp_path):
    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    _save_epochs(tiny_cfg, tmp_path, [1])
    with open(tmp_path / "train_model_1" / "experiment_state.json", "w") as f:
        f.write('{"current_iter": 4')  # crash mid-write
    with pytest.raises(ckpt.CheckpointCorruptError, match="corrupt"):
        ckpt.load_checkpoint(
            str(tmp_path), "train_model", 1, maml.init_state(tiny_cfg)
        )


def test_missing_checkpoint_stays_file_not_found(tiny_cfg, tmp_path):
    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    with pytest.raises(FileNotFoundError):
        ckpt.load_checkpoint(
            str(tmp_path), "train_model", 7, maml.init_state(tiny_cfg)
        )


def test_peek_experiment_state(tiny_cfg, tmp_path):
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    _save_epochs(tiny_cfg, tmp_path, ["emergency"])
    # enrich the JSON the way the preemption path does
    p = tmp_path / "train_model_emergency" / "experiment_state.json"
    state = json.loads(p.read_text())
    state["emergency_reason"] = "preemption"
    p.write_text(json.dumps(state))
    peeked = ckpt.peek_experiment_state(
        str(tmp_path), "train_model", "emergency"
    )
    assert peeked["emergency_reason"] == "preemption"
    assert ckpt.peek_experiment_state(str(tmp_path), "train_model", 9) is None
    assert ckpt.list_checkpoints(str(tmp_path), "train_model") == ["emergency"]
