"""Test environment: CPU backend with 8 virtual devices.

Multi-chip sharding paths are validated without TPU hardware by forcing the
host platform to present 8 devices (the TPU-native answer to testing
multi-device code on one machine — SURVEY.md §4). Must run before jax's
first import anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

os.environ.setdefault(
    # hermeticity: a TUNING.json a developer measured at the repo root
    # must not flip `auto` lowering resolution under the test suite (the
    # autotune tests point this at their own tmp tables explicitly)
    "MAML_TUNING_TABLE",
    os.path.join(os.path.dirname(__file__), "_no_tuning_table.json"),
)

import jax

# The sandbox's sitecustomize registers an experimental TPU-tunnel backend
# and force-updates jax_platforms at interpreter start, overriding the env
# var above; re-update so tests never try to initialise the tunnel.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig

REFERENCE_ROOT = "/root/reference"
OMNIGLOT_PATH = os.path.join(REFERENCE_ROOT, "datasets", "omniglot_dataset")

needs_omniglot = pytest.mark.skipif(
    not os.path.isdir(OMNIGLOT_PATH), reason="omniglot dataset not available"
)
needs_torch = pytest.mark.skipif(
    not bool(__import__("importlib").util.find_spec("torch")),
    reason="torch (oracle) not available",
)


@pytest.fixture
def tiny_cfg() -> MAMLConfig:
    """A minimal MAML++ config: all MAML++ mechanisms on, tiny shapes."""
    return MAMLConfig(
        dataset_name="omniglot_dataset",
        image_height=14,
        image_width=14,
        image_channels=1,
        num_classes_per_set=4,
        num_samples_per_class=1,
        num_target_samples=2,
        batch_size=4,
        cnn_num_filters=6,
        num_stages=2,
        max_pooling=False,
        conv_padding=True,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True,
        second_order=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        multi_step_loss_num_epochs=3,
        total_epochs=5,
        total_iter_per_epoch=4,
        use_remat=False,
    )


def make_synthetic_batch(cfg: MAMLConfig, batch_size=None, seed=0):
    """A deterministic synthetic task batch, NHWC, (x_s, y_s, x_t, y_t)."""
    rng = np.random.RandomState(seed)
    b = batch_size or cfg.batch_size
    n = cfg.num_classes_per_set
    s, t = cfg.num_samples_per_class, cfg.num_target_samples
    h, w, c = cfg.im_shape
    # class-dependent means so tasks are learnable
    means = rng.randn(b, n, 1, 1, 1, 1).astype(np.float32)
    x_s = rng.randn(b, n, s, h, w, c).astype(np.float32) * 0.1 + means
    x_t = rng.randn(b, n, t, h, w, c).astype(np.float32) * 0.1 + means
    y_s = np.tile(np.arange(n, dtype=np.int32)[None, :, None], (b, 1, s))
    y_t = np.tile(np.arange(n, dtype=np.int32)[None, :, None], (b, 1, t))
    return x_s, y_s, x_t, y_t


@pytest.fixture
def synthetic_batch():
    return make_synthetic_batch


def make_micro_cfg(**overrides) -> MAMLConfig:
    """The smallest config that still exercises every MAML++ mechanism
    (second order, MSL, learnable LSLR, per-step BN) — used where many
    programs must compile (the program-contract audits)."""
    base = dict(
        dataset_name="omniglot_dataset",
        image_height=8,
        image_width=8,
        image_channels=1,
        num_classes_per_set=2,
        num_samples_per_class=1,
        num_target_samples=1,
        batch_size=2,
        cnn_num_filters=4,
        num_stages=1,
        max_pooling=False,
        conv_padding=True,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True,
        second_order=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        multi_step_loss_num_epochs=3,
        total_epochs=5,
        total_iter_per_epoch=4,
        use_remat=False,
    )
    base.update(overrides)
    return MAMLConfig(**base)


@pytest.fixture(scope="session")
def micro_cfg() -> MAMLConfig:
    return make_micro_cfg()


@pytest.fixture(scope="session")
def audit_reports(micro_cfg):
    """One audit of the canonical program family (4 donating train-step
    jits + fused eval multi-step + index expander + serving step), compiled
    ONCE per test
    session and shared by the contract tests (test_analysis.py) and the
    donation-contract tests (test_donation.py)."""
    from howtotrainyourmamlpytorch_tpu.analysis import auditor as audit_lib

    return audit_lib.audit_system_programs(micro_cfg)


@pytest.fixture(scope="session")
def spmd_micro_cfg() -> MAMLConfig:
    """The micro config at a mesh-divisible batch (8 tasks over the 8
    virtual devices) — what the SPMD audits compile."""
    return make_micro_cfg(batch_size=8)


@pytest.fixture(scope="session")
def spmd_audit_reports(spmd_micro_cfg):
    """One SPMD audit of the canonical family under a 2x4 hybrid
    (data, task) mesh — both mesh axes exist, so the collective census
    exercises its ICI/DCN/both classification — compiled ONCE per test
    session and shared by test_spmd.py and the re-expressed sharding
    contract tests in test_parallel.py."""
    from howtotrainyourmamlpytorch_tpu.analysis import spmd as spmd_lib

    mesh = spmd_lib.build_audit_mesh(2, 4)
    auditor = spmd_lib.SpmdAuditor(spmd_micro_cfg, mesh)
    return spmd_lib.audit_spmd_programs(
        spmd_micro_cfg, mesh=mesh, auditor=auditor
    )
