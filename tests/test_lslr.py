"""LSLR inner-optimizer unit tests (inner_loop_optimizers.py:55-113)."""

import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.core import lslr


def test_init_shapes_and_value():
    # one (steps+1,) vector per adapted param, init at the task LR
    # (inner_loop_optimizers.py:86-91)
    p = lslr.init(["a", "b"], num_inner_steps=5, init_learning_rate=0.1)
    assert set(p) == {"a", "b"}
    for v in p.values():
        assert v.shape == (6,)
        np.testing.assert_allclose(v, 0.1)


def test_update_math_per_step():
    # theta' = theta - lr[name][step] * g (inner_loop_optimizers.py:108-113)
    weights = {"w": jnp.asarray([1.0, 2.0])}
    grads = {"w": jnp.asarray([0.5, -1.0])}
    lrs = {"w": jnp.asarray([0.1, 0.2, 0.3])}
    out0 = lslr.update_params(weights, grads, lrs, 0)
    np.testing.assert_allclose(out0["w"], [1 - 0.05, 2 + 0.1], rtol=1e-6)
    out1 = lslr.update_params(weights, grads, lrs, 1)
    np.testing.assert_allclose(out1["w"], [1 - 0.1, 2 + 0.2], rtol=1e-6)


def test_update_only_touches_given_keys():
    weights = {"w": jnp.ones(2), "v": jnp.ones(2)}
    grads = {"w": jnp.ones(2), "v": jnp.zeros(2)}
    lrs = {"w": jnp.asarray([0.5]), "v": jnp.asarray([0.5])}
    out = lslr.update_params(weights, grads, lrs, 0)
    np.testing.assert_allclose(out["w"], 0.5)
    np.testing.assert_allclose(out["v"], 1.0)


def test_sgd_update_math():
    # theta' = theta - eta * g (inner_loop_optimizers.py:39-52)
    weights = {"w": jnp.asarray([1.0, 2.0])}
    grads = {"w": jnp.asarray([0.5, -1.0])}
    out = lslr.sgd_update_params(weights, grads, 0.1)
    np.testing.assert_allclose(out["w"], [0.95, 2.1], rtol=1e-6)


@pytest.mark.slow
def test_sgd_mode_equals_nonlearnable_lslr(tiny_cfg, synthetic_batch):
    # slow lane: compiles two full second-order grads_fns; the SGD update
    # math itself is pinned by the fast test_sgd_update_math above.
    # fixed-LR GD == LSLR with all LRs at init (the reference's unused
    # GradientDescentLearningRule vs LSLRGradientDescentLearningRule at init)
    from howtotrainyourmamlpytorch_tpu.core import maml, msl

    cfg_lslr = tiny_cfg.replace(
        learnable_per_layer_per_step_inner_loop_learning_rate=False
    )
    cfg_sgd = cfg_lslr.replace(inner_loop_optimizer="sgd")
    x_s, y_s, x_t, y_t = synthetic_batch(cfg_lslr)
    w = jnp.asarray(
        msl.final_step_only(cfg_lslr.number_of_training_steps_per_iter)
    )
    state = maml.init_state(cfg_lslr)
    loss_a, grads_a = maml.make_grads_fn(cfg_lslr, second_order=True)(
        state, x_s, y_s, x_t, y_t, w
    )
    loss_b, grads_b = maml.make_grads_fn(cfg_sgd, second_order=True)(
        state, x_s, y_s, x_t, y_t, w
    )
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
    for k in grads_a["net"]:
        np.testing.assert_allclose(
            np.asarray(grads_a["net"][k]), np.asarray(grads_b["net"][k]),
            rtol=1e-5, atol=1e-6,
        )
