"""Episodic data pipeline tests: deterministic seeding, resume continuity,
reference quirks (Omniglot [0,255] pixels, fixed val stream, test==val seed)."""

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.data import datasets as ds
from howtotrainyourmamlpytorch_tpu.data.episodes import sample_episode
from howtotrainyourmamlpytorch_tpu.data.loader import MetaLearningDataLoader

from conftest import OMNIGLOT_PATH, needs_omniglot


def _synthetic_classes(n_classes=10, per_class=7, h=8, w=8, c=1, seed=0):
    rng = np.random.RandomState(seed)
    return {
        str(i): rng.randn(per_class, h, w, c).astype(np.float32)
        for i in range(n_classes)
    }


def _cfg(**kw):
    base = dict(
        dataset_name="synthetic", image_height=8, image_width=8,
        image_channels=1, num_classes_per_set=3, num_samples_per_class=2,
        num_target_samples=2,
    )
    base.update(kw)
    return MAMLConfig(**base)


def test_same_seed_same_episode():
    cfg = _cfg()
    classes = _synthetic_classes()
    keys = np.array(list(classes.keys()))
    e1 = sample_episode(cfg, classes, keys, seed=42, augment=False)
    e2 = sample_episode(cfg, classes, keys, seed=42, augment=False)
    np.testing.assert_array_equal(e1.x_support, e2.x_support)
    np.testing.assert_array_equal(e1.y_target, e2.y_target)


def test_different_seed_different_episode():
    cfg = _cfg()
    classes = _synthetic_classes()
    keys = np.array(list(classes.keys()))
    e1 = sample_episode(cfg, classes, keys, seed=1, augment=False)
    e2 = sample_episode(cfg, classes, keys, seed=2, augment=False)
    assert not np.array_equal(e1.x_support, e2.x_support)


def test_episode_shapes_and_labels():
    cfg = _cfg()
    classes = _synthetic_classes()
    keys = np.array(list(classes.keys()))
    e = sample_episode(cfg, classes, keys, seed=0, augment=False)
    n, s, t = 3, 2, 2
    assert e.x_support.shape == (n, s, 8, 8, 1)
    assert e.x_target.shape == (n, t, 8, 8, 1)
    # episode labels are the remap 0..n-1 in selected order (data.py:491-493)
    np.testing.assert_array_equal(e.y_support[:, 0], np.arange(n))
    np.testing.assert_array_equal(e.y_target[:, 0], np.arange(n))


def test_stream_seeds_test_equals_val():
    """data.py:132-142 — test stream seed == val stream seed."""
    cfg = _cfg(train_seed=0, val_seed=0)
    seeds = ds.draw_stream_seeds(cfg)
    assert seeds["test"] == seeds["val"]
    cfg2 = _cfg(train_seed=3, val_seed=5)
    seeds2 = ds.draw_stream_seeds(cfg2)
    assert seeds2["test"] == seeds2["val"]
    assert seeds2["val"] != seeds["val"]


def test_ratio_split_partitions_all_classes():
    cfg = _cfg(train_val_test_split=[0.6, 0.2, 0.2])
    index = {str(i): [f"img{i}_{j}" for j in range(5)] for i in range(20)}
    splits = ds.split_classes(cfg, index, {}, val_stream_seed=7)
    total = sum(len(v) for v in splits.values())
    assert total == 20
    assert len(splits["train"]) == 12
    all_keys = set()
    for s in splits.values():
        assert not (all_keys & set(s))
        all_keys |= set(s)


def test_presplit_mode_uses_path_prefix():
    cfg = _cfg(sets_are_pre_split=True)
    index = {"0": ["a"], "1": ["b"], "2": ["c"]}
    idx_to_label = {0: "train/cls_a", 1: "val/cls_b", 2: "test/cls_c"}
    splits = ds.split_classes(cfg, index, idx_to_label, val_stream_seed=0)
    assert splits["train"] == {"cls_a": ["a"]}
    assert splits["val"] == {"cls_b": ["b"]}
    assert splits["test"] == {"cls_c": ["c"]}


@needs_omniglot
def test_omniglot_load_matches_reference_pipeline(tmp_path):
    """Reference quirk: Omniglot load is LANCZOS resize + float32 with NO
    rescaling division (data.py:383-387). The source PNGs are 1-bit, so the
    resulting values are exactly the resized binary mask as float."""
    from howtotrainyourmamlpytorch_tpu.data.episodes import load_image
    import glob
    from PIL import Image

    cfg = _cfg(dataset_name="omniglot_dataset", image_height=28, image_width=28)
    path = glob.glob(OMNIGLOT_PATH + "/*/*/*/*.png")[0]
    img = load_image(cfg, path)
    assert img.shape == (28, 28, 1)
    # independent oracle: the reference's exact load sequence
    expected = np.array(
        Image.open(path).resize((28, 28), resample=Image.LANCZOS), np.float32
    )[:, :, None]
    np.testing.assert_array_equal(img, expected)


@needs_omniglot
def test_loader_resume_continuity(tmp_path):
    """A loader resumed at iter k must produce exactly the batch a
    continuous run would produce as its (k+1)-th (data.py:583-602)."""
    cfg = MAMLConfig(
        dataset_name="omniglot_dataset", dataset_path=OMNIGLOT_PATH,
        train_val_test_split=[0.70918052988, 0.03080714725, 0.2606284658],
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=14, image_width=14, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=1, num_target_samples=1,
        batch_size=2, num_dataprovider_workers=2,
        cache_dir=str(tmp_path),
    )
    continuous = MetaLearningDataLoader(cfg, current_iter=0, cache_dir=str(tmp_path))
    batches = list(continuous.get_train_batches(total_batches=3))
    resumed = MetaLearningDataLoader(cfg, current_iter=2, cache_dir=str(tmp_path))
    (resumed_batch,) = list(resumed.get_train_batches(total_batches=1))
    np.testing.assert_array_equal(batches[2][0], resumed_batch[0])
    np.testing.assert_array_equal(batches[2][4], resumed_batch[4])  # seeds


@needs_omniglot
def test_val_stream_identical_every_call(tmp_path):
    """Val tasks are the same every epoch (data.py:538-539)."""
    cfg = MAMLConfig(
        dataset_name="omniglot_dataset", dataset_path=OMNIGLOT_PATH,
        train_val_test_split=[0.70918052988, 0.03080714725, 0.2606284658],
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=14, image_width=14, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=1, num_target_samples=1,
        batch_size=2, num_dataprovider_workers=2,
        cache_dir=str(tmp_path),
    )
    loader = MetaLearningDataLoader(cfg, cache_dir=str(tmp_path))
    a = list(loader.get_val_batches(total_batches=2))
    b = list(loader.get_val_batches(total_batches=2))
    np.testing.assert_array_equal(a[0][0], b[0][0])
    np.testing.assert_array_equal(a[1][4], b[1][4])


def test_rotation_augment_only_when_enabled():
    cfg = _cfg(dataset_name="omniglot_dataset")
    classes = _synthetic_classes()
    keys = np.array(list(classes.keys()))
    # same seed: augmented vs not differ only by rotations; rng stream
    # still advances identically (k always drawn, data.py:489-490)
    e_aug = sample_episode(cfg, classes, keys, seed=5, augment=True)
    e_plain = sample_episode(cfg, classes, keys, seed=5, augment=False)
    np.testing.assert_array_equal(e_aug.y_support, e_plain.y_support)
    # replicate the rng stream to recover each class's rotation k
    rng = np.random.RandomState(5)
    selected = rng.choice(keys, size=3, replace=False)
    rng.shuffle(selected)
    k_list = rng.randint(0, 4, size=3)
    for i, k in enumerate(k_list):
        np.testing.assert_array_equal(
            e_aug.x_support[i, 0], np.rot90(e_plain.x_support[i, 0], k=k)
        )


@needs_omniglot
def test_loader_host_shards_reassemble_global_batch(tmp_path):
    """Multi-host slicing: the concatenation of every host's slice must be
    bit-identical to the single-host batch (global-index seed discipline)."""
    cfg = MAMLConfig(
        dataset_name="omniglot_dataset", dataset_path=OMNIGLOT_PATH,
        train_val_test_split=[0.70918052988, 0.03080714725, 0.2606284658],
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=14, image_width=14, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=1, num_target_samples=1,
        batch_size=4, num_dataprovider_workers=2,
        cache_dir=str(tmp_path),
    )
    single = MetaLearningDataLoader(
        cfg, cache_dir=str(tmp_path), shard_id=0, num_shards=1
    )
    (full,) = list(single.get_train_batches(total_batches=1))
    shards = []
    for p in range(2):
        loader = MetaLearningDataLoader(
            cfg, cache_dir=str(tmp_path), shard_id=p, num_shards=2
        )
        assert loader.tasks_per_shard == 2
        (b,) = list(loader.get_train_batches(total_batches=1))
        shards.append(b)
    for i in range(5):  # x_s, x_t, y_s, y_t, seeds
        np.testing.assert_array_equal(
            full[i], np.concatenate([shards[0][i], shards[1][i]], axis=0)
        )


def test_loader_rejects_indivisible_shards(tmp_path):
    cfg = MAMLConfig(
        dataset_name="omniglot_dataset",
        dataset_path=OMNIGLOT_PATH,
        train_val_test_split=[0.70918052988, 0.03080714725, 0.2606284658],
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=14, image_width=14, image_channels=1,
        num_classes_per_set=3, num_samples_per_class=1, num_target_samples=1,
        batch_size=3, cache_dir=str(tmp_path),
    )
    import pytest as _pytest
    with _pytest.raises(ValueError, match="not divisible"):
        MetaLearningDataLoader(
            cfg, cache_dir=str(tmp_path), shard_id=0, num_shards=2
        )


def test_reverse_channels_flips_rgb_order():
    """reverse_channels flips RGB->BGR on decoded-but-unnormalized values
    (ref data.py:442-457, preprocess_data after load_batch's decode/scale)."""
    from howtotrainyourmamlpytorch_tpu.data.episodes import decode_cached

    cfg = _cfg(
        dataset_name="mini_imagenet", image_channels=3, reverse_channels=True
    )
    arr = np.arange(2 * 2 * 3, dtype=np.uint8).reshape(2, 2, 3)
    out = decode_cached(cfg, arr)
    expected = (arr.astype(np.float32) / 255.0)[..., ::-1]
    np.testing.assert_allclose(out, expected)
    # flag off: untouched
    cfg_off = _cfg(dataset_name="mini_imagenet", image_channels=3)
    np.testing.assert_allclose(
        decode_cached(cfg_off, arr), arr.astype(np.float32) / 255.0
    )


def test_reverse_channels_in_episode_before_normalization():
    """The mmap-cache fast path (uint8 stores) reverses channels BEFORE the
    ImageNet-stat normalization, matching the reference's order (load_batch
    -> preprocess_data -> get_set's normalize): normalize(reverse(x)), not
    reverse(normalize(x))."""
    from howtotrainyourmamlpytorch_tpu.data.episodes import (
        IMAGENET_MEAN,
        IMAGENET_STD,
    )

    cfg = _cfg(
        dataset_name="mini_imagenet", image_channels=3, reverse_channels=True
    )
    rng = np.random.RandomState(0)
    classes = {
        str(i): rng.randint(0, 255, (7, 8, 8, 3), dtype=np.uint8)
        for i in range(6)
    }
    keys = np.array(list(classes.keys()))
    ep = sample_episode(cfg, classes, keys, seed=11, augment=False)
    cfg_off = _cfg(dataset_name="mini_imagenet", image_channels=3)
    ep_off = sample_episode(cfg_off, classes, keys, seed=11, augment=False)
    # undo the off-run's normalization, reverse, re-normalize == on-run
    raw = ep_off.x_support * IMAGENET_STD + IMAGENET_MEAN
    expected = (raw[..., ::-1] - IMAGENET_MEAN) / IMAGENET_STD
    np.testing.assert_allclose(ep.x_support, expected, rtol=1e-5, atol=1e-6)
