"""The roofline-driven step autotuner (analysis/autotune.py + cli tune).

Covers the stdlib table half (validate / load / lookup / merge), the
config consult (``'auto'`` resolution reads the measured winner for this
device kind + dtype, heuristic fallback otherwise), the roofline
cross-check, and — slow-marked — the ``cli tune --fast`` end-to-end sweep
(two real bench.py subprocesses on the CPU backend producing a valid
table, the CI bench-smoke gate's twin).
"""

import json
import os
import subprocess
import sys

import pytest

from howtotrainyourmamlpytorch_tpu.analysis import autotune
from howtotrainyourmamlpytorch_tpu.config import MAMLConfig


def _valid_table(entries=None):
    return {
        "version": autotune.TUNING_VERSION,
        "entries": entries if entries is not None else {
            "TPU v5 lite@bfloat16": {
                "conv_impl": "gemm",
                "pad_channels": "tile",
                "remat_policy": "save_conv",
                "meta_accum_steps": 2,
                "tasks_per_sec_per_chip": 57.9,
            },
        },
    }


def _write(tmp_path, data, name="TUNING.json"):
    path = os.path.join(str(tmp_path), name)
    with open(path, "w") as f:
        json.dump(data, f)
    autotune.clear_cache()
    return path


# -- table format -------------------------------------------------------------


def test_validate_accepts_valid_table():
    autotune.validate_tuning_table(_valid_table())


@pytest.mark.parametrize("mutate", [
    lambda t: t.update(version=99),
    lambda t: t.update(entries={}),
    lambda t: t["entries"].update({"no-at-sign": {
        "conv_impl": "gemm", "pad_channels": "tile",
        "remat_policy": "full", "meta_accum_steps": 1,
        "tasks_per_sec_per_chip": 1.0}}),
    lambda t: t["entries"]["TPU v5 lite@bfloat16"].update(
        conv_impl="winograd"),
    lambda t: t["entries"]["TPU v5 lite@bfloat16"].update(
        pad_channels="maybe"),
    lambda t: t["entries"]["TPU v5 lite@bfloat16"].update(
        remat_policy="sometimes"),
    lambda t: t["entries"]["TPU v5 lite@bfloat16"].update(
        meta_accum_steps=0),
    lambda t: t["entries"]["TPU v5 lite@bfloat16"].update(
        tasks_per_sec_per_chip=-1),
])
def test_validate_rejects_malformed_tables(mutate):
    table = _valid_table()
    mutate(table)
    with pytest.raises(ValueError):
        autotune.validate_tuning_table(table)


def test_load_returns_none_for_missing_or_invalid(tmp_path, capsys):
    assert autotune.load_tuning_table(
        os.path.join(str(tmp_path), "absent.json")
    ) is None
    bad = _write(tmp_path, {"version": 99, "entries": {}}, "bad.json")
    assert autotune.load_tuning_table(bad) is None
    assert "ignoring invalid tuning table" in capsys.readouterr().err
    good = _write(tmp_path, _valid_table(), "good.json")
    assert autotune.load_tuning_table(good) is not None


def test_tuned_entry_exact_and_substring_match(tmp_path):
    path = _write(tmp_path, _valid_table())
    entry = autotune.tuned_entry("TPU v5 lite", "bfloat16", path=path)
    assert entry is not None and entry["conv_impl"] == "gemm"
    # relaxed device-kind matching, same as the roofline peak table
    entry = autotune.tuned_entry(
        "TPU v5 litepod slice", "bfloat16", path=path
    )
    assert entry is not None
    # dtype must match exactly: a bf16 tuning never serves f32 configs
    assert autotune.tuned_entry("TPU v5 lite", "float32", path=path) is None
    assert autotune.tuned_entry("TPU v4", "bfloat16", path=path) is None


def test_build_table_picks_best_and_merges():
    existing = _valid_table()
    results = [
        {"value": 10.0, "device_kind": "cpu", "dtype": "float32",
         "mfu": None, "backend": "cpu", "batch_size": 2, "reduced": True,
         "point": {"conv_impl": "im2col", "pad_channels": "off",
                   "remat_policy": "full", "meta_accum_steps": 1}},
        {"value": 12.5, "device_kind": "cpu", "dtype": "float32",
         "mfu": None, "backend": "cpu", "batch_size": 2, "reduced": True,
         "point": {"conv_impl": "gemm", "pad_channels": "off",
                   "remat_policy": "full", "meta_accum_steps": 2}},
    ]
    table = autotune.build_table(results, existing=existing)
    autotune.validate_tuning_table(table)
    # the faster point won
    assert table["entries"]["cpu@float32"]["conv_impl"] == "gemm"
    assert table["entries"]["cpu@float32"]["meta_accum_steps"] == 2
    # the foreign device entry survived the merge
    assert "TPU v5 lite@bfloat16" in table["entries"]


def test_build_table_reduced_sweep_never_clobbers_full_entry(capsys):
    """A --fast (reduced-workload) smoke on an already-tuned host must
    keep the full-workload entry — the smoke proves the harness, not the
    tuning."""
    existing = {
        "version": autotune.TUNING_VERSION,
        "entries": {
            "cpu@float32": {
                "conv_impl": "gemm", "pad_channels": "tile",
                "remat_policy": "save_conv", "meta_accum_steps": 4,
                "tasks_per_sec_per_chip": 50.0, "reduced": False,
            },
        },
    }
    smoke = [{
        "value": 99.0, "device_kind": "cpu", "dtype": "float32",
        "reduced": True, "backend": "cpu", "batch_size": 2, "mfu": None,
        "point": {"conv_impl": "im2col", "pad_channels": "off",
                  "remat_policy": "full", "meta_accum_steps": 1},
    }]
    table = autotune.build_table(smoke, existing=existing)
    assert table["entries"]["cpu@float32"]["conv_impl"] == "gemm"
    assert "keeping the existing full-workload entry" in (
        capsys.readouterr().err
    )
    # a reduced sweep may still replace a reduced (or absent) entry
    table = autotune.build_table(smoke, existing=None)
    assert table["entries"]["cpu@float32"]["conv_impl"] == "im2col"


def test_build_table_records_the_clamped_accum_bench_measured():
    """bench.py clamps a point's accum to the largest batch divisor and
    reports the clamped value in its line; the table must record what was
    MEASURED, not what was requested."""
    rec = {
        "value": 10.0, "device_kind": "cpu", "dtype": "float32",
        "reduced": True, "backend": "cpu", "batch_size": 6, "mfu": None,
        "meta_accum_steps": 2,  # bench clamped the requested 4 to 2
        "point": {"conv_impl": "im2col", "pad_channels": "off",
                  "remat_policy": "full", "meta_accum_steps": 4},
    }
    table = autotune.build_table([rec])
    assert table["entries"]["cpu@float32"]["meta_accum_steps"] == 2


def test_cross_check_roofline_flags_disagreement():
    def rec(value, predicted, **point):
        base = {"conv_impl": "gemm", "pad_channels": "off",
                "remat_policy": "full", "meta_accum_steps": 1}
        base.update(point)
        return {
            "value": value, "batch_size": 4, "n_chips": 1,
            "roofline": {"predicted_step_seconds": predicted},
            "point": base,
        }

    agree = autotune.cross_check_roofline(
        [rec(10.0, 0.4), rec(20.0, 0.2, meta_accum_steps=2)]
    )
    assert agree["winner_agrees_with_roofline"] is True
    disagree = autotune.cross_check_roofline(
        [rec(10.0, 0.2), rec(20.0, 0.4, meta_accum_steps=2)]
    )
    assert disagree["winner_agrees_with_roofline"] is False
    assert disagree["predicted_winner"].startswith("conv_impl=gemm")


def test_measured_step_seconds():
    assert autotune.measured_step_seconds(
        {"value": 8.0, "batch_size": 4, "n_chips": 1}
    ) == pytest.approx(0.5)
    assert autotune.measured_step_seconds({"value": None}) is None


def test_sweep_points_fast_and_full():
    fast = autotune.sweep_points(fast=True)
    assert len(fast) == 2
    full = autotune.sweep_points(fast=False)
    # conv_impl x pad x remat x accum x bn_stats x pool (PR 16)
    assert len(full) == 3 * 2 * 2 * 3 * 2 * 2
    for p in full + fast:
        assert set(p) == set(autotune.SWEEP_KNOBS)
    # both fast points together cover both values of each diet axis, so
    # the CI smoke exercises both lowerings
    for knob in ("bn_stats_impl", "pool_impl"):
        assert len({p[knob] for p in fast}) == 2


def test_validate_diet_knobs_optional_but_checked():
    """Pre-PR-16 tables (no bn_stats_impl/pool_impl) stay loadable —
    `cli tune` output from an older checkout must not brick the consult —
    but when present the values are validated like every other knob."""
    autotune.validate_tuning_table(_valid_table())  # absent: fine
    table = _valid_table()
    table["entries"]["TPU v5 lite@bfloat16"].update(
        bn_stats_impl="fused", pool_impl="reshape")
    autotune.validate_tuning_table(table)  # present + valid: fine
    for bad in ({"bn_stats_impl": "onepass"}, {"pool_impl": "stride"}):
        table = _valid_table()
        table["entries"]["TPU v5 lite@bfloat16"].update(bad)
        with pytest.raises(ValueError):
            autotune.validate_tuning_table(table)


def test_build_table_records_diet_knobs():
    rec = {
        "value": 10.0, "device_kind": "cpu", "dtype": "float32",
        "reduced": True, "backend": "cpu", "batch_size": 2, "mfu": None,
        "bn_stats_impl": "fused", "pool_impl": "reshape",
        "point": {"conv_impl": "im2col", "pad_channels": "off",
                  "remat_policy": "full", "meta_accum_steps": 1,
                  "bn_stats_impl": "fused", "pool_impl": "reshape"},
    }
    table = autotune.build_table([rec])
    autotune.validate_tuning_table(table)
    entry = table["entries"]["cpu@float32"]
    assert entry["bn_stats_impl"] == "fused"
    assert entry["pool_impl"] == "reshape"


# -- config consult -----------------------------------------------------------


def _cpu_table(tmp_path, conv_impl="gemm", pad="tile", name="t.json"):
    import jax

    kind = jax.devices()[0].device_kind
    return _write(tmp_path, _valid_table({
        autotune.table_key(kind, "float32"): {
            "conv_impl": conv_impl,
            "pad_channels": pad,
            "remat_policy": "save_conv",
            "meta_accum_steps": 2,
            "tasks_per_sec_per_chip": 123.4,
        },
    }), name)


def test_auto_resolution_consults_tuning_table(tmp_path, monkeypatch):
    """`'auto'` resolves through the table: the measured winner for this
    device kind + dtype beats the heuristic (CPU heuristic would say
    im2col/off; a table saying gemm/tile wins)."""
    path = _cpu_table(tmp_path)
    monkeypatch.setenv(autotune.TUNING_TABLE_ENV, path)
    autotune.clear_cache()
    cfg = MAMLConfig(dataset_name="omniglot_dataset")
    assert cfg.resolved_conv_impl == "gemm"
    assert cfg.resolved_pad_channels == "tile"
    # explicit knobs still beat the table
    assert cfg.replace(conv_impl="lax").resolved_conv_impl == "lax"
    assert cfg.replace(pad_channels="off").resolved_pad_channels == "off"


def test_auto_resolution_falls_back_to_heuristic(tmp_path, monkeypatch):
    """No table / no entry / wrong dtype => the PR-4 heuristic (im2col +
    off on the CPU test backend)."""
    monkeypatch.setenv(
        autotune.TUNING_TABLE_ENV, os.path.join(str(tmp_path), "none.json")
    )
    autotune.clear_cache()
    cfg = MAMLConfig(dataset_name="omniglot_dataset")
    assert cfg.resolved_conv_impl == "im2col"
    assert cfg.resolved_pad_channels == "off"
    # entry pinned for bf16 only: an f32 config keeps the heuristic
    import jax

    kind = jax.devices()[0].device_kind
    path = _write(tmp_path, _valid_table({
        autotune.table_key(kind, "bfloat16"): {
            "conv_impl": "gemm", "pad_channels": "tile",
            "remat_policy": "full", "meta_accum_steps": 1,
            "tasks_per_sec_per_chip": 9.0,
        },
    }), "bf16only.json")
    monkeypatch.setenv(autotune.TUNING_TABLE_ENV, path)
    autotune.clear_cache()
    cfg = MAMLConfig(dataset_name="omniglot_dataset")
    assert cfg.resolved_conv_impl == "im2col"
    cfg_bf16 = MAMLConfig(
        dataset_name="omniglot_dataset", compute_dtype="bfloat16"
    )
    assert cfg_bf16.resolved_conv_impl == "gemm"


def test_corrupt_table_degrades_to_heuristic(tmp_path, monkeypatch):
    path = os.path.join(str(tmp_path), "corrupt.json")
    with open(path, "w") as f:
        f.write("{not json")
    monkeypatch.setenv(autotune.TUNING_TABLE_ENV, path)
    autotune.clear_cache()
    cfg = MAMLConfig(dataset_name="omniglot_dataset")
    assert cfg.resolved_conv_impl == "im2col"  # CPU heuristic, no crash


# -- cli tune end to end ------------------------------------------------------


@pytest.mark.slow
def test_cli_tune_fast_emits_valid_table(tmp_path):
    """The CI gate's twin: `cli tune --fast` runs the 2-point sweep with
    real bench.py subprocesses on the CPU backend and writes a valid
    device-keyed table whose entry the config consult then picks up."""
    out = os.path.join(str(tmp_path), "TUNING.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop(autotune.TUNING_TABLE_ENV, None)
    r = subprocess.run(
        [sys.executable, "-m", "howtotrainyourmamlpytorch_tpu.cli",
         "tune", "--fast", "--out", out, "--json"],
        capture_output=True, text=True, timeout=1200, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    with open(out) as f:
        table = json.load(f)
    autotune.validate_tuning_table(table)
    payload = json.loads(r.stdout)
    assert payload["table_path"] == out
    assert len(payload["ranking"]) >= 1
    # the CPU entry is keyed by the live device kind and resolvable
    autotune.clear_cache()
    entry = autotune.tuned_entry("cpu", "float32", table=table)
    assert entry is not None
    assert entry["conv_impl"] in ("lax", "im2col", "gemm")
