"""Crash-equivalence proof: kill the run (SIGTERM gracefully, SIGKILL
hard) at an arbitrary mid-epoch iteration, resume, and assert the final
params and the per-epoch statistics are bit-identical to an uninterrupted
run. Plus the transient-I/O-fault matrix: retried-through checkpoint
faults with zero data loss, degraded stats writes, and the dead-producer
fix — all on the CPU backend (the fast lane owns everything but the
mid-finalize SIGKILL)."""

import csv
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.data.loader import (
    MetaLearningDataLoader,
    ProducerCrashedError,
)
from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt
from howtotrainyourmamlpytorch_tpu.experiment.builder import ExperimentBuilder
from howtotrainyourmamlpytorch_tpu.experiment.system import MAMLFewShotClassifier
from howtotrainyourmamlpytorch_tpu.resilience import (
    PREEMPT_EXIT_CODE,
    PreemptedError,
    faults,
)

TOTAL_ITER_PER_EPOCH = 4
TOTAL_EPOCHS = 3
PREEMPT_ITER = 6  # mid-epoch 2: partial-epoch state must survive resume


def make_cfg(data_root, cache_dir, exp_root, exp_name, fault_spec="",
             total_epochs=TOTAL_EPOCHS, **overrides):
    """The one config recipe shared by the in-process runs AND the
    subprocess worker (tests/_resilience_worker.py imports it), so every
    compared run trains the identical program."""
    kwargs = dict(
        experiment_name=os.path.join(exp_root, exp_name),
        dataset_name="imagenet_synthetic_presplit",
        dataset_path=data_root,
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=8, image_width=8, image_channels=3,
        num_classes_per_set=2, num_samples_per_class=1, num_target_samples=1,
        batch_size=2, cnn_num_filters=4, num_stages=1, max_pooling=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        number_of_training_steps_per_iter=1,
        number_of_evaluation_steps_per_iter=1,
        second_order=False,
        total_epochs=total_epochs,
        total_iter_per_epoch=TOTAL_ITER_PER_EPOCH,
        num_evaluation_tasks=4,
        total_epochs_before_pause=100,
        num_dataprovider_workers=2,
        cache_dir=cache_dir,
        use_mmap_cache=True, use_remat=False, seed=0,
        telemetry_level="scalars",
        io_retry_backoff_s=0.0,  # tests never sleep
        # persistent compile cache DISABLED: on this jaxlib (0.4.37, CPU)
        # a resumed run that executes the donating train step deserialized
        # from the persistent cache flakily corrupts the CPU client
        # (segfault mid-run in long-lived processes, or in the atexit
        # clear_backends). Kill/resume tests resume constantly, so they
        # pay the few-second CPU recompile instead ('' = off; the 'auto'
        # default would re-enable it under the experiment dir).
        compilation_cache_dir="",
        fault_spec=fault_spec,
    )
    kwargs.update(overrides)
    return MAMLConfig(**kwargs)


def _write_presplit_rgb(root, n_classes=4, per_class=6, size=8, seed=0):
    rng = np.random.RandomState(seed)
    for set_name in ("train", "val", "test"):
        for ci in range(n_classes):
            d = os.path.join(root, set_name, f"n{ci:04d}")
            os.makedirs(d, exist_ok=True)
            base = rng.randint(0, 200)
            for j in range(per_class):
                arr = np.clip(
                    base + rng.randint(-30, 30, (size, size, 3)), 0, 255
                ).astype(np.uint8)
                Image.fromarray(arr, "RGB").save(os.path.join(d, f"im{j}.png"))


class _Env:
    """Shared dataset/cache/compile-cache plus the baseline run, built once
    per module (every test compares against the same uninterrupted run)."""

    def __init__(self, root):
        self.root = str(root)
        self.data_root = os.path.join(self.root, "imagenet_synthetic_presplit")
        self.cache_dir = os.path.join(self.root, "cache")
        _write_presplit_rgb(self.data_root)
        self.baseline = self.run("baseline")

    def cfg(self, exp_name, fault_spec="", **overrides):
        return make_cfg(
            self.data_root, self.cache_dir, self.root, exp_name,
            fault_spec=fault_spec, **overrides,
        )

    def build(self, exp_name, fault_spec="", **overrides):
        cfg = self.cfg(exp_name, fault_spec=fault_spec, **overrides)
        model = MAMLFewShotClassifier(cfg, use_mesh=False)
        return ExperimentBuilder(
            cfg, model, MetaLearningDataLoader,
            experiment_root=self.root, verbose=False,
        )

    def run(self, exp_name, fault_spec="", **overrides):
        builder = self.build(exp_name, fault_spec=fault_spec, **overrides)
        test_losses = builder.run_experiment()
        return builder, test_losses

    # -- comparison helpers -----------------------------------------------

    def exp_dir(self, exp_name):
        return os.path.join(self.root, exp_name)

    def final_state(self, exp_name, epoch=TOTAL_EPOCHS):
        from howtotrainyourmamlpytorch_tpu.core import maml

        state, exp = ckpt.load_checkpoint(
            os.path.join(self.exp_dir(exp_name), "saved_models"),
            "train_model", epoch,
            maml.init_state(self.cfg(exp_name + "_template")),
        )
        return state, exp

    @staticmethod
    def _deterministic_key(k):
        """Training-math columns; timing/stream/wall-clock columns are
        excluded — they can never be bit-stable across runs and are not
        part of the equivalence contract."""
        return (
            "loss" in k or "accuracy" in k or "learning_rate" in k
            or k == "epoch"
        )

    def det_rows(self, exp_name):
        """The deterministic columns of the summary CSV."""
        path = os.path.join(
            self.exp_dir(exp_name), "logs", "summary_statistics.csv"
        )
        with open(path) as f:
            rows = list(csv.DictReader(f))
        return [
            {k: v for k, v in row.items() if self._deterministic_key(k)}
            for row in rows
        ]

    def assert_equivalent(self, exp_name, epoch=TOTAL_EPOCHS):
        """Bit-identical final params + experiment state + per-epoch
        statistics vs the uninterrupted baseline."""
        import jax

        state_a, exp_a = self.final_state("baseline", epoch)
        state_b, exp_b = self.final_state(exp_name, epoch)
        for leaf_a, leaf_b in zip(
            jax.tree_util.tree_leaves(state_a._asdict()),
            jax.tree_util.tree_leaves(state_b._asdict()),
        ):
            np.testing.assert_array_equal(
                np.asarray(leaf_a), np.asarray(leaf_b)
            )
        det = lambda stats: {  # noqa: E731
            k: v for k, v in stats.items() if self._deterministic_key(k)
        }
        assert det(exp_a["per_epoch_statistics"]) == det(
            exp_b["per_epoch_statistics"]
        )
        assert exp_a["current_iter"] == exp_b["current_iter"]
        assert self.det_rows(exp_name) == self.det_rows("baseline")


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    faults.uninstall()
    e = _Env(tmp_path_factory.mktemp("resilience"))
    yield e
    faults.uninstall()


def _telemetry_records(env, exp_name):
    path = os.path.join(env.exp_dir(exp_name), "logs", "telemetry.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- SIGTERM: graceful preemption + bit-exact resume --------------------------


def test_sigterm_preempt_then_resume_is_bit_identical(env):
    # the preemption run: a self-delivered SIGTERM at the iter-6 dispatch
    # boundary (mid-epoch 2) must drain to a resumable emergency
    # checkpoint and exit with the distinct code
    builder = env.build("preempt", fault_spec=f"signal:sigterm@iter={PREEMPT_ITER}")
    with pytest.raises(PreemptedError) as ei:
        builder.run_experiment()
    assert ei.value.code == PREEMPT_EXIT_CODE
    assert ei.value.iter_at_preempt == PREEMPT_ITER

    saved = os.path.join(env.exp_dir("preempt"), "saved_models")
    emerg = ckpt.peek_experiment_state(saved, "train_model", "emergency")
    assert emerg["emergency_reason"] == "preemption"
    assert emerg["current_iter"] == PREEMPT_ITER
    assert emerg["preempt_signal"] == signal.SIGTERM
    # the partial epoch's metric history rides along for the resumed
    # run's epoch summary
    assert "loss" in emerg["inflight"]["total_losses"]

    # preemption is documented in the run's own log: a schema-valid
    # `preemption` record plus a forensic incident dir
    from howtotrainyourmamlpytorch_tpu.telemetry import schema

    log = os.path.join(env.exp_dir("preempt"), "logs", "telemetry.jsonl")
    schema.validate_file(log)
    records = _telemetry_records(env, "preempt")
    (preempt_rec,) = [r for r in records if r["kind"] == "preemption"]
    assert preempt_rec["iter"] == PREEMPT_ITER
    assert preempt_rec["signal"] == signal.SIGTERM
    incidents = [
        r for r in records
        if r["kind"] == "incident" and r["reason"] == "preemption"
    ]
    assert incidents and os.path.isdir(incidents[0]["path"])

    # resume (no fault spec, like a scheduler restart): picks the
    # emergency checkpoint over `latest` (iter 6 > 4) and completes
    builder2, test_losses2 = env.run("preempt")
    env.assert_equivalent("preempt")
    assert test_losses2 == env.baseline[1]
    # the consumed preemption emergency was pruned once epoch 2's
    # checkpoint superseded it
    assert not ckpt.checkpoint_exists(saved, "train_model", "emergency")


def test_inspect_summary_surfaces_preemption_and_retry_counts(env, capsys):
    from howtotrainyourmamlpytorch_tpu.tools import telemetry_cli

    log = os.path.join(env.exp_dir("preempt"), "logs", "telemetry.jsonl")
    assert telemetry_cli.main(["summary", log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["preemptions"] == 1
    assert payload["counts_by_kind"]["preemption"] == 1
    # human output names the resilience line too
    assert telemetry_cli.main(["summary", log]) == 0
    assert "preemption exits" in capsys.readouterr().out


# -- SIGKILL: hard kill + resume from `latest` --------------------------------


def _spawn_worker(env, exp_name, fault_spec, total_epochs=TOTAL_EPOCHS):
    worker = os.path.join(os.path.dirname(__file__), "_resilience_worker.py")
    return subprocess.run(
        [sys.executable, worker,
         "--data_root", env.data_root,
         "--cache_dir", env.cache_dir,
         "--exp_root", env.root,
         "--exp_name", exp_name,
         "--fault_spec", fault_spec,
         "--total_epochs", str(total_epochs)],
        capture_output=True, text=True, timeout=240,
    )


def test_sigkill_then_resume_is_bit_identical(env):
    """SIGKILL at a mid-epoch dispatch boundary — no handler, no drain, the
    process just dies. Resume from `latest` replays the partial epoch from
    the last boundary checkpoint; the deterministic episode stream makes
    the retrained run bit-identical to the uninterrupted baseline.

    Killed at iter 10 (mid-epoch 3): the epoch-2 boundary save at iter 8
    barriered the epoch-1 finalize before starting, so at the kill point a
    loadable ``latest`` provably exists (epoch 1 or 2 — whichever the
    still-async epoch-2 finalize reached; both resume equivalently)."""
    kill_iter = 2 * TOTAL_ITER_PER_EPOCH + 2
    proc = _spawn_worker(env, "hardkill", f"signal:sigkill@iter={kill_iter}")
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    assert "WORKER_DONE" not in proc.stdout

    saved = os.path.join(env.exp_dir("hardkill"), "saved_models")
    # nothing graceful happened: no emergency checkpoint; `latest` is a
    # boundary save (never the mid-epoch kill point)
    assert not ckpt.checkpoint_exists(saved, "train_model", "emergency")
    latest = ckpt.peek_experiment_state(saved, "train_model", "latest")
    assert latest["current_iter"] in (
        TOTAL_ITER_PER_EPOCH, 2 * TOTAL_ITER_PER_EPOCH,
    )

    builder2, test_losses2 = env.run("hardkill")
    assert builder2.start_epoch in (1, 2)  # resumed from a boundary save
    env.assert_equivalent("hardkill")
    assert test_losses2 == env.baseline[1]


@pytest.mark.slow
def test_sigkill_mid_async_finalize_then_resume(env):
    """PR 1's kill-mid-save crash-safety test, extended to the full builder
    loop with the fault harness driving the kill point: SIGKILL inside the
    async checkpoint finalizer thread (write done, tmp->final swap not).
    Whatever instant the kill hit, a resumed run must find a loadable
    state — `latest` or a clean from_scratch start — and end bit-identical
    to the baseline."""
    proc = _spawn_worker(env, "midfinalize", "ckpt_finalize:sigkill@call=1")
    assert proc.returncode == -signal.SIGKILL, proc.stderr

    saved = os.path.join(env.exp_dir("midfinalize"), "saved_models")
    if ckpt.checkpoint_exists(saved, "train_model", "latest"):
        latest = ckpt.peek_experiment_state(saved, "train_model", "latest")
        assert latest["current_iter"] % TOTAL_ITER_PER_EPOCH == 0
    builder2, test_losses2 = env.run("midfinalize")
    env.assert_equivalent("midfinalize")
    assert test_losses2 == env.baseline[1]


# -- transient I/O faults below the retry budget ------------------------------


def test_transient_ckpt_faults_below_budget_zero_data_loss(env):
    """First two checkpoint-save attempts and the first JSON mirror write
    fail with injected OSErrors; the 3-attempt budget absorbs them. The
    run completes with `retry` telemetry records and outputs bit-identical
    to the fault-free baseline — zero data loss."""
    builder, test_losses = env.run(
        "retryrun",
        fault_spec="ckpt_save:oserror@call=1x2,json_write:oserror@call=1",
    )
    env.assert_equivalent("retryrun")
    assert test_losses == env.baseline[1]
    records = _telemetry_records(env, "retryrun")
    retries = [r for r in records if r["kind"] == "retry"]
    assert {r["site"] for r in retries} == {"ckpt_save", "json_write"}
    assert len([r for r in retries if r["site"] == "ckpt_save"]) == 2
    from howtotrainyourmamlpytorch_tpu.telemetry import schema

    schema.validate_file(
        os.path.join(env.exp_dir("retryrun"), "logs", "telemetry.jsonl")
    )
    # the JSON mirror exists despite its first write failing
    assert os.path.isfile(os.path.join(
        env.exp_dir("retryrun"), "logs", "summary_statistics.json"
    ))


def test_exhausted_stats_writes_degrade_without_killing_the_run(env):
    """A permanently-broken stats CSV seam (every attempt fails) must not
    kill training: rows are skipped with retry records, the epoch data
    still lands in telemetry and the checkpoints."""
    builder, test_losses = env.run(
        "degraded",
        fault_spec="stats_write:oserror@call=1x999",
        io_retry_attempts=2,
        total_epochs=1,
    )
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0
    logs = env.exp_dir("degraded") + "/logs"
    assert not os.path.isfile(os.path.join(logs, "summary_statistics.csv"))
    records = _telemetry_records(env, "degraded")
    assert [r for r in records if r["kind"] == "retry"]
    # the epoch numbers survived in the telemetry twin
    assert [r for r in records if r["kind"] == "epoch"]


# -- the dead-producer fix ----------------------------------------------------


def test_producer_crash_fails_fast_and_poisons_later_pulls(env):
    """A producer thread that dies must surface its exception to the train
    loop (not hang until the watchdog) and re-raise from the next
    get_*_batches pull."""
    builder = env.build(
        "producer_crash", fault_spec="producer:raise@batch=1"
    )
    with pytest.raises(ProducerCrashedError, match="injected fault"):
        builder.run_experiment()
    # the loader is poisoned: the NEXT pull re-raises instead of blocking
    with pytest.raises(ProducerCrashedError):
        builder.data.get_val_batches(total_batches=1)
    with pytest.raises(ProducerCrashedError):
        builder.data.get_train_batches(total_batches=1)


def test_latched_producer_error_raises_from_next_pull(env):
    """The latch half of the fix, without a thread death: a latched error
    surfaces from the next pull even when no queue item ever carried it."""
    loader = MetaLearningDataLoader(
        env.cfg("latch_probe"), current_iter=0, cache_dir=env.cache_dir
    )
    loader._producer_error = RuntimeError("producer died off-queue")
    with pytest.raises(ProducerCrashedError, match="died off-queue"):
        loader.get_train_batches(total_batches=1)
