"""Experiment generator tests: the grid regenerates 36 schema-valid configs +
launch scripts (reference script_generation_tools/, SURVEY.md §2.1)."""

import os
import subprocess
import sys

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_generator_produces_full_grid(tmp_path):
    out = subprocess.run(
        [sys.executable, "script_generation_tools/generate_experiments.py",
         "--output_root", str(tmp_path)],
        cwd=REPO, capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    configs = sorted(os.listdir(tmp_path / "experiment_config"))
    scripts = sorted(os.listdir(tmp_path / "experiment_scripts"))
    # 3 seeds x (omniglot spc{1,5} x way{20,5} + mini-imagenet spc{1,5}) x
    # {maml, maml++} = 36 (generate_configs.py:30-36 grid), plus the
    # TPU large-meta-batch extra
    assert len(configs) == 37
    assert len(scripts) == 37
    large = [n for n in configs if "large_batch" in n]
    assert len(large) == 1
    lb = MAMLConfig.from_json_file(str(tmp_path / "experiment_config" / large[0]))
    assert lb.batch_size == 256 and lb.use_mmap_cache
    # every config loads through the typed schema and round-trips key fields
    for name in configs:
        cfg = MAMLConfig.from_json_file(str(tmp_path / "experiment_config" / name))
        assert cfg.total_epochs == 100 and cfg.total_iter_per_epoch == 500
        if "maml++" in name:
            assert cfg.use_multi_step_loss_optimization
            assert cfg.learnable_per_layer_per_step_inner_loop_learning_rate
            assert cfg.per_step_bn_statistics
        else:
            assert not cfg.use_multi_step_loss_optimization
    # scripts are executable and reference their config
    for name in scripts:
        path = tmp_path / "experiment_scripts" / name
        assert os.access(path, os.X_OK)
        body = path.read_text()
        assert "train_maml_system.py" in body


def test_checked_in_configs_match_schema():
    """The shipped experiment_config/ files stay loadable (the reference's 36
    JSONs are the user-facing interface)."""
    cfg_dir = os.path.join(REPO, "experiment_config")
    names = [n for n in os.listdir(cfg_dir) if n.endswith(".json")]
    assert len(names) == 37  # reference's 36-point grid + TPU large-batch
    for name in names:
        cfg = MAMLConfig.from_json_file(os.path.join(cfg_dir, name))
        assert cfg.num_classes_per_set in (5, 20)
