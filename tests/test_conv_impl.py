"""Equivalence of the conv lowerings and the pool formulations.

The im2col path (ops.functional.conv2d impl='im2col') exists because
XLA:CPU's kernel-gradient convolution profiles ~40x slower than the
same-FLOPs GEMM (see conv2d docstring); it must be numerically
interchangeable with the native conv at every AD order the framework uses
(forward, first-order inner grads, second-order meta-grads).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.ops import functional as F


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape).astype(np.float32))


@pytest.mark.parametrize("stride,padding", [(1, 1), (2, 1), (1, 0), (2, 0)])
def test_im2col_matches_lax_forward(stride, padding):
    x = _rand((3, 13, 13, 5), 0)
    w = _rand((3, 3, 5, 7), 1)
    b = _rand((7,), 2)
    out_lax = F.conv2d(x, w, b, stride, padding, impl="lax")
    out_im = F.conv2d(x, w, b, stride, padding, impl="im2col")
    assert out_lax.shape == out_im.shape
    np.testing.assert_allclose(out_lax, out_im, rtol=1e-5, atol=1e-5)


def test_im2col_matches_lax_first_and_second_order():
    x = _rand((2, 8, 8, 4), 3)
    w = _rand((3, 3, 4, 6), 4)

    def loss(impl):
        def f(w):
            return jnp.sum(F.conv2d(x, w, None, 1, 1, impl=impl) ** 2)

        return f

    g_lax = jax.grad(loss("lax"))(w)
    g_im = jax.grad(loss("im2col"))(w)
    np.testing.assert_allclose(g_lax, g_im, rtol=1e-4, atol=1e-4)

    # second order: grad of a scalar function of the grad (the structure the
    # second-order MAML outer step differentiates)
    def meta(impl):
        def f(w):
            g = jax.grad(lambda w_: jnp.sum(F.conv2d(x, w_, None, 1, 1, impl=impl) ** 2))(w)
            return jnp.sum(jnp.tanh(g))

        return f

    gg_lax = jax.grad(meta("lax"))(w)
    gg_im = jax.grad(meta("im2col"))(w)
    # double differentiation amplifies accumulation-order noise; the two
    # lowerings contract in different orders
    np.testing.assert_allclose(gg_lax, gg_im, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("hw", [(14, 14), (7, 7), (7, 9)])
def test_max_pool_reshape_matches_reduce_window(hw):
    """The reshape-max fast path must equal the reduce_window formulation,
    including VALID's drop of trailing odd rows/cols."""
    h, w = hw
    x = _rand((3, h, w, 5), 7)
    fast = F.max_pool2d(x)  # window == stride == 2 -> reshape path
    ref = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(ref))


def test_max_pool_gradient_matches_reduce_window():
    x = _rand((2, 8, 8, 3), 8)

    def f_fast(x):
        return jnp.sum(F.max_pool2d(x) ** 2)

    def f_ref(x):
        return jnp.sum(
            jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
            )
            ** 2
        )

    # continuous random input: ties have probability zero, so the two
    # formulations route identical gradients
    np.testing.assert_allclose(
        jax.grad(f_fast)(x), jax.grad(f_ref)(x), rtol=1e-6, atol=1e-6
    )


@pytest.mark.parametrize("stride,padding", [(1, 1), (2, 1), (1, 0), (2, 0)])
def test_gemm_matches_lax_forward(stride, padding):
    x = _rand((3, 13, 13, 5), 0)
    w = _rand((3, 3, 5, 7), 1)
    b = _rand((7,), 2)
    out_lax = F.conv2d(x, w, b, stride, padding, impl="lax")
    out_gemm = F.conv2d(x, w, b, stride, padding, impl="gemm")
    assert out_lax.shape == out_gemm.shape
    np.testing.assert_allclose(out_lax, out_gemm, rtol=1e-5, atol=1e-5)


def test_gemm_is_task_batched_under_vmap():
    """The property the TPU lowering depends on: vmap over per-task weights
    turns the gemm conv into ONE batched dot_general — (task, M, K) x
    (task, K, cout) — instead of a feature_group_count=tasks grouped conv.
    Checked both numerically (vs per-task lax convs) and structurally (the
    jaxpr contains a batched dot_general and no grouped convolution)."""
    import jax

    tasks = 4
    x = _rand((tasks, 2, 8, 8, 3), 5)
    w = _rand((tasks, 3, 3, 3, 6), 6)  # per-task adapted weights

    def gemm_call(xi, wi):
        return F.conv2d(xi, wi, None, 1, 1, impl="gemm")

    batched = jax.vmap(gemm_call)(x, w)
    for t in range(tasks):
        ref = F.conv2d(x[t], w[t], None, 1, 1, impl="lax")
        np.testing.assert_allclose(
            np.asarray(batched[t]), np.asarray(ref), rtol=1e-5, atol=1e-5
        )
    jaxpr = str(jax.make_jaxpr(jax.vmap(gemm_call))(x, w))
    assert "dot_general" in jaxpr
    assert "conv_general_dilated" not in jaxpr
    # the contraction is batched over the task axis, not grouped
    assert "feature_group_count" not in jaxpr


@pytest.mark.slow
def test_gemm_matches_lax_through_train_step(tiny_cfg, synthetic_batch):
    """Slow lane (compiles a full second-order step per impl); the forward
    and jaxpr-structure equivalence tests above stay in the fast lane.

    The task-batched GEMM lowering must match the native conv through the
    full second-order outer step: bitwise-equal loss/accuracy is too strict
    across lowerings, so metrics compare to float tolerance and the
    meta-gradients to the same tolerances the remat/task-axis equivalence
    tests use (post-Adam weights amplify ~zero-gradient noise)."""
    import jax
    import jax.numpy as jnp
    from howtotrainyourmamlpytorch_tpu.core import maml, msl

    cfg_lax = tiny_cfg.replace(conv_impl="lax")
    cfg_gemm = tiny_cfg.replace(conv_impl="gemm")
    state = maml.init_state(cfg_lax)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg_lax)
    w = jnp.asarray(
        msl.loss_weights_for(
            cfg_lax.number_of_training_steps_per_iter, True, True, 0,
            cfg_lax.multi_step_loss_num_epochs,
        )
    )
    loss_l, g_l = jax.jit(maml.make_grads_fn(cfg_lax, True))(
        state, x_s, y_s, x_t, y_t, w
    )
    loss_g, g_g = jax.jit(maml.make_grads_fn(cfg_gemm, True))(
        state, x_s, y_s, x_t, y_t, w
    )
    assert float(loss_l) == pytest.approx(float(loss_g), rel=1e-5)
    for part in ("net", "lslr"):
        for k in g_l[part]:
            np.testing.assert_allclose(
                np.asarray(g_l[part][k]), np.asarray(g_g[part][k]),
                atol=1e-5, rtol=1e-4, err_msg=f"{part}.{k}",
            )
    # metrics through the full step (inner scan + Adam): train and eval
    step_l = jax.jit(maml.make_train_step(cfg_lax, second_order=True))
    step_g = jax.jit(maml.make_train_step(cfg_gemm, second_order=True))
    s_l, m_l = step_l(state, x_s, y_s, x_t, y_t, w, 0.01)
    s_g, m_g = step_g(state, x_s, y_s, x_t, y_t, w, 0.01)
    assert float(m_l["loss"]) == pytest.approx(float(m_g["loss"]), rel=1e-5)
    assert float(m_l["accuracy"]) == pytest.approx(float(m_g["accuracy"]))
    ev_l = jax.jit(maml.make_eval_step(cfg_lax))
    ev_g = jax.jit(maml.make_eval_step(cfg_gemm))
    em_l, p_l = ev_l(s_l, x_s, y_s, x_t, y_t)
    em_g, p_g = ev_g(s_l, x_s, y_s, x_t, y_t)
    assert float(em_l["loss"]) == pytest.approx(float(em_g["loss"]), rel=1e-5)
    assert float(em_l["accuracy"]) == pytest.approx(float(em_g["accuracy"]))
    np.testing.assert_allclose(
        np.asarray(p_l), np.asarray(p_g), atol=1e-5, rtol=1e-4
    )


def test_resolved_conv_impl_auto():
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig

    cfg = MAMLConfig(dataset_name="omniglot_dataset")
    assert cfg.conv_impl == "auto"
    # tests run on the CPU backend (conftest) -> auto resolves to im2col
    # regardless of the task-axis mode (the gemm pick is accelerator-only)
    assert cfg.resolved_conv_impl == "im2col"
    assert cfg.replace(task_axis_mode="map").resolved_conv_impl == "im2col"
    assert cfg.replace(conv_impl="lax").resolved_conv_impl == "lax"
    assert cfg.replace(conv_impl="gemm").resolved_conv_impl == "gemm"
    with pytest.raises(ValueError, match="conv_impl"):
        MAMLConfig(conv_impl="winograd")


def test_max_pool_impl_flag_equivalence():
    """impl='reduce_window' must produce the same values as the reshape fast
    path (it is what resolved_pool_impl selects on TPU backends)."""
    x = _rand((3, 9, 7, 5), 11)
    np.testing.assert_array_equal(
        np.asarray(F.max_pool2d(x, impl="reshape")),
        np.asarray(F.max_pool2d(x, impl="reduce_window")),
    )


def test_resolved_pool_impl_auto_and_validation():
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig

    cfg = MAMLConfig(dataset_name="omniglot_dataset")
    assert cfg.resolved_pool_impl == "reshape"  # tests run on CPU
    cfg = MAMLConfig(dataset_name="omniglot_dataset", pool_impl="reduce_window")
    assert cfg.resolved_pool_impl == "reduce_window"
    with pytest.raises(ValueError, match="pool_impl"):
        MAMLConfig(dataset_name="omniglot_dataset", pool_impl="bogus")
