"""Profiling/timing hooks and hybrid mesh construction."""

import glob

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.parallel import distributed, mesh as mesh_lib
from howtotrainyourmamlpytorch_tpu.utils.profiling import (
    StepTimer,
    TraceWindow,
    maybe_trace,
)


class _FakeProfiler:
    """Records start/stop calls in place of jax.profiler (monkeypatched)."""

    def __init__(self):
        self.calls = []

    def start_trace(self, trace_dir):
        self.calls.append(("start", trace_dir))

    def stop_trace(self):
        self.calls.append(("stop",))


@pytest.fixture
def fake_profiler(monkeypatch):
    fake = _FakeProfiler()
    monkeypatch.setattr(jax, "profiler", fake)
    return fake


def test_trace_window_default_matches_legacy_behavior(fake_profiler):
    """epoch=-1/start_step=1: trace steps [1, 1+N) of this run — the old
    profile_trace_dir semantics (step 0 is compile)."""
    tw = TraceWindow("/tmp/t", num_steps=2, epoch=-1, start_step=1)
    tw.step(epoch=0, step_in_epoch=0, step_in_run=0)
    assert not tw.active  # step 0 = compile, skipped
    tw.step(epoch=0, step_in_epoch=1, step_in_run=1)
    assert tw.active
    tw.step(epoch=0, step_in_epoch=2, step_in_run=2)
    assert tw.active  # 1 step captured so far
    tw.step(epoch=0, step_in_epoch=3, step_in_run=3)
    assert not tw.active and tw.done
    assert fake_profiler.calls == [("start", "/tmp/t"), ("stop",)]
    # done: further steps never restart
    tw.step(epoch=1, step_in_epoch=0, step_in_run=4)
    assert len(fake_profiler.calls) == 2


def test_trace_window_targets_chosen_epoch_and_step(fake_profiler):
    """profile_epoch/profile_start_step select the window without code
    edits; counters advancing by k (chunked dispatch) still trigger."""
    synced = []
    tw = TraceWindow("/tmp/t", num_steps=4, epoch=2, start_step=2)
    tw.step(epoch=0, step_in_epoch=3, step_in_run=3)  # wrong epoch
    tw.step(epoch=1, step_in_epoch=2, step_in_run=7)  # wrong epoch
    assert not tw.active
    tw.step(epoch=2, step_in_epoch=0, step_in_run=10)  # before start_step
    assert not tw.active
    # chunked dispatch jumps the step counter past start_step: >= triggers
    tw.step(epoch=2, step_in_epoch=3, step_in_run=13)
    assert tw.active
    # leaving the target epoch clips the window even mid-capture
    tw.step(epoch=3, step_in_epoch=0, step_in_run=15,
            sync=lambda: synced.append(True))
    assert not tw.active and tw.done
    assert synced == [True]  # device drained before stop
    assert fake_profiler.calls == [("start", "/tmp/t"), ("stop",)]


def test_trace_window_close_stops_open_window(fake_profiler):
    tw = TraceWindow("/tmp/t", num_steps=100, epoch=-1, start_step=0)
    tw.step(epoch=0, step_in_epoch=0, step_in_run=0)
    assert tw.active
    tw.close()
    assert not tw.active and tw.done
    assert fake_profiler.calls == [("start", "/tmp/t"), ("stop",)]
    tw.close()  # idempotent
    assert len(fake_profiler.calls) == 2


def test_trace_window_disabled_without_dir(fake_profiler):
    tw = TraceWindow("", num_steps=2)
    for i in range(5):
        tw.step(epoch=0, step_in_epoch=i, step_in_run=i)
    tw.close()
    assert fake_profiler.calls == []


def test_trace_window_reports_events(fake_profiler):
    events = []
    tw = TraceWindow(
        "/tmp/t", num_steps=1, epoch=-1, start_step=1,
        on_event=lambda action, **f: events.append((action, f)),
    )
    tw.step(epoch=0, step_in_epoch=1, step_in_run=1)
    tw.step(epoch=0, step_in_epoch=2, step_in_run=2)
    assert [e[0] for e in events] == ["start", "stop"]
    assert events[0][1]["at_step"] == 1


def test_step_timer_stats():
    t = StepTimer()
    assert t.summary() == {}
    for _ in range(4):
        t.tick()
    s = t.summary()
    assert s["train_iters_per_sec"] > 0
    assert s["train_step_time_min_ms"] <= s["train_step_time_ms"]
    assert s["train_step_time_ms"] <= s["train_step_time_max_ms"]
    # percentiles from the duration reservoir, ordered and bounded
    assert (
        s["train_step_time_min_ms"]
        <= s["train_step_time_p50_ms"]
        <= s["train_step_time_p95_ms"]
        <= s["train_step_time_p99_ms"]
        <= s["train_step_time_max_ms"]
    )
    t.reset()
    assert t.summary() == {}


def test_step_timer_reservoir_bounded():
    t = StepTimer()
    t.RESERVOIR = 8
    for _ in range(100):
        t.tick()
    assert len(t._samples) == 8
    assert t.count == 99
    assert "train_step_time_p99_ms" in t.summary()


def test_maybe_trace_disabled_is_noop():
    with maybe_trace(None):
        pass
    with maybe_trace(""):
        pass


def test_maybe_trace_writes_profile(tmp_path):
    import jax.numpy as jnp

    with maybe_trace(str(tmp_path)):
        jax.jit(lambda x: x * 2)(jnp.ones(8)).block_until_ready()
    written = glob.glob(str(tmp_path / "**" / "*"), recursive=True)
    assert written, "profiler produced no files"


def test_hybrid_mesh_single_process():
    m = distributed.hybrid_task_mesh()
    assert m.axis_names == (distributed.DATA_AXIS, mesh_lib.TASK_AXIS)
    assert m.devices.shape == (1, len(jax.devices()))


def test_hybrid_mesh_simulated_hosts():
    # simulate 2 hosts x 4 devices on the 8-device virtual CPU mesh
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    m = distributed.hybrid_task_mesh(processes=2)
    assert m.devices.shape == (2, 4)
    # sharding a global batch over both axes: 8 tasks -> 1 per device
    sharding = distributed.global_batch_sharding(m)
    x = jax.device_put(np.arange(8.0), sharding)
    assert len(x.addressable_shards) == 8
    np.testing.assert_array_equal(np.asarray(x), np.arange(8.0))


def test_initialize_distributed_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert distributed.initialize_distributed() is False
