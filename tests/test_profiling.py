"""Profiling/timing hooks and hybrid mesh construction."""

import glob

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.parallel import distributed, mesh as mesh_lib
from howtotrainyourmamlpytorch_tpu.utils.profiling import (
    StepTimer,
    TraceWindow,
    maybe_trace,
)


class _FakeProfiler:
    """Records start/stop calls in place of jax.profiler (monkeypatched)."""

    def __init__(self):
        self.calls = []

    def start_trace(self, trace_dir):
        self.calls.append(("start", trace_dir))

    def stop_trace(self):
        self.calls.append(("stop",))


@pytest.fixture
def fake_profiler(monkeypatch):
    fake = _FakeProfiler()
    monkeypatch.setattr(jax, "profiler", fake)
    return fake


def test_trace_window_default_matches_legacy_behavior(fake_profiler):
    """epoch=-1/start_step=1: trace steps [1, 1+N) of this run — the old
    profile_trace_dir semantics (step 0 is compile)."""
    tw = TraceWindow("/tmp/t", num_steps=2, epoch=-1, start_step=1)
    tw.step(epoch=0, step_in_epoch=0, step_in_run=0)
    assert not tw.active  # step 0 = compile, skipped
    tw.step(epoch=0, step_in_epoch=1, step_in_run=1)
    assert tw.active
    tw.step(epoch=0, step_in_epoch=2, step_in_run=2)
    assert tw.active  # 1 step captured so far
    tw.step(epoch=0, step_in_epoch=3, step_in_run=3)
    assert not tw.active and tw.done
    assert fake_profiler.calls == [("start", "/tmp/t"), ("stop",)]
    # done: further steps never restart
    tw.step(epoch=1, step_in_epoch=0, step_in_run=4)
    assert len(fake_profiler.calls) == 2


def test_trace_window_targets_chosen_epoch_and_step(fake_profiler):
    """profile_epoch/profile_start_step select the window without code
    edits; counters advancing by k (chunked dispatch) still trigger."""
    synced = []
    tw = TraceWindow("/tmp/t", num_steps=4, epoch=2, start_step=2)
    tw.step(epoch=0, step_in_epoch=3, step_in_run=3)  # wrong epoch
    tw.step(epoch=1, step_in_epoch=2, step_in_run=7)  # wrong epoch
    assert not tw.active
    tw.step(epoch=2, step_in_epoch=0, step_in_run=10)  # before start_step
    assert not tw.active
    # chunked dispatch jumps the step counter past start_step: >= triggers
    tw.step(epoch=2, step_in_epoch=3, step_in_run=13)
    assert tw.active
    # leaving the target epoch clips the window even mid-capture
    tw.step(epoch=3, step_in_epoch=0, step_in_run=15,
            sync=lambda: synced.append(True))
    assert not tw.active and tw.done
    assert synced == [True]  # device drained before stop
    assert fake_profiler.calls == [("start", "/tmp/t"), ("stop",)]


def test_trace_window_close_stops_open_window(fake_profiler):
    tw = TraceWindow("/tmp/t", num_steps=100, epoch=-1, start_step=0)
    tw.step(epoch=0, step_in_epoch=0, step_in_run=0)
    assert tw.active
    tw.close()
    assert not tw.active and tw.done
    assert fake_profiler.calls == [("start", "/tmp/t"), ("stop",)]
    tw.close()  # idempotent
    assert len(fake_profiler.calls) == 2


def test_trace_window_disabled_without_dir(fake_profiler):
    tw = TraceWindow("", num_steps=2)
    for i in range(5):
        tw.step(epoch=0, step_in_epoch=i, step_in_run=i)
    tw.close()
    assert fake_profiler.calls == []


def test_trace_window_reports_events(fake_profiler):
    events = []
    tw = TraceWindow(
        "/tmp/t", num_steps=1, epoch=-1, start_step=1,
        on_event=lambda action, **f: events.append((action, f)),
    )
    tw.step(epoch=0, step_in_epoch=1, step_in_run=1)
    tw.step(epoch=0, step_in_epoch=2, step_in_run=2)
    assert [e[0] for e in events] == ["start", "stop"]
    assert events[0][1]["at_step"] == 1


def test_step_timer_stats():
    t = StepTimer()
    assert t.summary() == {}
    for _ in range(4):
        t.tick()
    s = t.summary()
    assert s["train_iters_per_sec"] > 0
    assert s["train_step_time_min_ms"] <= s["train_step_time_ms"]
    assert s["train_step_time_ms"] <= s["train_step_time_max_ms"]
    # percentiles from the duration reservoir, ordered and bounded
    assert (
        s["train_step_time_min_ms"]
        <= s["train_step_time_p50_ms"]
        <= s["train_step_time_p95_ms"]
        <= s["train_step_time_p99_ms"]
        <= s["train_step_time_max_ms"]
    )
    t.reset()
    assert t.summary() == {}


def test_step_timer_reservoir_bounded():
    t = StepTimer()
    t.RESERVOIR = 8
    for _ in range(100):
        t.tick()
    assert len(t._samples) == 8
    assert t.count == 99
    assert "train_step_time_p99_ms" in t.summary()


def test_maybe_trace_disabled_is_noop():
    with maybe_trace(None):
        pass
    with maybe_trace(""):
        pass


def test_maybe_trace_writes_profile(tmp_path):
    import jax.numpy as jnp

    with maybe_trace(str(tmp_path)):
        jax.jit(lambda x: x * 2)(jnp.ones(8)).block_until_ready()
    written = glob.glob(str(tmp_path / "**" / "*"), recursive=True)
    assert written, "profiler produced no files"


def test_hybrid_mesh_single_process():
    m = distributed.hybrid_task_mesh()
    assert m.axis_names == (distributed.DATA_AXIS, mesh_lib.TASK_AXIS)
    assert m.devices.shape == (1, len(jax.devices()))


def test_hybrid_mesh_simulated_hosts():
    # simulate 2 hosts x 4 devices on the 8-device virtual CPU mesh
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    m = distributed.hybrid_task_mesh(processes=2)
    assert m.devices.shape == (2, 4)
    # sharding a global batch over both axes: 8 tasks -> 1 per device
    sharding = distributed.global_batch_sharding(m)
    x = jax.device_put(np.arange(8.0), sharding)
    assert len(x.addressable_shards) == 8
    np.testing.assert_array_equal(np.asarray(x), np.arange(8.0))


def test_initialize_distributed_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert distributed.initialize_distributed() is False


# -- OnDemandProfiler: runtime-triggered capture ----------------------------


def _ondemand(tmp_path, fake, **kwargs):
    from howtotrainyourmamlpytorch_tpu.utils.profiling import (
        OnDemandProfiler,
    )

    return OnDemandProfiler(
        str(tmp_path / "PROFILE_REQUEST"),
        str(tmp_path / "profile_traces"),
        profiler_module=fake,
        **kwargs,
    )


def test_ondemand_idle_never_touches_profiler(tmp_path):
    fake = _FakeProfiler()
    prof = _ondemand(tmp_path, fake)
    for _ in range(20):
        prof.step()
    prof.close()
    assert fake.calls == [] and not prof.active


def test_ondemand_file_trigger_captures_requested_window(tmp_path):
    """`echo 3 > PROFILE_REQUEST` mid-run: the NEXT 3 dispatches are
    captured, the trigger file is consumed, events carry the trace id."""
    events = []
    fake = _FakeProfiler()
    prof = _ondemand(
        tmp_path, fake,
        on_event=lambda action, **f: events.append((action, f)),
        trace_id="ab12cd34ef567890",
    )
    prof.step()  # idle
    (tmp_path / "PROFILE_REQUEST").write_text("3\n")
    prof.step()  # consumes the trigger, starts the window
    assert prof.active and fake.calls[0][0] == "start"
    assert not (tmp_path / "PROFILE_REQUEST").exists()
    prof.step()  # dispatch 2 of 3
    prof.step()  # dispatch 3 of 3
    assert prof.active
    synced = []
    prof.step(sync=lambda: synced.append(True))  # window over: stop
    assert not prof.active
    assert synced == [True]  # drained before stop
    assert [c[0] for c in fake.calls] == ["start", "stop"]
    assert "ondemand_00" in fake.calls[0][1]
    (start, f0), (stop, f1) = events
    assert start == "start" and f0["steps"] == 3
    assert f0["trace_id"] == "ab12cd34ef567890" and f0["on_demand"] is True
    assert stop == "stop" and f1["trace_dir"] == f0["trace_dir"]


def test_ondemand_empty_trigger_uses_default_and_renumbers(tmp_path):
    fake = _FakeProfiler()
    prof = _ondemand(tmp_path, fake, default_steps=2)
    (tmp_path / "PROFILE_REQUEST").write_text("")
    prof.step()
    prof.step()
    prof.step()  # 2-step window over
    assert not prof.active
    (tmp_path / "PROFILE_REQUEST").write_text("garbled")
    prof.step()  # unreadable count: default window, second capture dir
    assert prof.active
    prof.close()
    dirs = [c[1] for c in fake.calls if c[0] == "start"]
    assert dirs[0].endswith("ondemand_00") and dirs[1].endswith(
        "ondemand_01"
    )


def test_ondemand_programmatic_trigger_and_signal_flag(tmp_path):
    """The SIGUSR2 path sets a flag only; the capture starts at the next
    step() (trigger() is the handler's body)."""
    fake = _FakeProfiler()
    prof = _ondemand(tmp_path, fake, default_steps=1)
    prof.trigger(num_steps=1)
    assert fake.calls == []  # nothing in signal context
    prof.step()
    assert prof.active
    prof.step()
    assert not prof.active
    assert [c[0] for c in fake.calls] == ["start", "stop"]


def test_ondemand_close_stops_open_window(tmp_path):
    fake = _FakeProfiler()
    prof = _ondemand(tmp_path, fake, default_steps=100)
    prof.trigger()
    prof.step()
    assert prof.active
    prof.close()
    assert not prof.active
    assert [c[0] for c in fake.calls] == ["start", "stop"]


def test_ondemand_signal_handler_installs_on_main_thread_only(tmp_path):
    import threading

    fake = _FakeProfiler()
    prof = _ondemand(tmp_path, fake)
    results = []
    t = threading.Thread(
        target=lambda: results.append(prof.install_signal_handler())
    )
    t.start()
    t.join()
    assert results == [False]  # worker thread: refused, nothing changed
