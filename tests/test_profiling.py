"""Profiling/timing hooks and hybrid mesh construction."""

import glob
import os

import jax
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.parallel import distributed, mesh as mesh_lib
from howtotrainyourmamlpytorch_tpu.utils.profiling import StepTimer, maybe_trace


def test_step_timer_stats():
    t = StepTimer()
    assert t.summary() == {}
    for _ in range(4):
        t.tick()
    s = t.summary()
    assert s["train_iters_per_sec"] > 0
    assert s["train_step_time_min_ms"] <= s["train_step_time_ms"]
    assert s["train_step_time_ms"] <= s["train_step_time_max_ms"]
    # percentiles from the duration reservoir, ordered and bounded
    assert (
        s["train_step_time_min_ms"]
        <= s["train_step_time_p50_ms"]
        <= s["train_step_time_p95_ms"]
        <= s["train_step_time_p99_ms"]
        <= s["train_step_time_max_ms"]
    )
    t.reset()
    assert t.summary() == {}


def test_step_timer_reservoir_bounded():
    t = StepTimer()
    t.RESERVOIR = 8
    for _ in range(100):
        t.tick()
    assert len(t._samples) == 8
    assert t.count == 99
    assert "train_step_time_p99_ms" in t.summary()


def test_maybe_trace_disabled_is_noop():
    with maybe_trace(None):
        pass
    with maybe_trace(""):
        pass


def test_maybe_trace_writes_profile(tmp_path):
    import jax.numpy as jnp

    with maybe_trace(str(tmp_path)):
        jax.jit(lambda x: x * 2)(jnp.ones(8)).block_until_ready()
    written = glob.glob(str(tmp_path / "**" / "*"), recursive=True)
    assert written, "profiler produced no files"


def test_hybrid_mesh_single_process():
    m = distributed.hybrid_task_mesh()
    assert m.axis_names == (distributed.DATA_AXIS, mesh_lib.TASK_AXIS)
    assert m.devices.shape == (1, len(jax.devices()))


def test_hybrid_mesh_simulated_hosts():
    # simulate 2 hosts x 4 devices on the 8-device virtual CPU mesh
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    m = distributed.hybrid_task_mesh(processes=2)
    assert m.devices.shape == (2, 4)
    # sharding a global batch over both axes: 8 tasks -> 1 per device
    sharding = distributed.global_batch_sharding(m)
    x = jax.device_put(np.arange(8.0), sharding)
    assert len(x.addressable_shards) == 8
    np.testing.assert_array_equal(np.asarray(x), np.arange(8.0))


def test_initialize_distributed_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert distributed.initialize_distributed() is False
