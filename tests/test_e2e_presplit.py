"""End-to-end integration: the Mini-ImageNet-shaped code path (pre-split
directory layout, RGB /255 + ImageNet-stat normalize, outer-grad clamp) on a
tiny synthetic dataset, driven through ExperimentBuilder exactly like
train_maml_system.py wires it."""

import os

import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.data.loader import MetaLearningDataLoader
from howtotrainyourmamlpytorch_tpu.experiment.builder import ExperimentBuilder
from howtotrainyourmamlpytorch_tpu.experiment.system import MAMLFewShotClassifier


def _write_presplit_rgb(root, n_classes=4, per_class=6, size=10, seed=0):
    rng = np.random.RandomState(seed)
    for set_name in ("train", "val", "test"):
        for ci in range(n_classes):
            d = os.path.join(root, set_name, f"n{ci:04d}")
            os.makedirs(d, exist_ok=True)
            # class-dependent mean so tasks are learnable
            base = rng.randint(0, 200)
            for j in range(per_class):
                arr = np.clip(
                    base + rng.randint(-30, 30, (size, size, 3)), 0, 255
                ).astype(np.uint8)
                Image.fromarray(arr, "RGB").save(os.path.join(d, f"im{j}.png"))


def test_presplit_rgb_end_to_end(tmp_path):
    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root))
    cfg = MAMLConfig(
        experiment_name=str(tmp_path / "exp"),
        dataset_name="mini_imagenet_full_size",
        dataset_path=str(data_root),
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=10, image_width=10, image_channels=3,
        num_classes_per_set=2, num_samples_per_class=1, num_target_samples=1,
        batch_size=2, cnn_num_filters=4, num_stages=2, max_pooling=True,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        use_multi_step_loss_optimization=True, second_order=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        total_epochs=2, total_iter_per_epoch=2, num_evaluation_tasks=4,
        total_epochs_before_pause=100,
        num_dataprovider_workers=2, cache_dir=str(tmp_path / "cache"),
        use_mmap_cache=True, use_remat=False, seed=0,
        steps_per_dispatch=2,  # exercise the chunked-dispatch builder path
        # fused eval: 4 tasks / batch 2 = 2 val batches -> ONE dispatch per
        # validation epoch, and the test ensemble sweeps in fused chunks
        eval_batches_per_dispatch=2,
    )
    assert cfg.clip_grads  # imagenet datasets clamp outer grads to ±10
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    builder = ExperimentBuilder(
        cfg, model, MetaLearningDataLoader,
        experiment_root=str(tmp_path), verbose=False,
    )
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0
    # artifacts: dual checkpoints + CSV/JSON metrics
    saved = os.listdir(builder.saved_models_filepath)
    assert "train_model_latest" in saved and "train_model_1" in saved
    logs = os.listdir(builder.logs_filepath)
    assert "summary_statistics.csv" in logs and "test_summary.csv" in logs

    # resume: a new builder from 'latest' starts at epoch 2, trains to 3
    cfg2 = cfg.replace(total_epochs=3)
    model2 = MAMLFewShotClassifier(cfg2, use_mesh=False)
    builder2 = ExperimentBuilder(
        cfg2, model2, MetaLearningDataLoader,
        experiment_root=str(tmp_path), verbose=False,
    )
    assert builder2.start_epoch == 2
    builder2.run_experiment()
    assert "train_model_3" in os.listdir(builder2.saved_models_filepath)

    # evaluate_on_test_set_only: skips training entirely, goes straight to
    # the checkpoint ensemble (ref experiment_builder.py:304 gate)
    cfg3 = cfg2.replace(evaluate_on_test_set_only=True)
    model3 = MAMLFewShotClassifier(cfg3, use_mesh=False)
    builder3 = ExperimentBuilder(
        cfg3, model3, MetaLearningDataLoader,
        experiment_root=str(tmp_path), verbose=False,
    )
    ckpts_before = set(os.listdir(builder3.saved_models_filepath))
    csv_rows_before = open(
        os.path.join(builder3.logs_filepath, "summary_statistics.csv")
    ).read().count("\n")
    test_only = builder3.run_experiment()
    # no training ran: no new checkpoints, no new epoch rows
    # (current_iter is legitimately rewritten by the ensemble's checkpoint
    # loads — the reference's load_model does the same)
    assert set(os.listdir(builder3.saved_models_filepath)) == ckpts_before
    assert open(
        os.path.join(builder3.logs_filepath, "summary_statistics.csv")
    ).read().count("\n") == csv_rows_before
    assert 0.0 <= test_only["test_accuracy_mean"] <= 1.0


def test_max_models_to_save_prunes_checkpoints(tmp_path):
    """max_models_to_save=K keeps `latest` + the top-K epochs by val
    accuracy, and the final ensemble still finds its checkpoints (the
    reference parses the key but never prunes)."""
    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root))
    cfg = MAMLConfig(
        experiment_name=str(tmp_path / "exp"),
        dataset_name="mini_imagenet_full_size",
        dataset_path=str(data_root),
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=10, image_width=10, image_channels=3,
        num_classes_per_set=2, num_samples_per_class=1, num_target_samples=1,
        batch_size=2, cnn_num_filters=4, num_stages=2, max_pooling=True,
        number_of_training_steps_per_iter=1,
        number_of_evaluation_steps_per_iter=1,
        total_epochs=4, total_iter_per_epoch=2, num_evaluation_tasks=4,
        total_epochs_before_pause=100,
        num_dataprovider_workers=2, cache_dir=str(tmp_path / "cache"),
        use_mmap_cache=True, use_remat=False, seed=0,
        max_models_to_save=2,
    )
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    builder = ExperimentBuilder(
        cfg, model, MetaLearningDataLoader,
        experiment_root=str(tmp_path), verbose=False,
    )
    test_losses = builder.run_experiment()
    assert 0.0 <= test_losses["test_accuracy_mean"] <= 1.0
    saved = set(os.listdir(builder.saved_models_filepath))
    assert "train_model_latest" in saved
    epoch_ckpts = saved - {"train_model_latest"}
    assert len(epoch_ckpts) == 2
    # builder.state was rewritten by the ensemble's checkpoint loads; the
    # CSV holds the full 4-epoch val history
    import csv

    with open(
        os.path.join(builder.logs_filepath, "summary_statistics.csv")
    ) as f:
        rows = list(csv.DictReader(f))
    val = np.asarray([float(r["val_accuracy_mean"]) for r in rows])
    assert len(val) == 4
    expected = {
        f"train_model_{int(i) + 1}"
        for i in np.argsort(val, kind="stable")[::-1][:2]
    }
    assert epoch_ckpts == expected

    # resuming from a pruned epoch raises a clear error naming pruning as
    # the cause, not a raw orbax FileNotFoundError (ADVICE.md r5)
    pruned = {1, 2, 3, 4} - {int(n.rsplit("_", 1)[1]) for n in epoch_ckpts}
    cfg_resume = cfg.replace(continue_from_epoch=str(min(pruned)))
    model_resume = MAMLFewShotClassifier(cfg_resume, use_mesh=False)
    with pytest.raises(FileNotFoundError, match="max_models_to_save"):
        ExperimentBuilder(
            cfg_resume, model_resume, MetaLearningDataLoader,
            experiment_root=str(tmp_path), verbose=False,
        )

    # a stats/checkpoint register mismatch (on-disk epoch checkpoint beyond
    # the recorded val rows, i.e. pre-reorder history) disables pruning
    # instead of ranking — and possibly deleting — off-register checkpoints
    os.makedirs(os.path.join(builder.saved_models_filepath, "train_model_99"))
    before = set(os.listdir(builder.saved_models_filepath))
    builder._prune_saved_models()
    assert set(os.listdir(builder.saved_models_filepath)) == before


@pytest.mark.slow
def test_presplit_uint8_stream_end_to_end(tmp_path):
    """The uint8_stream placement tier end-to-end on the presplit config:
    host ships raw uint8, the jitted step decodes on device. Exercises the
    chunked train dispatch, fused eval, checkpoints, resume-free full run —
    and asserts the metrics equal a host-placement run bit-for-bit (the
    on-device decode LUT is the host decode by construction)."""
    data_root = tmp_path / "mini_imagenet_full_size"
    _write_presplit_rgb(str(data_root))

    def run(placement, name):
        cfg = MAMLConfig(
            experiment_name=str(tmp_path / name),
            dataset_name="mini_imagenet_full_size",
            dataset_path=str(data_root),
            sets_are_pre_split=True,
            indexes_of_folders_indicating_class=[-3, -2],
            image_height=10, image_width=10, image_channels=3,
            num_classes_per_set=2, num_samples_per_class=1,
            num_target_samples=1,
            batch_size=2, cnn_num_filters=4, num_stages=2, max_pooling=True,
            per_step_bn_statistics=True,
            learnable_per_layer_per_step_inner_loop_learning_rate=True,
            use_multi_step_loss_optimization=True, second_order=True,
            number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2,
            total_epochs=2, total_iter_per_epoch=2, num_evaluation_tasks=4,
            total_epochs_before_pause=100,
            num_dataprovider_workers=2,
            cache_dir=str(tmp_path / f"cache_{name}"),
            use_mmap_cache=True, use_remat=False, seed=0,
            steps_per_dispatch=2,
            eval_batches_per_dispatch=2,
            data_placement=placement,
        )
        model = MAMLFewShotClassifier(cfg, use_mesh=False)
        builder = ExperimentBuilder(
            cfg, model, MetaLearningDataLoader,
            experiment_root=str(tmp_path), verbose=False,
        )
        test_losses = builder.run_experiment()
        return builder, test_losses

    builder_u8, test_u8 = run("uint8_stream", "exp_u8")
    assert 0.0 <= test_u8["test_accuracy_mean"] <= 1.0
    saved = os.listdir(builder_u8.saved_models_filepath)
    assert "train_model_latest" in saved and "train_model_1" in saved
    logs = os.listdir(builder_u8.logs_filepath)
    assert "summary_statistics.csv" in logs and "test_summary.csv" in logs

    builder_host, test_host = run("host", "exp_host")
    assert test_u8 == test_host
    import csv

    def rows(builder):
        with open(os.path.join(
            builder.logs_filepath, "summary_statistics.csv"
        )) as f:
            return [
                (r["train_loss_mean"], r["val_accuracy_mean"])
                for r in csv.DictReader(f)
            ]

    assert rows(builder_u8) == rows(builder_host)
