"""Subprocess worker for the elastic kill-and-rejoin chaos harness.

Launched by ``tests/test_elastic_e2e.py`` as a gang of N coordinated CPU
processes (``jax.distributed``, ``--xla_force_host_platform_device_count``
virtual devices each) running the production entry (``cli.main``) over a
shared synthetic dataset — with a per-worker ``fault_spec`` so ONE member
of the gang can be SIGKILLed or SIGTERMed deterministically mid-epoch.
The test then resumes the experiment at a DIFFERENT process count (same
total device count) and asserts bit-identical final params and per-epoch
CSV against an uninterrupted baseline — the multi-host extension of
``tests/_resilience_worker.py``'s single-process proof.

The config recipe is imported from the test module
(``test_elastic_e2e.worker_config_kwargs``) so the worker can never drift
from the runs it is compared against.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--process_id", type=int, required=True)
    ap.add_argument("--num_processes", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--n_local_devices", type=int, required=True)
    ap.add_argument("--data_root", required=True)
    ap.add_argument("--exp_name", required=True)
    ap.add_argument("--cache_dir", required=True)
    ap.add_argument("--total_epochs", type=int, default=3)
    ap.add_argument("--fault_spec", default="")
    args = ap.parse_args()

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={args.n_local_devices} "
        + os.environ.get("MAML_ELASTIC_XLA_EXTRA", "")
    ).strip()
    if args.num_processes > 1:
        # cli.main -> initialize_distributed() reads exactly these env vars
        os.environ["JAX_COORDINATOR_ADDRESS"] = f"localhost:{args.port}"
        os.environ["JAX_NUM_PROCESSES"] = str(args.num_processes)
        os.environ["JAX_PROCESS_ID"] = str(args.process_id)
    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.num_processes > 1:
        # cross-process collectives on the CPU backend need an explicit
        # implementation (the default 'none' client rejects multiprocess
        # computations); gloo-over-TCP ships in jaxlib and rides the same
        # coordination service jax.distributed.initialize sets up
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        # gloo cannot tolerate CONCURRENT collectives on one TCP pair: the
        # one-step-lag pipeline keeps a dispatch in flight while the next
        # is enqueued, and two overlapping all-reduces race the pair's
        # preamble ("op.preamble.length <= op.nbytes" aborts, ~1 in 3
        # runs). Inline dispatch serializes device programs, which is the
        # correct-first choice for a CPU test rig anyway.
        jax.config.update("jax_cpu_enable_async_dispatch", False)

    # the test owns the config recipe — import it so every compared run
    # (baseline, chaos, every resume topology) trains the identical program
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(tests_dir))  # repo root: the package
    sys.path.insert(0, tests_dir)
    from test_elastic_e2e import worker_config_kwargs

    from howtotrainyourmamlpytorch_tpu.cli import main as cli_main

    kwargs = worker_config_kwargs(
        data_root=args.data_root,
        exp_name=args.exp_name,
        cache_dir=args.cache_dir,
        total_epochs=args.total_epochs,
        fault_spec=args.fault_spec,
    )
    argv = []
    for key, value in kwargs.items():
        argv += [f"--{key}", (
            json.dumps(value) if isinstance(value, list)
            else str(value).lower() if isinstance(value, bool)
            else str(value)
        )]
    cli_main(argv)
    print(f"WORKER_DONE process={jax.process_index()}", flush=True)


if __name__ == "__main__":
    sys.exit(main())
