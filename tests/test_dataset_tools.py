"""Dataset bootstrap: archive extraction + file-count validation
(utils/dataset_tools.py, ref dataset_tools.py:4-56)."""

import os
import tarfile

import numpy as np
import pytest
from PIL import Image

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.utils import dataset_tools as dt


def _make_archive(tmp_path, name, n_images):
    src = tmp_path / "build" / name
    for i in range(n_images):
        d = src / f"class{i}"
        d.mkdir(parents=True, exist_ok=True)
        Image.fromarray(
            np.zeros((4, 4), np.uint8)
        ).save(d / "img0.png")
    archive = tmp_path / f"{name}.tar.bz2"
    with tarfile.open(archive, "w:bz2") as tf:
        tf.add(src, arcname=name)
    return archive


def test_extracts_missing_dataset(tmp_path, monkeypatch):
    _make_archive(tmp_path, "my_custom_set", 3)
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    cfg = MAMLConfig(
        dataset_name="my_custom_set", dataset_path="my_custom_set"
    )
    assert cfg.dataset_path == os.path.join(str(tmp_path), "my_custom_set")
    dt.maybe_unzip_dataset(cfg)
    assert os.path.isdir(cfg.dataset_path)
    assert cfg.reset_stored_filepaths  # stale caches must be rebuilt
    assert dt.count_dataset_files(cfg.dataset_path) == 3


def test_missing_archive_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    cfg = MAMLConfig(dataset_name="nope", dataset_path="nope")
    with pytest.raises(FileNotFoundError, match="no archive"):
        dt.maybe_unzip_dataset(cfg)


def test_count_mismatch_reextracts_then_raises(tmp_path, monkeypatch):
    # known dataset name with wrong count -> remove, re-extract, still wrong
    # -> RuntimeError (bounded version of ref's unbounded recursion :49-51)
    _make_archive(tmp_path, "omniglot_dataset", 2)
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    cfg = MAMLConfig(
        dataset_name="omniglot_dataset", dataset_path="omniglot_dataset"
    )
    with pytest.raises(RuntimeError, match="count validation"):
        dt.maybe_unzip_dataset(cfg)


def test_existing_valid_dataset_untouched(tmp_path, monkeypatch):
    monkeypatch.setenv("DATASET_DIR", str(tmp_path))
    d = tmp_path / "userdata" / "c0"
    d.mkdir(parents=True)
    Image.fromarray(np.zeros((4, 4), np.uint8)).save(d / "x.png")
    cfg = MAMLConfig(dataset_name="userdata", dataset_path="userdata")
    dt.maybe_unzip_dataset(cfg)  # unknown dataset: no count contract
    assert not cfg.reset_stored_filepaths


def test_expected_counts():
    assert dt.expected_count("omniglot_dataset") == 1623 * 20
    assert dt.expected_count("mini_imagenet_full_size") == 60000
    assert dt.expected_count("mini_imagenet_pkl") == 3
    assert dt.expected_count("anything_else") is None
