"""telemetry/tracing.py — the causal span layer (schema v10).

The load-bearing contracts:

* **off is free**: a disabled tracer allocates no span objects and emits
  nothing — the off path is one attribute check (the telemetry-off proof
  standard), and tracing never feeds the jitted programs, so jaxprs are
  independent of the knob by construction (pinned below anyway);
* spans carry monotonic perf_counter intervals, nest through the
  thread-local parent stack, can be parented explicitly ACROSS threads
  (``use_parent``), and every emitted record is schema-valid;
* the Chrome/Perfetto exporter produces structurally valid trace-event
  JSON: monotonic ``ts``, complete (``ph='X'``) events, thread-name
  metadata, and parent/child containment for a nested request tree;
* the critical-path summary recovers the serving
  queue/assemble/dispatch/sync decomposition per (program, bucket,
  shots).

Pure host-side tests — no jax except the one jaxpr-identity pin.
"""

import json
import threading
import time

import pytest

from howtotrainyourmamlpytorch_tpu.telemetry import schema as tel
from howtotrainyourmamlpytorch_tpu.telemetry import tracing
from howtotrainyourmamlpytorch_tpu.telemetry.sinks import make_record


def make_tracer():
    records = []
    tracer = tracing.Tracer(
        emit=lambda **f: records.append(make_record("span", **f))
    )
    return tracer, records


# -- the off path ------------------------------------------------------------


def test_disabled_tracer_allocates_and_emits_nothing():
    null = tracing.NULL_TRACER
    assert not null.enabled
    assert null.start_span("x", cat="train") is None
    null.end_span(None)  # the handle it handed out: a no-op
    with null.span("y", cat="train") as sp:
        assert sp is None
    assert null.current() is None
    with null.use_parent(None):
        pass


def test_jitted_programs_independent_of_tracing_level():
    """tracing_level never reaches a program factory: the train step's
    jaxpr is byte-identical with tracing on and off (the
    telemetry_level='off' bit-identity standard)."""
    jax = pytest.importorskip("jax")
    from conftest import make_micro_cfg, make_synthetic_batch

    from howtotrainyourmamlpytorch_tpu.core import maml

    cfg_off = make_micro_cfg()
    cfg_on = make_micro_cfg(
        telemetry_level="scalars", tracing_level="on"
    )
    batch = make_synthetic_batch(cfg_off)
    import numpy as np

    weights = np.ones(
        cfg_off.number_of_training_steps_per_iter, np.float32
    )

    def jaxpr_for(cfg):
        state = maml.init_state(cfg)
        step = maml.make_train_step(cfg, second_order=True)
        return str(jax.make_jaxpr(step)(state, *batch, weights, 1e-3))

    assert jaxpr_for(cfg_off) == jaxpr_for(cfg_on)


# -- span emission -----------------------------------------------------------


def test_spans_emit_schema_valid_records_with_nesting():
    tracer, records = make_tracer()
    with tracer.span("request", cat="serving", request_id="t-1") as root:
        assert tracer.current() is root
        with tracer.span("queue", cat="serving", shots=1):
            time.sleep(0.001)
        with tracer.span("dispatch", cat="serving",
                         program="adapt", bucket=2, shots=1):
            pass
    assert tracer.current() is None
    assert [r["name"] for r in records] == ["queue", "dispatch", "request"]
    for rec in records:
        tel.validate_record(rec)
        assert rec["trace_id"] == tracer.trace_id
        assert rec["dur_ms"] >= 0
    queue, dispatch, request = records
    assert queue["parent_id"] == request["span_id"]
    assert dispatch["parent_id"] == request["span_id"]
    assert "parent_id" not in request
    assert queue["dur_ms"] >= 1.0  # the sleep is inside the interval
    assert queue["attrs"] == {"shots": 1}
    assert dispatch["attrs"] == {"program": "adapt", "bucket": 2,
                                 "shots": 1}


def test_explicit_start_end_and_late_attrs():
    tracer, records = make_tracer()
    sp = tracer.start_span("checkpoint", cat="train", epoch=3)
    assert sp is not None and tracer.current() is None  # explicit form
    tracer.end_span(sp, outcome="saved")
    (rec,) = records
    assert rec["attrs"] == {"epoch": 3, "outcome": "saved"}


def test_use_parent_carries_causality_across_threads():
    """The batcher pattern: a request span opened on the submit thread
    parents dispatch spans emitted by a worker thread."""
    tracer, records = make_tracer()
    root = tracer.start_span("request", cat="serving", request_id="r-9")

    def worker():
        with tracer.use_parent(root):
            with tracer.span("dispatch", cat="serving", program="adapt",
                             bucket=1, shots=1):
                pass

    t = threading.Thread(target=worker, name="test-worker")
    t.start()
    t.join()
    tracer.end_span(root)
    dispatch, request = records
    assert dispatch["parent_id"] == request["span_id"]
    assert dispatch["tid"] == "test-worker"
    assert request["tid"] != "test-worker"


def test_thread_local_stacks_do_not_cross_threads():
    tracer, records = make_tracer()
    seen = []

    def worker():
        seen.append(tracer.current())

    with tracer.span("outer", cat="train"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen == [None]  # the other thread's stack is its own


# -- the Chrome/Perfetto exporter -------------------------------------------


def _request_tree_records():
    """One request tree (queue -> dispatch -> sync under a root) plus an
    unrelated train span, as emitted records."""
    tracer, records = make_tracer()
    with tracer.span("request", cat="serving", request_id="r-1",
                     shots=1) as root:
        with tracer.span("queue", cat="serving", shots=1):
            time.sleep(0.001)
        with tracer.use_parent(root):
            with tracer.span("dispatch", cat="serving", program="adapt",
                             bucket=2, shots=1):
                time.sleep(0.001)
            with tracer.span("sync", cat="serving", program="adapt",
                             bucket=2, shots=1):
                pass
    with tracer.span("train_dispatch", cat="train", iter=0):
        pass
    return records


def test_chrome_trace_structure():
    records = _request_tree_records()
    trace = tracing.to_chrome_trace(records)
    json.dumps(trace)  # loadable
    events = trace["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    metas = [e for e in events if e["ph"] == "M"]
    assert len(xs) == len(records)
    # complete events only (no unmatched B/E), monotonic ts
    assert all(e["ph"] in ("X", "M") for e in events)
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)
    assert all(e["dur"] >= 0 for e in xs)
    # thread-name metadata present for every tid used
    named = {m["args"]["name"] for m in metas}
    assert {e["tid"] for e in xs} == {m["tid"] for m in metas}
    assert named  # at least the main thread
    # parent/child containment: each child's interval sits inside its
    # parent's (the request spans queue -> dispatch -> sync)
    by_id = {e["args"]["span_id"]: e for e in xs}
    children = [e for e in xs if e["args"].get("parent_id")]
    assert children, "no nested spans exported"
    for child in children:
        parent = by_id[child["args"]["parent_id"]]
        assert parent["ts"] <= child["ts"]
        assert (child["ts"] + child["dur"]
                <= parent["ts"] + parent["dur"] + 100)  # 0.1ms rounding
    # the request root has queue, dispatch AND sync as children
    root = next(e for e in xs if e["name"] == "request")
    kid_names = {
        e["name"] for e in xs
        if e["args"].get("parent_id") == root["args"]["span_id"]
    }
    assert {"queue", "dispatch", "sync"} <= kid_names


def test_chrome_trace_skips_malformed_spans_never_raises():
    trace = tracing.to_chrome_trace([
        {"kind": "span", "name": "ok", "start_ms": 1.0, "dur_ms": 2.0},
        {"kind": "span", "name": "no_times"},
        {"kind": "span", "start_ms": 1.0, "dur_ms": 2.0},  # no name
        {"kind": "span", "name": "bad", "start_ms": "x", "dur_ms": 1.0},
    ])
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in xs] == ["ok"]


# -- the critical-path summary ----------------------------------------------


def test_critical_path_summary_decomposition():
    records = _request_tree_records()
    summary = tracing.critical_path_summary(records)
    # flat profile covers every name
    for name in ("request", "queue", "dispatch", "sync",
                 "train_dispatch"):
        assert summary["by_name"][name]["count"] == 1
        assert summary["by_name"][name]["mean_ms"] >= 0
    # the serving decomposition keys by (program, bucket, shots); queue
    # and request (pre-grouping) key by shots only
    sv = summary["serving"]
    assert "adapt/b2/s1" in sv
    row = sv["adapt/b2/s1"]
    assert row["dispatch_count"] == 1 and row["sync_count"] == 1
    assert row["dispatch_ms_mean"] >= 1.0  # the sleep
    assert row["stages_ms"] >= row["dispatch_ms_mean"]
    assert "*/b*/s1" in sv
    assert sv["*/b*/s1"]["queue_count"] == 1
    assert sv["*/b*/s1"]["requests"] == 1
    assert sv["*/b*/s1"]["request_ms_mean"] >= 2.0  # both sleeps


def test_span_records_filter():
    spans = tracing.span_records([
        {"kind": "span", "name": "a"},
        {"kind": "epoch", "epoch": 1},
        {"kind": "span", "name": "b"},
    ])
    assert [s["name"] for s in spans] == ["a", "b"]
