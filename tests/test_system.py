"""System-facade tests: per-iteration host logic of MAMLFewShotClassifier
(few_shot_learning_system.py:296-397 equivalents) — LR schedule, MSL logging,
first->second-order switch, layout conversion."""

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.core import maml, msl
from howtotrainyourmamlpytorch_tpu.experiment.system import (
    MAMLFewShotClassifier,
    _to_nhwc,
)


def _batch(cfg, seed=0):
    """The conftest synthetic batch, reordered to the facade's data-batch
    convention (x_s, x_t, y_s, y_t — reference few_shot_learning_system.py:
    355-358)."""
    from conftest import make_synthetic_batch

    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg, seed=seed)
    return x_s, x_t, y_s, y_t


def test_losses_dict_has_reference_keys(tiny_cfg):
    model = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
    losses = model.run_train_iter(_batch(tiny_cfg), epoch=0)
    assert "loss" in losses and "accuracy" in losses
    assert losses["learning_rate"] == pytest.approx(maml.cosine_lr(tiny_cfg, 0))
    # per-step MSL weights logged each iteration (ref :260-262)
    n_steps = tiny_cfg.number_of_training_steps_per_iter
    expected = msl.per_step_loss_importance(
        n_steps, tiny_cfg.multi_step_loss_num_epochs, 0
    )
    for i in range(n_steps):
        assert losses[f"loss_importance_vector_{i}"] == pytest.approx(
            float(expected[i])
        )


def test_cosine_lr_follows_epoch(tiny_cfg):
    model = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
    batch = _batch(tiny_cfg)
    l0 = model.run_train_iter(batch, epoch=0)
    l3 = model.run_train_iter(batch, epoch=3)
    assert l0["learning_rate"] == pytest.approx(maml.cosine_lr(tiny_cfg, 0))
    assert l3["learning_rate"] == pytest.approx(maml.cosine_lr(tiny_cfg, 3))
    assert l3["learning_rate"] < l0["learning_rate"]


def test_first_to_second_order_switch(tiny_cfg):
    """epoch > first_order_to_second_order_epoch selects the second-order
    compile (ref :304-305)."""
    cfg = tiny_cfg.replace(second_order=True, first_order_to_second_order_epoch=1)
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    batch = _batch(cfg)
    model.run_train_iter(batch, epoch=0)
    assert set(model._train_steps) == {False}
    model.run_train_iter(batch, epoch=1)  # not yet: 1 > 1 is False
    assert set(model._train_steps) == {False}
    model.run_train_iter(batch, epoch=2)
    assert set(model._train_steps) == {False, True}


def test_second_order_false_never_compiles_second_order(tiny_cfg):
    cfg = tiny_cfg.replace(second_order=False, first_order_to_second_order_epoch=-1)
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    model.run_train_iter(_batch(cfg), epoch=5)
    assert set(model._train_steps) == {False}


def test_to_nhwc_accepts_both_layouts(tiny_cfg):
    h, w = 14, 14
    nchw = np.zeros((2, 3, 1, h, w), np.float32)  # (..., c, h, w)
    nhwc = np.zeros((2, 3, h, w, 1), np.float32)
    assert _to_nhwc(nchw).shape == (2, 3, h, w, 1)
    assert _to_nhwc(nhwc).shape == (2, 3, h, w, 1)
    with pytest.raises(ValueError):
        _to_nhwc(np.zeros((2, 3, 5, 7, 9), np.float32))


def test_run_train_iters_matches_sequential(tiny_cfg):
    """K updates in one dispatch (steps_per_dispatch / lax.scan) must match
    K sequential single dispatches: same final params, same per-iteration
    metrics."""
    batches = [_batch(tiny_cfg, seed=s) for s in range(3)]
    m_seq = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
    seq_losses = [m_seq.run_train_iter(b, epoch=0) for b in batches]
    m_chk = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
    chk = m_chk.run_train_iters(batches, epoch=0)
    # device metrics come back (k,)-stacked; schedule entries are scalars
    chk_loss = np.asarray(chk["loss"])
    chk_acc = np.asarray(chk["accuracy"])
    assert chk_loss.shape == (3,) and chk_acc.shape == (3,)
    for i, ls in enumerate(seq_losses):
        np.testing.assert_allclose(
            float(ls["loss"]), float(chk_loss[i]), rtol=1e-5
        )
        np.testing.assert_allclose(
            float(ls["accuracy"]), float(chk_acc[i]), rtol=1e-6
        )
        assert ls["learning_rate"] == chk["learning_rate"]
    for k in m_seq.state.net:
        # ulp-level grad codegen differences between the fused (unrolled
        # scan) program and k separate dispatches are amplified by Adam's
        # sign normalization on parameters whose true gradient is ~0
        # (conv bias feeding batch-norm) into O(lr)-scale absolute drift
        # — the same effect make_grads_fn documents; loss/accuracy above
        # pin the tight equivalence
        np.testing.assert_allclose(
            np.asarray(m_seq.state.net[k]),
            np.asarray(m_chk.state.net[k]),
            atol=2e-3,
            err_msg=k,
        )


def test_run_validation_iters_matches_sequential(tiny_cfg):
    """K eval passes in one dispatch (eval_batches_per_dispatch / lax.scan)
    must match K sequential run_validation_iter dispatches batch-for-batch:
    same per-batch metrics, same ensemble predictions."""
    batches = [_batch(tiny_cfg, seed=s) for s in range(3)]
    model = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
    # eval never mutates state, so one model serves both paths
    seq = [model.run_validation_iter(b, return_preds=True) for b in batches]
    losses, preds = model.run_validation_iters(batches, return_preds=True)
    chk_loss = np.asarray(losses["loss"])
    chk_acc = np.asarray(losses["accuracy"])
    assert chk_loss.shape == (3,) and chk_acc.shape == (3,)
    b = tiny_cfg.batch_size
    n, t = tiny_cfg.num_classes_per_set, tiny_cfg.num_target_samples
    assert preds.shape == (3, b, n * t, n)
    for i, (seq_metrics, seq_preds) in enumerate(seq):
        np.testing.assert_allclose(
            float(seq_metrics["loss"]), float(chk_loss[i]), rtol=1e-5
        )
        np.testing.assert_allclose(
            float(seq_metrics["accuracy"]), float(chk_acc[i]), rtol=1e-6
        )
        np.testing.assert_allclose(seq_preds, preds[i], atol=1e-6)
    # plain validation: no preds materialised
    losses_np, preds_np = model.run_validation_iters(batches)
    assert preds_np is None
    np.testing.assert_allclose(
        np.asarray(losses_np["loss"]), chk_loss, rtol=1e-6
    )
    # k=1 falls back to the sequential path with the same stacked contract
    losses_1, preds_1 = model.run_validation_iters(
        batches[:1], return_preds=True
    )
    np.testing.assert_allclose(
        float(np.asarray(losses_1["loss"][0])), float(chk_loss[0]), rtol=1e-5
    )
    assert preds_1.shape == (1, b, n * t, n)


def test_to_nhwc_explicit_layout_never_guesses():
    # a 3xHxW image whose W == 3: the heuristic alone is ambiguous
    ambiguous = np.zeros((2, 4, 3, 5, 3), np.float32)
    with pytest.raises(ValueError, match="ambiguous"):
        _to_nhwc(ambiguous)
    assert _to_nhwc(ambiguous, layout="nhwc").shape == (2, 4, 3, 5, 3)
    assert _to_nhwc(ambiguous, layout="nchw").shape == (2, 4, 5, 3, 3)
    # the config's im_shape disambiguates in auto mode
    assert _to_nhwc(ambiguous, im_shape=(3, 5, 3)).shape == (2, 4, 3, 5, 3)
    assert _to_nhwc(ambiguous, im_shape=(5, 3, 3)).shape == (2, 4, 5, 3, 3)


def test_input_layout_config_validated(tiny_cfg):
    with pytest.raises(ValueError, match="input_layout"):
        tiny_cfg.replace(input_layout="bogus")
    assert tiny_cfg.replace(input_layout="nchw").input_layout == "nchw"


def test_validation_iter_returns_preds_only_on_request(tiny_cfg):
    model = MAMLFewShotClassifier(tiny_cfg, use_mesh=False)
    losses, preds = model.run_validation_iter(_batch(tiny_cfg))
    assert preds is None and "accuracy" in losses
    losses, preds = model.run_validation_iter(_batch(tiny_cfg), return_preds=True)
    b = tiny_cfg.batch_size
    n, t = tiny_cfg.num_classes_per_set, tiny_cfg.num_target_samples
    assert preds.shape == (b, n * t, n)


def test_mesh_sized_from_loader_task_count(tiny_cfg):
    """Mesh sizing must use the SAME task count the loader stacks
    (num_of_gpus * batch_size * samples_per_iter, data/loader.py): a
    num_of_gpus=2 config on 8 virtual devices gets the full 8-way mesh
    (8 | 2*4*1), and a train iter over the loader-convention batch runs."""
    import dataclasses

    cfg = dataclasses.replace(tiny_cfg, num_of_gpus=2, batch_size=4)
    model = MAMLFewShotClassifier(cfg, use_mesh=True)
    assert model.mesh is not None
    assert model.mesh.devices.size == 8
    # the loader stacks num_of_gpus * batch_size tasks per global batch
    from conftest import make_synthetic_batch

    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg, batch_size=8)
    losses = model.run_train_iter((x_s, x_t, y_s, y_t), epoch=0)
    assert np.isfinite(float(losses["loss"]))


def test_mesh_undersized_without_num_of_gpus_factor(tiny_cfg):
    """Regression guard for the round-3 finding: batch_size=6 alone does not
    divide 8 devices (falls to 6), but with num_of_gpus=4 the loader stacks
    24 tasks and the mesh must be the full 8."""
    import dataclasses

    cfg = dataclasses.replace(tiny_cfg, num_of_gpus=4, batch_size=6)
    model = MAMLFewShotClassifier(cfg, use_mesh=True)
    assert model.mesh is not None and model.mesh.devices.size == 8
