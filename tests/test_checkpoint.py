"""Checkpoint round-trip and resume tests (ref contract:
few_shot_learning_system.py:399-424, experiment_builder.py:190-206)."""

import jax
import jax.numpy as jnp
import numpy as np

from howtotrainyourmamlpytorch_tpu.core import maml
from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt


def _tree_equal(a, b):
    ok = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))), a, b
    )
    return all(jax.tree_util.tree_leaves(ok))


def test_round_trip_exact(tiny_cfg, tmp_path, synthetic_batch):
    cfg = tiny_cfg
    state = maml.init_state(cfg)
    # advance a step so Adam state is nontrivial
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    import howtotrainyourmamlpytorch_tpu.core.msl as msl

    w = jnp.asarray(msl.per_step_loss_importance(2, 3, 0))
    state, _ = jax.jit(maml.make_train_step(cfg, True))(
        state, x_s, y_s, x_t, y_t, w, 0.001
    )
    exp_state = {"best_val_acc": 0.5, "best_val_iter": 7, "current_iter": 12,
                 "per_epoch_statistics": {"val_accuracy_mean": [0.4, 0.5]}}
    ckpt.save_checkpoint(str(tmp_path), "train_model", "latest", state, exp_state)
    assert ckpt.checkpoint_exists(str(tmp_path), "train_model", "latest")

    fresh = maml.init_state(cfg)
    assert not _tree_equal(fresh.net, state.net)
    restored, exp_restored = ckpt.load_checkpoint(
        str(tmp_path), "train_model", "latest", fresh
    )
    assert _tree_equal(restored.net, state.net)
    assert _tree_equal(restored.lslr, state.lslr)
    assert _tree_equal(restored.bn, state.bn)
    assert _tree_equal(restored.opt, state.opt)
    assert exp_restored == exp_state


def test_epoch_and_latest_are_independent(tiny_cfg, tmp_path):
    cfg = tiny_cfg
    s1 = maml.init_state(cfg, seed=1)
    s2 = maml.init_state(cfg, seed=2)
    ckpt.save_checkpoint(str(tmp_path), "train_model", 1, s1, {"current_iter": 1})
    ckpt.save_checkpoint(str(tmp_path), "train_model", "latest", s2, {"current_iter": 2})
    r1, e1 = ckpt.load_checkpoint(str(tmp_path), "train_model", 1, maml.init_state(cfg))
    rl, el = ckpt.load_checkpoint(str(tmp_path), "train_model", "latest", maml.init_state(cfg))
    assert _tree_equal(r1.net, s1.net)
    assert _tree_equal(rl.net, s2.net)
    assert e1["current_iter"] == 1 and el["current_iter"] == 2


def test_overwrite_latest(tiny_cfg, tmp_path):
    cfg = tiny_cfg
    s1 = maml.init_state(cfg, seed=1)
    s2 = maml.init_state(cfg, seed=2)
    ckpt.save_checkpoint(str(tmp_path), "train_model", "latest", s1, {"current_iter": 1})
    ckpt.save_checkpoint(str(tmp_path), "train_model", "latest", s2, {"current_iter": 2})
    r, e = ckpt.load_checkpoint(str(tmp_path), "train_model", "latest", maml.init_state(cfg))
    assert _tree_equal(r.net, s2.net)
    assert e["current_iter"] == 2
