"""Checkpoint round-trip and resume tests (ref contract:
few_shot_learning_system.py:399-424, experiment_builder.py:190-206)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.core import maml
from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt


def _tree_equal(a, b):
    ok = jax.tree_util.tree_map(
        lambda x, y: bool(np.array_equal(np.asarray(x), np.asarray(y))), a, b
    )
    return all(jax.tree_util.tree_leaves(ok))


def test_round_trip_exact(tiny_cfg, tmp_path, synthetic_batch):
    cfg = tiny_cfg
    state = maml.init_state(cfg)
    # advance a step so Adam state is nontrivial
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    import howtotrainyourmamlpytorch_tpu.core.msl as msl

    w = jnp.asarray(msl.per_step_loss_importance(2, 3, 0))
    state, _ = jax.jit(maml.make_train_step(cfg, True))(
        state, x_s, y_s, x_t, y_t, w, 0.001
    )
    exp_state = {"best_val_acc": 0.5, "best_val_iter": 7, "current_iter": 12,
                 "per_epoch_statistics": {"val_accuracy_mean": [0.4, 0.5]}}
    ckpt.save_checkpoint(str(tmp_path), "train_model", "latest", state, exp_state)
    assert ckpt.checkpoint_exists(str(tmp_path), "train_model", "latest")

    fresh = maml.init_state(cfg)
    assert not _tree_equal(fresh.net, state.net)
    restored, exp_restored = ckpt.load_checkpoint(
        str(tmp_path), "train_model", "latest", fresh
    )
    assert _tree_equal(restored.net, state.net)
    assert _tree_equal(restored.lslr, state.lslr)
    assert _tree_equal(restored.bn, state.bn)
    assert _tree_equal(restored.opt, state.opt)
    assert exp_restored == exp_state


def test_epoch_and_latest_are_independent(tiny_cfg, tmp_path):
    cfg = tiny_cfg
    s1 = maml.init_state(cfg, seed=1)
    s2 = maml.init_state(cfg, seed=2)
    ckpt.save_checkpoint(str(tmp_path), "train_model", 1, s1, {"current_iter": 1})
    ckpt.save_checkpoint(str(tmp_path), "train_model", "latest", s2, {"current_iter": 2})
    r1, e1 = ckpt.load_checkpoint(str(tmp_path), "train_model", 1, maml.init_state(cfg))
    rl, el = ckpt.load_checkpoint(str(tmp_path), "train_model", "latest", maml.init_state(cfg))
    assert _tree_equal(r1.net, s1.net)
    assert _tree_equal(rl.net, s2.net)
    assert e1["current_iter"] == 1 and el["current_iter"] == 2


def test_overwrite_latest(tiny_cfg, tmp_path):
    cfg = tiny_cfg
    s1 = maml.init_state(cfg, seed=1)
    s2 = maml.init_state(cfg, seed=2)
    ckpt.save_checkpoint(str(tmp_path), "train_model", "latest", s1, {"current_iter": 1})
    ckpt.save_checkpoint(str(tmp_path), "train_model", "latest", s2, {"current_iter": 2})
    r, e = ckpt.load_checkpoint(str(tmp_path), "train_model", "latest", maml.init_state(cfg))
    assert _tree_equal(r.net, s2.net)
    assert e["current_iter"] == 2


class _CountingCheckpointer:
    """Proxy that counts device->host serializations (``save`` calls)."""

    def __init__(self, inner):
        self.inner = inner
        self.saves = 0

    def save(self, *args, **kwargs):
        self.saves += 1
        return self.inner.save(*args, **kwargs)

    def wait_until_finished(self):
        return self.inner.wait_until_finished()


def test_async_save_dedups_latest_single_serialization(
    tiny_cfg, tmp_path, monkeypatch
):
    """One epoch save with clone_to='latest' must produce BOTH loadable
    checkpoints from exactly ONE pytree serialization, and the experiment
    state (incl. per_epoch_statistics) must round-trip through the async
    path + barrier."""
    cfg = tiny_cfg
    state = maml.init_state(cfg, seed=3)
    exp_state = {
        "best_val_acc": 0.5,
        "current_iter": 8,
        "per_epoch_statistics": {"val_accuracy_mean": [0.25, 0.5]},
    }
    counting = _CountingCheckpointer(ckpt._get_async_checkpointer())
    monkeypatch.setattr(ckpt, "_get_async_checkpointer", lambda: counting)
    ckpt.save_checkpoint_async(
        str(tmp_path), "train_model", 2, state, exp_state, clone_to="latest"
    )
    ckpt.wait_for_pending()
    assert counting.saves == 1
    for idx in (2, "latest"):
        restored, exp_restored = ckpt.load_checkpoint(
            str(tmp_path), "train_model", idx, maml.init_state(cfg)
        )
        assert _tree_equal(restored.net, state.net)
        assert _tree_equal(restored.opt, state.opt)
        assert exp_restored == exp_state


def test_async_save_barriers_are_path_aware(tiny_cfg, tmp_path):
    """checkpoint_exists/remove_checkpoint on the in-flight path must wait
    for the finalize (no resurrection after a prune); a later sync save
    serializes behind the pending async one."""
    cfg = tiny_cfg
    s1 = maml.init_state(cfg, seed=1)
    ckpt.save_checkpoint_async(str(tmp_path), "train_model", 1, s1, {"current_iter": 1})
    # exists() barriers on the touched path: the checkpoint must be visible
    assert ckpt.checkpoint_exists(str(tmp_path), "train_model", 1)
    # prune of the just-saved epoch: barrier first, then rmtree — the
    # background finalize must never resurrect a pruned directory
    ckpt.save_checkpoint_async(str(tmp_path), "train_model", 2, s1, {"current_iter": 2})
    ckpt.remove_checkpoint(str(tmp_path), "train_model", 2)
    ckpt.wait_for_pending()
    assert not ckpt.checkpoint_exists(str(tmp_path), "train_model", 2)
    assert ckpt.checkpoint_exists(str(tmp_path), "train_model", 1)


_KILL_CHILD = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.core import maml
from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

cfg = MAMLConfig(
    image_height=8, image_width=8, image_channels=1,
    num_classes_per_set=2, num_samples_per_class=1, num_target_samples=1,
    batch_size=2, cnn_num_filters=4, num_stages=1,
    number_of_training_steps_per_iter=1,
    number_of_evaluation_steps_per_iter=1, use_remat=False,
)
save_dir = {save_dir!r}
s1 = maml.init_state(cfg, seed=1)
ckpt.save_checkpoint(save_dir, "train_model", "latest", s1, {{"current_iter": 1}})
s2 = maml.init_state(cfg, seed=2)
# async epoch-2 save that would re-clone `latest`; the parent SIGKILLs us
# between save-start and the barrier
ckpt.save_checkpoint_async(
    save_dir, "train_model", 2, s2, {{"current_iter": 2}}, clone_to="latest"
)
print("SAVE_STARTED", flush=True)
time.sleep(120)  # killed here; never reaches wait_for_pending
"""


@pytest.mark.slow
def test_kill_between_async_save_start_and_barrier_keeps_latest_loadable(
    tiny_cfg, tmp_path,
):
    """SIGKILL a process after save_checkpoint_async returns but before the
    barrier: `latest` must still load — either the pre-save state (kill beat
    the background finalize) or the new one (finalize beat the kill), never
    a corrupt directory."""
    import os
    import signal
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    save_dir = str(tmp_path / "ckpts")
    os.makedirs(save_dir)
    code = _KILL_CHILD.format(repo=repo, save_dir=save_dir)
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        assert "SAVE_STARTED" in line, proc.stderr.read()
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    # the child's tiny config, mirrored for restore shapes
    from howtotrainyourmamlpytorch_tpu.config import MAMLConfig

    cfg = MAMLConfig(
        image_height=8, image_width=8, image_channels=1,
        num_classes_per_set=2, num_samples_per_class=1, num_target_samples=1,
        batch_size=2, cnn_num_filters=4, num_stages=1,
        number_of_training_steps_per_iter=1,
        number_of_evaluation_steps_per_iter=1, use_remat=False,
    )
    assert ckpt.checkpoint_exists(save_dir, "train_model", "latest")
    restored, exp = ckpt.load_checkpoint(
        save_dir, "train_model", "latest", maml.init_state(cfg)
    )
    assert exp["current_iter"] in (1, 2)
    expected = maml.init_state(cfg, seed=exp["current_iter"])
    assert _tree_equal(restored.net, expected.net)


def test_async_save_snapshot_immune_to_donation_after_return(
    tiny_cfg, tmp_path,
):
    """What lands on disk is the state AT save time, even though the caller
    donates/mutates the buffers immediately after save_checkpoint_async
    returns. On CPU a jax.Array is a zero-copy view of its buffer, so
    without the eager host copy inside the async path, the donating next
    step would mutate the very memory the background write was reading —
    the silent early-epoch checkpoint corruption the kill/resume
    equivalence suite caught (and the occasional use-after-free segfault)."""
    cfg = tiny_cfg
    state = maml.init_state(cfg, seed=3)
    snapshot = jax.tree_util.tree_map(
        lambda x: np.array(x), state._asdict()
    )
    ckpt.save_checkpoint_async(
        str(tmp_path), "train_model", 1, state, {"current_iter": 1},
        clone_to="latest",
    )
    # donate every buffer of the just-saved state straight back into a
    # mutating jit BEFORE the background write barriers — repeatedly, so
    # the old buffers are both invalidated and rewritten with new values
    mutate = jax.jit(
        lambda t: jax.tree_util.tree_map(lambda a: a * -3.0 + 1.0, t),
        donate_argnums=(0,),
    )
    t = state._asdict()
    for _ in range(4):
        t = mutate(t)
    jax.block_until_ready(t)
    ckpt.wait_for_pending()
    for idx in (1, "latest"):
        restored, exp = ckpt.load_checkpoint(
            str(tmp_path), "train_model", idx, maml.init_state(cfg)
        )
        assert _tree_equal(restored._asdict(), snapshot)
        assert exp["current_iter"] == 1


def test_restored_arrays_own_their_memory(tiny_cfg, tmp_path):
    """Restored leaves must be numpy arrays owning their data — orbax hands
    back views over tensorstore capsules, and feeding those into donating
    train steps tied XLA buffer lifetime to a foreign allocator."""
    cfg = tiny_cfg
    state = maml.init_state(cfg, seed=4)
    ckpt.save_checkpoint(
        str(tmp_path), "train_model", 1, state, {"current_iter": 1}
    )
    restored, _ = ckpt.load_checkpoint(
        str(tmp_path), "train_model", 1, maml.init_state(cfg)
    )
    for leaf in jax.tree_util.tree_leaves(restored._asdict()):
        if isinstance(leaf, np.ndarray):
            assert leaf.flags.owndata, "restored leaf is a borrowed view"
