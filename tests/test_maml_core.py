"""Bi-level optimization core tests: learning works, gradient orders differ,
meta-gradient matches finite differences, partitions honour reference rules."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.core import maml, msl, partition
from howtotrainyourmamlpytorch_tpu.models import vgg


def _weights(cfg, training=True, epoch=0):
    return jnp.asarray(
        msl.loss_weights_for(
            cfg.number_of_training_steps_per_iter,
            cfg.use_multi_step_loss_optimization,
            training,
            epoch,
            cfg.multi_step_loss_num_epochs,
        )
    )


def test_loss_drops_and_accuracy_rises(tiny_cfg, synthetic_batch):
    cfg = tiny_cfg
    state = maml.init_state(cfg)
    step = jax.jit(maml.make_train_step(cfg, second_order=True))
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    w = _weights(cfg)
    lr = maml.cosine_lr(cfg, 0)
    state, m0 = step(state, x_s, y_s, x_t, y_t, w, lr)
    for _ in range(30):
        state, m = step(state, x_s, y_s, x_t, y_t, w, lr)
    assert float(m["loss"]) < float(m0["loss"])
    assert float(m["accuracy"]) > float(m0["accuracy"])
    assert float(m["accuracy"]) > 0.6


def test_bfloat16_compute_learns_and_tracks_f32(tiny_cfg, synthetic_batch):
    """compute_dtype='bfloat16' (the MXU-native precision) must train: loss
    finite and decreasing, params finite, and the first-step loss close to
    f32's (params/grads stay f32 master copies; only activations are bf16)."""
    cfg32 = tiny_cfg
    cfg16 = tiny_cfg.replace(compute_dtype="bfloat16")
    x_s, y_s, x_t, y_t = synthetic_batch(cfg32)
    w = _weights(cfg32)
    state32 = maml.init_state(cfg32)
    state16 = maml.init_state(cfg16)
    step32 = jax.jit(maml.make_train_step(cfg32, second_order=True))
    step16 = jax.jit(maml.make_train_step(cfg16, second_order=True))
    _, m32 = step32(state32, x_s, y_s, x_t, y_t, w, 0.001)
    state16, m16 = step16(state16, x_s, y_s, x_t, y_t, w, 0.001)
    assert abs(float(m32["loss"]) - float(m16["loss"])) < 0.05
    m0 = m16
    for _ in range(30):
        state16, m16 = step16(state16, x_s, y_s, x_t, y_t, w, 0.001)
    assert np.isfinite(float(m16["loss"]))
    assert float(m16["loss"]) < float(m0["loss"])
    for v in state16.net.values():
        assert v.dtype == jnp.float32  # master params stay f32
        assert bool(jnp.all(jnp.isfinite(v)))


def test_second_order_grads_differ_from_first_order(tiny_cfg, synthetic_batch):
    """create_graph=True vs False must change the meta-update
    (few_shot_learning_system.py:138-139)."""
    cfg = tiny_cfg
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    w = _weights(cfg)
    s2, _ = jax.jit(maml.make_train_step(cfg, True))(state, x_s, y_s, x_t, y_t, w, 0.01)
    s1, _ = jax.jit(maml.make_train_step(cfg, False))(state, x_s, y_s, x_t, y_t, w, 0.01)
    diffs = [
        float(jnp.max(jnp.abs(s2.net[k] - s1.net[k]))) for k in s2.net
    ]
    assert max(diffs) > 1e-6  # the orders genuinely differ
    # but both must produce finite updates
    for s in (s1, s2):
        for v in s.net.values():
            assert bool(jnp.all(jnp.isfinite(v)))


def test_meta_gradient_matches_finite_difference(tiny_cfg, synthetic_batch):
    """Second-order meta-gradient vs central finite differences of the full
    bi-level objective — the correctness test the reference never had
    (SURVEY.md §4)."""
    cfg = tiny_cfg.replace(
        num_stages=1, cnn_num_filters=3, batch_size=2,
        number_of_training_steps_per_iter=2, use_remat=False,
    )
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg, batch_size=2)
    w = _weights(cfg)
    learner = maml._task_learner(cfg, cfg.number_of_training_steps_per_iter, True)

    def outer(net):
        losses, _ = jax.vmap(
            lambda a, b, c, d: learner(net, state.lslr, state.bn, a, b, c, d, w)
        )(jnp.asarray(x_s), jnp.asarray(y_s), jnp.asarray(x_t), jnp.asarray(y_t))
        return jnp.mean(losses)

    grads = jax.grad(outer)(state.net)
    key = "linear.bias"
    for idx in range(2):
        eps = 1e-3
        net_p = dict(state.net)
        net_m = dict(state.net)
        net_p[key] = state.net[key].at[idx].add(eps)
        net_m[key] = state.net[key].at[idx].add(-eps)
        fd = (float(outer(net_p)) - float(outer(net_m))) / (2 * eps)
        assert abs(fd - float(grads[key][idx])) < 5e-3, (
            f"{key}[{idx}]: fd={fd} vs ad={float(grads[key][idx])}"
        )


def test_one_hot_weights_equal_final_step_loss(tiny_cfg, synthetic_batch):
    """The unified weighted-sum must reproduce the reference's
    final-step-only branch exactly (few_shot_learning_system.py:239-244)."""
    cfg = tiny_cfg
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    learner = maml._task_learner(cfg, cfg.number_of_training_steps_per_iter, False)

    def task0(weights_vec):
        loss, _ = learner(
            state.net, state.lslr, state.bn,
            jnp.asarray(x_s[0]), jnp.asarray(y_s[0]),
            jnp.asarray(x_t[0]), jnp.asarray(y_t[0]), weights_vec,
        )
        return float(loss)

    n = cfg.number_of_training_steps_per_iter
    onehot = jnp.asarray(msl.final_step_only(n))
    uniform = jnp.ones(n) / n
    assert task0(onehot) != pytest.approx(task0(uniform))
    # one-hot == manually extracting the last per-step loss
    eye = jnp.eye(n)
    per_step = [task0(eye[i]) for i in range(n)]
    assert task0(onehot) == pytest.approx(per_step[-1], rel=1e-6)


def test_inner_partition_excludes_norm_params(tiny_cfg):
    """Norm params stay out of the inner loop unless the enable flag
    (few_shot_learning_system.py:115-119)."""
    cfg = tiny_cfg
    params, _ = vgg.init(cfg, jax.random.PRNGKey(0))
    adapted, frozen = partition.split_inner(cfg, params)
    assert all(".norm." not in k for k in adapted)
    assert all(".norm." in k for k in frozen)
    cfg2 = cfg.replace(enable_inner_loop_optimizable_bn_params=True)
    params2, _ = vgg.init(cfg2, jax.random.PRNGKey(0))
    adapted2, _ = partition.split_inner(cfg2, params2)
    assert any(".norm." in k for k in adapted2)


def test_layer_norm_gamma_frozen(tiny_cfg):
    """LN weight is requires_grad=False in the reference (meta_...py:279)."""
    cfg = tiny_cfg.replace(norm_layer="layer_norm", per_step_bn_statistics=False)
    params, bn = vgg.init(cfg, jax.random.PRNGKey(0))
    assert bn == {}
    assert not partition.is_trainable(cfg, "conv0.norm.gamma")
    assert partition.is_trainable(cfg, "conv0.norm.beta")
    assert not partition.is_inner_adapted(
        cfg.replace(enable_inner_loop_optimizable_bn_params=True),
        "conv0.norm.gamma",
    )


def test_frozen_params_not_updated_by_outer_step(tiny_cfg, synthetic_batch):
    cfg = tiny_cfg.replace(
        learnable_bn_gamma=False, learnable_bn_beta=False,
        learnable_per_layer_per_step_inner_loop_learning_rate=False,
    )
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    new_state, _ = jax.jit(maml.make_train_step(cfg, True))(
        state, x_s, y_s, x_t, y_t, _weights(cfg), 0.01
    )
    for k in state.net:
        if ".norm." in k:
            np.testing.assert_array_equal(state.net[k], new_state.net[k])
    for k in state.lslr:
        np.testing.assert_array_equal(state.lslr[k], new_state.lslr[k])


def test_lslr_learned_when_enabled(tiny_cfg, synthetic_batch):
    cfg = tiny_cfg
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    new_state, _ = jax.jit(maml.make_train_step(cfg, True))(
        state, x_s, y_s, x_t, y_t, _weights(cfg), 0.01
    )
    moved = [
        float(jnp.max(jnp.abs(new_state.lslr[k] - state.lslr[k])))
        for k in state.lslr
    ]
    assert max(moved) > 0.0


def test_cosine_lr_schedule(tiny_cfg):
    """CosineAnnealingLR closed form (few_shot_learning_system.py:70-71)."""
    cfg = tiny_cfg.replace(
        meta_learning_rate=0.001, min_learning_rate=0.0001, total_epochs=10
    )
    assert maml.cosine_lr(cfg, 0) == pytest.approx(0.001)
    assert maml.cosine_lr(cfg, 10) == pytest.approx(0.0001)
    mid = maml.cosine_lr(cfg, 5)
    assert mid == pytest.approx((0.001 + 0.0001) / 2)


def test_eval_step_deterministic_and_shapes(tiny_cfg, synthetic_batch):
    cfg = tiny_cfg
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    ev = jax.jit(maml.make_eval_step(cfg))
    m1, p1 = ev(state, x_s, y_s, x_t, y_t)
    m2, p2 = ev(state, x_s, y_s, x_t, y_t)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    b = cfg.batch_size
    nt = cfg.num_classes_per_set * cfg.num_target_samples
    assert p1.shape == (b, nt, cfg.num_classes_per_set)
    # softmax outputs
    np.testing.assert_allclose(np.asarray(p1).sum(-1), 1.0, rtol=1e-4)


def test_grad_clamp_applied_for_imagenet(tiny_cfg, synthetic_batch):
    """Elementwise ±10 clamp on net grads for imagenet datasets
    (few_shot_learning_system.py:332-335) — verify the step runs with the
    clip branch compiled in and stays finite."""
    cfg = tiny_cfg.replace(dataset_name="mini_imagenet_full_size")
    assert cfg.clip_grads
    state = maml.init_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    new_state, m = jax.jit(maml.make_train_step(cfg, True))(
        state, x_s, y_s, x_t, y_t, _weights(cfg), 0.01
    )
    assert np.isfinite(float(m["loss"]))


@pytest.mark.parametrize("policy", ["full", "save_conv"])
def test_remat_matches_no_remat(tiny_cfg, synthetic_batch, policy):
    """Rematerialisation (under either policy) must not change the
    meta-gradients. Compared at the gradient level: post-Adam weights would
    amplify float-reordering noise on ~zero-gradient params (conv bias under
    BN) into O(lr) differences."""
    cfg_a = tiny_cfg.replace(use_remat=True, remat_policy=policy)
    cfg_b = tiny_cfg.replace(use_remat=False)
    sa = maml.init_state(cfg_a)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg_a)
    loss_a, g_a = jax.jit(maml.make_grads_fn(cfg_a, True))(
        sa, x_s, y_s, x_t, y_t, _weights(cfg_a)
    )
    sb = maml.init_state(cfg_b)
    loss_b, g_b = jax.jit(maml.make_grads_fn(cfg_b, True))(
        sb, x_s, y_s, x_t, y_t, _weights(cfg_b)
    )
    assert float(loss_a) == pytest.approx(float(loss_b), rel=1e-5)
    for part in ("net", "lslr"):
        for k in g_a[part]:
            np.testing.assert_allclose(
                np.asarray(g_a[part][k]), np.asarray(g_b[part][k]),
                atol=1e-5, rtol=1e-4, err_msg=f"{part}.{k}",
            )


def test_task_axis_map_matches_vmap(tiny_cfg, synthetic_batch):
    """task_axis_mode='map' (sequential lax.map over tasks — the CPU-host
    fast path; XLA:CPU's grouped-conv lowering of vmapped per-task weights
    runs far below peak) must produce the same meta-gradients as 'vmap'."""
    cfg_v = tiny_cfg.replace(task_axis_mode="vmap")
    cfg_m = tiny_cfg.replace(task_axis_mode="map")
    state = maml.init_state(cfg_v)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg_v)
    loss_v, g_v = jax.jit(maml.make_grads_fn(cfg_v, True))(
        state, x_s, y_s, x_t, y_t, _weights(cfg_v)
    )
    loss_m, g_m = jax.jit(maml.make_grads_fn(cfg_m, True))(
        state, x_s, y_s, x_t, y_t, _weights(cfg_m)
    )
    assert float(loss_v) == pytest.approx(float(loss_m), rel=1e-6)
    for part in ("net", "lslr"):
        for k in g_v[part]:
            np.testing.assert_allclose(
                np.asarray(g_v[part][k]), np.asarray(g_m[part][k]),
                atol=1e-5, rtol=1e-4, err_msg=f"{part}.{k}",
            )
    # eval path too: identical metrics and stacked predictions
    ev_v = jax.jit(maml.make_eval_step(cfg_v))
    ev_m = jax.jit(maml.make_eval_step(cfg_m))
    m_v, p_v = ev_v(state, x_s, y_s, x_t, y_t)
    m_m, p_m = ev_m(state, x_s, y_s, x_t, y_t)
    assert float(m_v["accuracy"]) == pytest.approx(float(m_m["accuracy"]))
    np.testing.assert_allclose(np.asarray(p_v), np.asarray(p_m), atol=1e-5)
