"""Whole-state buffer donation (``maml.TRAIN_DONATE``) safety.

The train-step executables donate the MetaState (argnum 0) so params + LSLR
+ BN + Adam moments alias in place instead of double-buffering in device
memory every dispatch. These tests pin the contract: donated buffers are
actually released, the executable really aliases them (memory_analysis),
repeated dispatch through the system facade keeps working after donation,
and eval — which must NOT donate (it returns no replacement state) — leaves
the state untouched and reusable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.core import maml, msl
from howtotrainyourmamlpytorch_tpu.experiment.system import MAMLFewShotClassifier


def _weights(cfg):
    return jnp.asarray(
        msl.loss_weights_for(
            cfg.number_of_training_steps_per_iter,
            cfg.use_multi_step_loss_optimization,
            True,
            0,
            cfg.multi_step_loss_num_epochs,
        )
    )


def _device_state(cfg):
    """An init state with every leaf explicitly placed as a device array
    (init_state already returns device arrays; device_put normalizes)."""
    return jax.tree_util.tree_map(jax.device_put, maml.init_state(cfg))


def test_donated_state_buffers_are_freed(tiny_cfg, synthetic_batch):
    """After a donating dispatch the old state's buffers are deleted (the
    aliasing consumed them) and reusing the donated state errors instead of
    silently reading freed memory."""
    cfg = tiny_cfg
    state = _device_state(cfg)
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    w = _weights(cfg)
    step = jax.jit(
        maml.make_train_step(cfg, second_order=True),
        donate_argnums=maml.TRAIN_DONATE,
    )
    old_net_leaf = state.net["conv0.conv.weight"]
    new_state, metrics = step(state, x_s, y_s, x_t, y_t, w, 0.01)
    jax.block_until_ready(new_state.net)
    assert old_net_leaf.is_deleted()
    # every donated leaf, not just one
    deleted = [
        leaf.is_deleted()
        for leaf in jax.tree_util.tree_leaves(state)
        if isinstance(leaf, jax.Array)
    ]
    assert deleted and all(deleted)
    with pytest.raises((RuntimeError, ValueError)):
        _ = step(state, x_s, y_s, x_t, y_t, w, 0.01)
    # the returned state is live and dispatches again (second dispatch
    # after donation works)
    new2, m2 = step(new_state, x_s, y_s, x_t, y_t, w, 0.01)
    assert np.isfinite(float(m2["loss"]))


def test_donation_does_not_change_numbers(tiny_cfg, synthetic_batch):
    """Aliasing is a memory optimization only: a donating step and a
    non-donating step produce bit-identical metrics and parameters."""
    cfg = tiny_cfg
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    w = _weights(cfg)
    plain = jax.jit(maml.make_train_step(cfg, second_order=True))
    donating = jax.jit(
        maml.make_train_step(cfg, second_order=True),
        donate_argnums=maml.TRAIN_DONATE,
    )
    s_plain, m_plain = plain(
        _device_state(cfg), x_s, y_s, x_t, y_t, w, 0.01
    )
    s_don, m_don = donating(
        _device_state(cfg), x_s, y_s, x_t, y_t, w, 0.01
    )
    assert float(m_plain["loss"]) == float(m_don["loss"])
    for k in s_plain.net:
        np.testing.assert_array_equal(
            np.asarray(s_plain.net[k]), np.asarray(s_don.net[k]), err_msg=k
        )


def test_all_four_train_jits_honor_donation_contract(audit_reports, micro_cfg):
    """The alias-bytes >= state-bytes assertion, generalized into the
    ProgramAuditor's ``donation`` contract and checked on ALL FOUR
    train-step jits (plain / multi / indexed / multi-indexed) instead of
    one — the signal bench.py's ``donation`` field watches for regressions
    (alias size collapsing => double-buffered state). The session-scoped
    ``audit_reports`` fixture compiled the family once."""
    from howtotrainyourmamlpytorch_tpu.analysis import auditor as audit_lib

    state_bytes = audit_lib.tree_byte_size(
        audit_lib._state_avals(micro_cfg)
    )
    assert state_bytes > 0
    train_reports = [
        r for r in audit_reports
        if r.program.startswith(audit_lib.TRAIN_STEP_PROGRAMS)
    ]
    assert len(train_reports) == 4
    for r in train_reports:
        donation_violations = [
            v for v in r.violations if v.contract == "donation"
        ]
        assert donation_violations == [], r.program
        assert r.donation is not None, r.program
        assert r.donation["donate_argnums"] == list(maml.TRAIN_DONATE)
        assert r.donation["alias_size_bytes"] >= state_bytes, r.program


def test_eval_programs_do_not_donate(audit_reports):
    """Eval deliberately donates nothing (no replacement state, batches
    unaliasable — see the contract note in core/maml.py): the audited
    eval/expander programs carry no donation spec. The SERVING step is
    the exception that proves the rule: it passes the state THROUGH as
    an output precisely so it CAN donate (maml.SERVE_DONATE) — checked
    separately below."""
    for r in audit_reports:
        if not r.program.startswith(
            ("train_step", "train_multi_step", "serve_step",
             "predict_step")
        ):
            assert r.donation is None, r.program


def test_serve_step_donates_passthrough_state(audit_reports, micro_cfg):
    """The serving program's donation contract: the passthrough state is
    donated and the executable aliases it whole — the servable snapshot
    stays single-buffered in HBM across request dispatches exactly like
    the train state (serving/engine.py re-binds per dispatch)."""
    from howtotrainyourmamlpytorch_tpu.analysis import auditor as audit_lib

    state_bytes = audit_lib.tree_byte_size(
        audit_lib._state_avals(micro_cfg)
    )
    serve = [r for r in audit_reports if r.program.startswith("serve_step")]
    assert len(serve) == 2  # the f32 and uint8 ingest variants
    for r in serve:
        assert [v for v in r.violations if v.contract == "donation"] == []
        assert r.donation is not None, r.program
        assert r.donation["donate_argnums"] == list(maml.SERVE_DONATE)
        assert r.donation["alias_size_bytes"] >= state_bytes, r.program
    # the cache-hit predict program carries the same passthrough-state
    # donation contract (maml.PREDICT_DONATE)
    predict = [
        r for r in audit_reports if r.program.startswith("predict_step")
    ]
    assert len(predict) == 1
    r = predict[0]
    assert [v for v in r.violations if v.contract == "donation"] == []
    assert r.donation["donate_argnums"] == list(maml.PREDICT_DONATE)
    assert r.donation["alias_size_bytes"] >= state_bytes


def test_system_repeated_dispatches_and_eval(tiny_cfg):
    """The facade re-binds self.state every dispatch, so donation is
    invisible to callers: repeated train iters, an eval in between (eval
    does not donate — the same state object keeps being dispatched), and
    a further train iter all keep working."""
    from conftest import make_synthetic_batch

    cfg = tiny_cfg
    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg)
    batch = (x_s, x_t, y_s, y_t)  # facade convention
    l0 = model.run_train_iter(batch, epoch=0)
    state_after_first = model.state
    l1 = model.run_train_iter(batch, epoch=0)
    # the pre-dispatch state was donated and re-bound
    assert model.state is not state_after_first
    # eval does NOT donate: the state survives and trains again afterwards
    ev_metrics, _ = model.run_validation_iter(batch)
    leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(model.state)
        if isinstance(leaf, jax.Array)
    ]
    jax.block_until_ready(leaves)
    assert not any(leaf.is_deleted() for leaf in leaves)
    l2 = model.run_train_iter(batch, epoch=0)
    for losses in (l0, l1, l2):
        assert np.isfinite(float(np.asarray(losses["loss"])))
    assert np.isfinite(float(np.asarray(ev_metrics["loss"])))


def test_donation_bounds_live_state_copies(tiny_cfg, synthetic_batch):
    """Steady-state dispatching must not accumulate live state copies:
    after k donating dispatches exactly one state's worth of net-param
    arrays is live (the k non-donated metric scalars are negligible)."""
    cfg = tiny_cfg
    x_s, y_s, x_t, y_t = synthetic_batch(cfg)
    w = _weights(cfg)
    step = jax.jit(
        maml.make_train_step(cfg, second_order=True),
        donate_argnums=maml.TRAIN_DONATE,
    )
    state = _device_state(cfg)
    shape = state.net["conv0.conv.weight"].shape

    def live_weight_arrays():
        return sum(
            1
            for a in jax.live_arrays()
            if isinstance(a, jax.Array) and a.shape == shape
            and not a.is_deleted()
        )

    state, metrics = step(state, x_s, y_s, x_t, y_t, w, 0.01)
    jax.block_until_ready(state.net)
    baseline = live_weight_arrays()
    for _ in range(3):
        state, metrics = step(state, x_s, y_s, x_t, y_t, w, 0.01)
    jax.block_until_ready(state.net)
    assert live_weight_arrays() <= baseline
